"""Structural validator for Terraform-JSON module trees and root documents.

The reference never shipped a validator — its modules were parsed by the
``terraform`` binary on every user run (shell/run_terraform.go:95-104:
``init`` + ``apply`` IS the product), so a block-shape typo surfaced on the
first user's machine. This framework authors its HCL tree in Terraform JSON
syntax precisely so it can be machine-checked *without* the binary:

* root-block grammar per file (``resource``/``data``/``variable``/``output``
  shapes, required attributes for the resource types the tree uses);
* per-resource-type attribute schemas (KNOWN_ATTRS): an unknown attribute
  name (``subnet_idd = ...``) or a typo'd/misshapen structural nested
  block (NESTED_BLOCK_ATTRS, e.g. ``ip_configuration``) is flagged —
  free-form maps (tags/triggers/labels/metadata) are exempt;
* every ``${var.x}`` resolves to a declared variable, ``${local.x}`` to a
  ``locals`` entry, resource/data references to declared blocks;
* ``depends_on`` entries resolve;
* function-call names are real Terraform builtins (catches ``templtefile``);
* ``${path.module}/...`` file references exist on disk;
* ``templatefile(...)`` calls pass every variable the template consumes;
* root documents: module sources resolve, required variables are present,
  unknown variables are flagged, and every ``${module.k.out}`` names a
  declared module and one of its registered OUTPUTS (the deferred-resolution
  contract of create/cluster.go:297-300).

Used three ways: the test suite validates all shipped modules; the
``TerraformExecutor`` preflights every document before shelling out (so a
bad doc fails in-process with a real message instead of mid-apply); and the
CLI exposes ``validate`` for operators editing documents by hand.

WHAT THIS CANNOT CATCH (vs real ``terraform validate``, which loads the
live provider schemas): attribute VALUE types (``size = "big"``), deeper
provider constraints (conflicting/exactly-one-of argument groups, enum
values), provider-version-dependent schema drift, and expression TYPE
errors inside interpolations. The authoritative cross-check is
``tests/test_terraform_modules.py::test_terraform_binary_validate`` (runs
wherever the binary exists; loud SKIP otherwise) — see
``terraform/modules/README.md`` and ``docs/ci-evidence/README.md``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# Expression scanning

_BUILTIN_HEADS = {"var", "local", "module", "data", "path", "each", "count",
                  "self", "terraform"}

_PATH_ATTRS = {"module", "root", "cwd"}

# Terraform language builtins (the subset is generous; unknown names are the
# signal we want — a typo'd call fails `terraform init` on a user machine).
KNOWN_FUNCTIONS = {
    "abs", "alltrue", "anytrue", "base64decode", "base64encode", "basename",
    "can", "ceil", "chomp", "cidrhost", "cidrnetmask", "cidrsubnet",
    "coalesce", "coalescelist", "compact", "concat", "contains", "dirname",
    "distinct", "element", "endswith", "file", "filebase64", "fileexists",
    "flatten", "floor", "format", "formatlist", "indent", "index", "join",
    "jsondecode", "jsonencode", "keys", "length", "list", "log", "lookup",
    "lower", "map", "max", "md5", "merge", "min", "one", "pathexpand",
    "pow", "range", "regex", "regexall", "replace", "reverse", "sensitive",
    "setproduct", "setunion", "sha1", "sha256", "signum", "slice", "sort",
    "split", "startswith", "strcontains", "substr", "sum", "templatefile",
    "timestamp", "title", "tobool", "tolist", "tomap", "tonumber", "toset",
    "tostring", "trim", "trimprefix", "trimspace", "trimsuffix", "try",
    "upper", "urlencode", "uuid", "values", "yamldecode", "yamlencode",
    "zipmap",
}

# Provider local-name for each resource/data type prefix used in the tree.
_PROVIDER_OF_PREFIX = {
    "aws": "aws", "google": "google", "azurerm": "azurerm",
    "vsphere": "vsphere", "null": "null", "local": "local",
    "external": "external", "triton": "triton", "random": "random",
    "tls": "tls",
}

# Required top-level attributes per resource type (conservative: only
# attributes that `terraform validate` itself would reject as missing).
REQUIRED_ATTRS: Dict[str, Tuple[str, ...]] = {
    "aws_vpc": ("cidr_block",),
    "aws_subnet": ("vpc_id", "cidr_block"),
    "aws_internet_gateway": ("vpc_id",),
    "aws_route_table": ("vpc_id",),
    "aws_route": ("route_table_id",),
    "aws_route_table_association": ("subnet_id", "route_table_id"),
    "aws_security_group_rule": ("type", "from_port", "to_port", "protocol",
                                "security_group_id"),
    "aws_key_pair": ("public_key",),
    "aws_instance": ("ami", "instance_type"),
    "aws_ebs_volume": ("availability_zone", "size"),
    "aws_volume_attachment": ("device_name", "volume_id", "instance_id"),
    "google_compute_network": ("name",),
    "google_compute_firewall": ("name", "network"),
    "google_compute_instance": ("name", "machine_type", "zone", "boot_disk",
                                "network_interface"),
    "google_compute_disk": ("name", "zone"),
    "google_compute_attached_disk": ("disk", "instance"),
    "google_container_cluster": ("name", "location"),
    "google_container_node_pool": ("cluster",),
    "azurerm_resource_group": ("name", "location"),
    "azurerm_virtual_network": ("name", "location", "resource_group_name",
                                "address_space"),
    "azurerm_subnet": ("name", "resource_group_name", "virtual_network_name",
                       "address_prefixes"),
    "azurerm_network_security_group": ("name", "location",
                                       "resource_group_name"),
    "azurerm_network_security_rule": ("name", "priority", "direction",
                                      "access", "protocol",
                                      "resource_group_name",
                                      "network_security_group_name"),
    "azurerm_subnet_network_security_group_association": (
        "subnet_id", "network_security_group_id"),
    "azurerm_public_ip": ("name", "location", "resource_group_name",
                          "allocation_method"),
    "azurerm_network_interface": ("name", "location", "resource_group_name",
                                  "ip_configuration"),
    "azurerm_linux_virtual_machine": ("name", "location",
                                      "resource_group_name", "size",
                                      "admin_username",
                                      "network_interface_ids", "os_disk"),
    "azurerm_managed_disk": ("name", "location", "resource_group_name",
                             "storage_account_type", "create_option"),
    "azurerm_virtual_machine_data_disk_attachment": (
        "managed_disk_id", "virtual_machine_id", "lun", "caching"),
    "azurerm_kubernetes_cluster": ("name", "location", "resource_group_name",
                                   "dns_prefix"),
    "vsphere_virtual_machine": ("name", "resource_pool_id",),
    "local_sensitive_file": ("filename",),
    "null_resource": (),
    "triton_machine": ("package", "image"),
    "kubernetes_deployment": ("metadata", "spec"),
}

# Known top-level attributes per resource type (used attributes across the
# tree + the common optional arguments of each provider schema). An attr
# not listed here and not a meta-argument is flagged — the drift class
# `terraform validate` catches via provider schemas (`subnet_idd = ...`).
# Free-form map attributes (tags, triggers, labels, metadata) are listed
# but their KEYS are never checked; structural nested blocks get their own
# schemas in NESTED_BLOCK_ATTRS below.
KNOWN_ATTRS: Dict[str, Set[str]] = {
    "aws_vpc": {"cidr_block", "enable_dns_hostnames", "enable_dns_support",
                "instance_tenancy", "tags"},
    "aws_subnet": {"vpc_id", "cidr_block", "availability_zone",
                   "map_public_ip_on_launch", "tags"},
    "aws_internet_gateway": {"vpc_id", "tags"},
    "aws_route_table": {"vpc_id", "route", "tags"},
    "aws_route": {"route_table_id", "destination_cidr_block", "gateway_id",
                  "nat_gateway_id", "instance_id"},
    "aws_route_table_association": {"subnet_id", "route_table_id"},
    "aws_security_group": {"name", "name_prefix", "description", "vpc_id",
                           "ingress", "egress", "tags"},
    "aws_security_group_rule": {"type", "from_port", "to_port", "protocol",
                                "security_group_id", "cidr_blocks",
                                "ipv6_cidr_blocks", "self", "description",
                                "source_security_group_id"},
    "aws_key_pair": {"key_name", "key_name_prefix", "public_key", "tags"},
    "aws_instance": {"ami", "instance_type", "key_name", "subnet_id",
                     "vpc_security_group_ids", "user_data",
                     "availability_zone", "iam_instance_profile",
                     "associate_public_ip_address", "root_block_device",
                     "ebs_block_device", "source_dest_check", "tags"},
    "aws_ebs_volume": {"availability_zone", "size", "type", "iops",
                       "throughput", "encrypted", "tags"},
    "aws_volume_attachment": {"device_name", "volume_id", "instance_id",
                              "force_detach", "skip_destroy"},
    "google_compute_network": {"name", "auto_create_subnetworks",
                               "description", "routing_mode", "mtu",
                               "project"},
    "google_compute_firewall": {"name", "network", "allow", "deny",
                                "source_ranges", "source_tags",
                                "target_tags", "direction", "priority",
                                "description", "project"},
    "google_compute_instance": {"name", "machine_type", "zone", "boot_disk",
                                "network_interface", "tags", "labels",
                                "metadata", "metadata_startup_script",
                                "scheduling", "service_account",
                                "allow_stopping_for_update",
                                "can_ip_forward", "project",
                                "deletion_protection"},
    "google_compute_disk": {"name", "zone", "size", "type", "image",
                            "labels", "project"},
    "google_compute_attached_disk": {"disk", "instance", "device_name",
                                     "mode", "zone", "project"},
    "google_container_cluster": {"name", "location", "network", "subnetwork",
                                 "initial_node_count",
                                 "remove_default_node_pool",
                                 "min_master_version", "node_version",
                                 "node_config", "node_locations",
                                 "release_channel", "deletion_protection",
                                 "networking_mode", "ip_allocation_policy",
                                 "project", "resource_labels"},
    "google_container_node_pool": {"cluster", "name", "location",
                                   "node_count", "node_config",
                                   "node_locations", "autoscaling",
                                   "management", "placement_policy",
                                   "initial_node_count", "max_pods_per_node",
                                   "version", "project"},
    "azurerm_resource_group": {"name", "location", "tags"},
    "azurerm_virtual_network": {"name", "location", "resource_group_name",
                                "address_space", "dns_servers", "tags"},
    "azurerm_subnet": {"name", "resource_group_name",
                       "virtual_network_name", "address_prefixes",
                       "service_endpoints"},
    "azurerm_network_security_group": {"name", "location",
                                       "resource_group_name",
                                       "security_rule", "tags"},
    "azurerm_network_security_rule": {"name", "priority", "direction",
                                      "access", "protocol",
                                      "source_port_range",
                                      "destination_port_range",
                                      "source_address_prefix",
                                      "destination_address_prefix",
                                      "resource_group_name",
                                      "network_security_group_name",
                                      "description"},
    "azurerm_subnet_network_security_group_association": {
        "subnet_id", "network_security_group_id"},
    "azurerm_public_ip": {"name", "location", "resource_group_name",
                          "allocation_method", "sku", "domain_name_label",
                          "tags"},
    "azurerm_network_interface": {"name", "location", "resource_group_name",
                                  "ip_configuration", "dns_servers",
                                  "tags"},
    "azurerm_linux_virtual_machine": {"name", "location",
                                      "resource_group_name", "size",
                                      "admin_username", "admin_password",
                                      "network_interface_ids", "os_disk",
                                      "admin_ssh_key",
                                      "source_image_reference",
                                      "source_image_id", "custom_data",
                                      "availability_set_id", "zone",
                                      "disable_password_authentication",
                                      "tags"},
    "azurerm_managed_disk": {"name", "location", "resource_group_name",
                             "storage_account_type", "create_option",
                             "disk_size_gb", "zone", "tags"},
    "azurerm_virtual_machine_data_disk_attachment": {
        "managed_disk_id", "virtual_machine_id", "lun", "caching"},
    "azurerm_kubernetes_cluster": {"name", "location",
                                   "resource_group_name", "dns_prefix",
                                   "kubernetes_version",
                                   "default_node_pool", "identity",
                                   "linux_profile", "network_profile",
                                   "tags"},
    "vsphere_virtual_machine": {"name", "resource_pool_id", "datastore_id",
                                "num_cpus", "memory", "guest_id", "clone",
                                "disk", "network_interface", "folder",
                                "annotation"},
    "local_sensitive_file": {"filename", "content", "content_base64",
                             "file_permission", "directory_permission",
                             "source"},
    "null_resource": set(),
    "triton_machine": {"package", "image", "name", "networks", "affinity",
                       "cns", "user_script", "user_data", "firewall_enabled",
                       "tags", "metadata"},
    "kubernetes_deployment": {"metadata", "spec", "wait_for_rollout"},
}

# Schemas for STRUCTURAL nested blocks (key typos and misshapen bodies are
# what `terraform validate` rejects). Free-form maps (tags, triggers,
# labels, metadata, node_config.labels) are deliberately absent.
NESTED_BLOCK_ATTRS: Dict[Tuple[str, str], Set[str]] = {
    ("aws_instance", "root_block_device"): {
        "volume_size", "volume_type", "iops", "encrypted",
        "delete_on_termination"},
    ("aws_security_group", "ingress"): {
        "from_port", "to_port", "protocol", "cidr_blocks",
        "ipv6_cidr_blocks", "security_groups", "prefix_list_ids", "self",
        "description"},
    ("aws_security_group", "egress"): {
        "from_port", "to_port", "protocol", "cidr_blocks",
        "ipv6_cidr_blocks", "security_groups", "prefix_list_ids", "self",
        "description"},
    ("google_compute_firewall", "allow"): {"protocol", "ports"},
    ("google_compute_instance", "boot_disk"): {
        "initialize_params", "source", "auto_delete", "device_name"},
    ("google_compute_instance", "network_interface"): {
        "network", "subnetwork", "access_config", "network_ip"},
    ("google_container_cluster", "release_channel"): {"channel"},
    ("google_container_node_pool", "management"): {
        "auto_repair", "auto_upgrade"},
    ("google_container_node_pool", "placement_policy"): {
        "type", "tpu_topology", "policy_name"},
    ("azurerm_network_interface", "ip_configuration"): {
        "name", "subnet_id", "private_ip_address_allocation",
        "private_ip_address", "public_ip_address_id", "primary"},
    ("azurerm_linux_virtual_machine", "os_disk"): {
        "caching", "storage_account_type", "disk_size_gb", "name"},
    ("azurerm_linux_virtual_machine", "admin_ssh_key"): {
        "username", "public_key"},
    ("azurerm_linux_virtual_machine", "source_image_reference"): {
        "publisher", "offer", "sku", "version"},
    ("azurerm_kubernetes_cluster", "default_node_pool"): {
        "name", "node_count", "vm_size", "vnet_subnet_id", "zones",
        "enable_auto_scaling", "min_count", "max_count"},
    ("azurerm_kubernetes_cluster", "identity"): {
        "type", "identity_ids"},
    ("azurerm_kubernetes_cluster", "linux_profile"): {
        "admin_username", "ssh_key"},
    ("vsphere_virtual_machine", "clone"): {
        "template_uuid", "customize", "timeout"},
    ("vsphere_virtual_machine", "disk"): {
        "label", "size", "unit_number", "thin_provisioned",
        "eagerly_scrub"},
    ("vsphere_virtual_machine", "network_interface"): {
        "network_id", "adapter_type"},
    ("triton_machine", "cns"): {"services"},
}

_ROOT_KEYS = {"//", "terraform", "provider", "variable", "output", "locals",
              "resource", "data", "module"}

_VARIABLE_KEYS = {"description", "default", "type", "sensitive", "nullable",
                  "validation"}

_META_ARGS = {"count", "for_each", "provider", "depends_on", "lifecycle",
              "provisioner", "connection", "triggers", "//"}


def interpolation_exprs(s: str) -> List[str]:
    """Extract every top-level ``${...}`` expression from a string,
    brace-balanced (object constructors and nested interpolations stay inside
    one expression), honoring ``$${`` escapes."""
    out: List[str] = []
    i = 0
    n = len(s)
    while i < n:
        j = s.find("${", i)
        if j < 0:
            break
        if j > 0 and s[j - 1] == "$":  # $${ literal escape
            i = j + 2
            continue
        depth = 1
        k = j + 2
        while k < n and depth:
            c = s[k]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
            k += 1
        out.append(s[j + 2:k - 1])
        i = k
    return out


_STRING_LIT = re.compile(r'"(?:[^"\\]|\\.)*"')
_FOR_VARS = re.compile(r"\bfor\s+([A-Za-z_]\w*)(?:\s*,\s*([A-Za-z_]\w*))?\s+in\b")
_REF = re.compile(
    r"(?<![\w.\"'-])([A-Za-z_][\w-]*)((?:\.(?:[A-Za-z_*0-9][\w-]*)|\[[^\]]*\])+)")
_FUNC = re.compile(r"(?<![\w.])([a-z][a-z0-9_]*)\s*\(")


def _strip_strings(expr: str) -> Tuple[str, List[str]]:
    """Replace string literals with spaces, returning nested interpolation
    expressions found inside them for recursive scanning."""
    nested: List[str] = []

    def repl(m: re.Match) -> str:
        nested.extend(interpolation_exprs(m.group(0)[1:-1]))
        return " " * len(m.group(0))

    return _STRING_LIT.sub(repl, expr), nested


def expression_refs(expr: str) -> Tuple[List[Tuple[str, List[str]]], Set[str]]:
    """All (head, path-segments) references and all function-call names in a
    Terraform expression, recursing into nested string interpolations."""
    refs: List[Tuple[str, List[str]]] = []
    funcs: Set[str] = set()
    queue = [expr]
    while queue:
        e = queue.pop()
        stripped, nested = _strip_strings(e)
        queue.extend(nested)
        loop_vars = set()
        for m in _FOR_VARS.finditer(stripped):
            loop_vars.update(g for g in m.groups() if g)
        for m in _FUNC.finditer(stripped):
            funcs.add(m.group(1))
        for m in _REF.finditer(stripped):
            head = m.group(1)
            if head in loop_vars:
                continue
            segs = [s for s in re.split(r"\.|\[[^\]]*\]", m.group(2)) if s]
            refs.append((head, segs))
    return refs, funcs


def _walk_strings(value: Any):
    if isinstance(value, str):
        yield value
    elif isinstance(value, dict):
        for k, v in value.items():
            if k == "//":
                continue
            yield from _walk_strings(v)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _walk_strings(v)


def _walk_key(value: Any, key: str):
    """Yield every value held under `key` anywhere in a JSON tree."""
    if isinstance(value, dict):
        for k, v in value.items():
            if k == key:
                yield v
            else:
                yield from _walk_key(v, key)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _walk_key(v, key)


# ---------------------------------------------------------------------------
# Module-directory validation


class _ModuleFiles:
    def __init__(self, path: str):
        self.path = path
        self.docs: Dict[str, Dict[str, Any]] = {}
        self.errors: List[str] = []
        for fname in ("main.tf.json", "variables.tf.json", "outputs.tf.json"):
            fpath = os.path.join(path, fname)
            if not os.path.isfile(fpath):
                self.errors.append(f"{fname}: missing")
                self.docs[fname] = {}
                continue
            try:
                with open(fpath) as f:
                    doc = json.load(f)
            except ValueError as e:
                self.errors.append(f"{fname}: invalid JSON: {e}")
                doc = {}
            if not isinstance(doc, dict):
                self.errors.append(f"{fname}: root must be a JSON object")
                doc = {}
            self.docs[fname] = doc


def validate_module_dir(path: str) -> List[str]:
    """Validate one HCL-JSON module directory; returns error strings
    (empty = valid)."""
    name = os.path.basename(path.rstrip("/"))
    mf = _ModuleFiles(path)
    errors = [f"{name}/{e}" for e in mf.errors]

    main = mf.docs["main.tf.json"]
    variables = mf.docs["variables.tf.json"].get("variable", {})
    outputs = mf.docs["outputs.tf.json"].get("output", {})

    def err(msg: str) -> None:
        errors.append(f"{name}: {msg}")

    # --- root-block grammar -------------------------------------------------
    for fname, doc in mf.docs.items():
        for key in doc:
            if key not in _ROOT_KEYS:
                errors.append(f"{name}/{fname}: unknown root block {key!r}")

    if not isinstance(variables, dict):
        err("variables.tf.json: 'variable' must be an object")
        variables = {}
    for vname, vbody in variables.items():
        if not isinstance(vbody, dict):
            err(f"variable {vname!r}: body must be an object")
            continue
        unknown = set(vbody) - _VARIABLE_KEYS - {"//"}
        if unknown:
            err(f"variable {vname!r}: unknown keys {sorted(unknown)}")

    if not isinstance(outputs, dict):
        err("outputs.tf.json: 'output' must be an object")
        outputs = {}
    for oname, obody in outputs.items():
        if not isinstance(obody, dict) or "value" not in obody:
            err(f"output {oname!r}: must be an object with a 'value'")

    # --- gather declarations ------------------------------------------------
    locals_decl: Set[str] = set()
    resources: Dict[str, Set[str]] = {}
    datas: Dict[str, Set[str]] = {}
    required_providers: Set[str] = set()
    for doc in mf.docs.values():
        loc = doc.get("locals", {})
        if isinstance(loc, dict):
            locals_decl.update(k for k in loc if k != "//")
        for rtype, insts in (doc.get("resource", {}) or {}).items():
            if not isinstance(insts, dict):
                err(f"resource {rtype!r}: must map names to bodies")
                continue
            resources.setdefault(rtype, set()).update(insts)
        for dtype, insts in (doc.get("data", {}) or {}).items():
            if not isinstance(insts, dict):
                err(f"data {dtype!r}: must map names to bodies")
                continue
            datas.setdefault(dtype, set()).update(insts)
        tf = doc.get("terraform", {})
        if isinstance(tf, dict):
            required_providers.update(tf.get("required_providers", {}) or {})

    # --- resource shapes + provider coverage --------------------------------
    for rtype, insts in (main.get("resource", {}) or {}).items():
        prefix = rtype.split("_", 1)[0]
        provider = _PROVIDER_OF_PREFIX.get(prefix)
        if provider and required_providers and \
                provider not in required_providers:
            err(f"resource {rtype!r}: provider {provider!r} not in "
                f"required_providers {sorted(required_providers)}")
        required = REQUIRED_ATTRS.get(rtype)
        known = KNOWN_ATTRS.get(rtype)
        for iname, body in insts.items():
            if not isinstance(body, dict):
                err(f"resource {rtype}.{iname}: body must be an object")
                continue
            for attr, val in body.items():
                if attr in _META_ARGS:
                    continue
                if known is not None and attr not in known:
                    err(f"resource {rtype}.{iname}: unknown attribute "
                        f"{attr!r} (not in the {rtype} schema)")
                    continue
                schema = NESTED_BLOCK_ATTRS.get((rtype, attr))
                if schema is None:
                    continue
                items = val if isinstance(val, list) else [val]
                for item in items:
                    if not isinstance(item, dict):
                        err(f"resource {rtype}.{iname}: block {attr!r} "
                            f"must be an object, got "
                            f"{type(item).__name__}")
                        continue
                    for k in item:
                        if k != "//" and k not in schema:
                            err(f"resource {rtype}.{iname}: unknown key "
                                f"{k!r} in block {attr!r}")
            if required is None:
                continue
            for attr in required:
                if attr not in body:
                    err(f"resource {rtype}.{iname}: missing required "
                        f"attribute {attr!r}")

    # --- reference resolution -----------------------------------------------
    used_vars: Set[str] = set()
    for doc in mf.docs.values():
        for s in _walk_strings(doc):
            for expr in interpolation_exprs(s):
                refs, funcs = expression_refs(expr)
                for fn in funcs - KNOWN_FUNCTIONS:
                    err(f"unknown function {fn!r} in ${{{expr[:60]}}}")
                for head, segs in refs:
                    if head == "var":
                        if segs and segs[0] not in variables:
                            err(f"undeclared variable var.{segs[0]} "
                                f"in ${{{expr[:60]}}}")
                        elif segs:
                            used_vars.add(segs[0])
                    elif head == "local":
                        if segs and segs[0] not in locals_decl:
                            err(f"undeclared local.{segs[0]} "
                                f"in ${{{expr[:60]}}}")
                    elif head == "module":
                        err(f"module reference ${{{expr[:60]}}} inside a "
                            f"module (submodule calls are not used here)")
                    elif head == "data":
                        if len(segs) >= 2 and (
                                segs[0] not in datas or
                                segs[1] not in datas[segs[0]]):
                            err(f"unresolved data.{'.'.join(segs[:2])} "
                                f"in ${{{expr[:60]}}}")
                    elif head == "path":
                        if segs and segs[0] not in _PATH_ATTRS:
                            err(f"unknown path.{segs[0]}")
                    elif head in ("each", "count", "self", "terraform"):
                        pass
                    else:
                        # resource reference
                        if head not in resources or (
                                segs and resources[head] and
                                segs[0] not in resources[head]):
                            if head in resources:
                                err(f"unresolved resource {head}.{segs[0]}")
                            elif "_" in head:
                                err(f"unresolved reference "
                                    f"{head}.{'.'.join(segs)} "
                                    f"in ${{{expr[:60]}}}")
                            # bare single-word heads that aren't declared
                            # resources are most likely expression locals we
                            # failed to scope — stay silent rather than
                            # false-positive.

    for vname in variables:
        if vname not in used_vars:
            # Declared-but-unused is legal terraform; only surface it when
            # the variable is required (no default) — then the module
            # demands an input it never reads, which is a doc-contract bug.
            # A "//" annotation in the variable body opts out (doc-level
            # passthrough vars that node modules copy, the reference's
            # create/node_vsphere.go currentState.Get pattern).
            if "default" not in variables[vname] and \
                    "//" not in variables[vname]:
                err(f"required variable {vname!r} is never referenced")

    # --- depends_on ---------------------------------------------------------
    for doc in mf.docs.values():
        for deps in _walk_key(doc, "depends_on"):
            if not isinstance(deps, (list, tuple)):
                err("depends_on must be a list")
                continue
            for dep in deps:
                segs = str(dep).split(".")
                if segs[0] == "data":
                    ok = len(segs) >= 3 and segs[1] in datas and \
                        segs[2] in datas[segs[1]]
                elif segs[0] == "module":
                    ok = False
                else:
                    ok = len(segs) >= 2 and segs[0] in resources and \
                        segs[1] in resources[segs[0]]
                if not ok:
                    err(f"depends_on entry {dep!r} does not resolve")

    # --- file references + templatefile contracts ---------------------------
    errors.extend(f"{name}: {e}" for e in _check_files(path, mf))
    return errors


_PATH_REF = re.compile(r"\$\{path\.module\}/((?:\.\./)?[A-Za-z0-9._/-]+)")
_TPL_CALL = re.compile(r"templatefile\(")


def _check_files(path: str, mf: _ModuleFiles) -> List[str]:
    errors: List[str] = []
    raw = json.dumps(mf.docs["main.tf.json"])
    for rel in sorted(set(_PATH_REF.findall(raw))):
        fpath = os.path.normpath(os.path.join(path, rel))
        if not os.path.isfile(fpath):
            errors.append(f"referenced file {rel} does not exist")
    # templatefile(path, {args}) — every ${ident} the template consumes must
    # be passed (terraform fails at apply otherwise; we fail here).
    for s in _walk_strings(mf.docs["main.tf.json"]):
        for m in _TPL_CALL.finditer(s):
            call = _balanced_call(s, m.end() - 1)
            if call is None:
                continue
            pm = _PATH_REF.search(call)
            if pm is None:
                continue
            tpl_path = os.path.normpath(os.path.join(path, pm.group(1)))
            if not os.path.isfile(tpl_path):
                continue  # existence already reported
            passed = _toplevel_object_keys(call)
            with open(tpl_path) as f:
                tpl = f.read()
            needed = _template_vars(tpl)
            missing = needed - passed
            if missing:
                errors.append(
                    f"templatefile({pm.group(1)}): template consumes "
                    f"{sorted(missing)} but call passes {sorted(passed)}")
    return errors


def _toplevel_object_keys(call: str) -> Set[str]:
    """Keys of the outermost object literal in a templatefile(...) call —
    nested map keys must not mask a missing top-level template variable.
    String literals (which may contain '{' via ${path.module}) are skipped;
    only `key =` pairs at object depth 1 directly inside the call's own
    parentheses count."""
    keys: Set[str] = set()
    paren = brace = 0
    anchor = -1
    i, n = 0, len(call)
    while i < n:
        c = call[i]
        if c == '"':
            i += 1
            while i < n and call[i] != '"':
                i += 2 if call[i] == "\\" else 1
        elif c == "(":
            paren += 1
        elif c == ")":
            paren -= 1
        elif c == "{":
            brace += 1
            if brace == 1 and paren == 1:
                anchor = i
        elif c == "}":
            brace -= 1
        elif c == "=" and brace == 1 and paren == 1 and anchor >= 0:
            if (i + 1 >= n or call[i + 1] != "=") and \
                    call[i - 1] not in "!<>=":
                m = re.search(r"(\w+)\s*$", call[anchor + 1:i])
                if m:
                    keys.add(m.group(1))
        elif c == "," and brace == 1 and paren == 1:
            anchor = i
        i += 1
    return keys


def _balanced_call(s: str, open_paren: int) -> Optional[str]:
    depth = 0
    for k in range(open_paren, len(s)):
        if s[k] == "(":
            depth += 1
        elif s[k] == ")":
            depth -= 1
            if depth == 0:
                return s[open_paren:k + 1]
    return None


def _template_vars(tpl: str) -> Set[str]:
    """Variables a .tpl template consumes: heads of ${...} interpolations
    and %{ for/if } directives that are plain identifiers (function calls
    and $${bash} escapes excluded)."""
    needed: Set[str] = set()
    loop_vars: Set[str] = set()
    for m in _FOR_VARS.finditer(tpl):
        loop_vars.update(g for g in m.groups() if g)
    for expr in interpolation_exprs(tpl):
        refs, _funcs = expression_refs(expr)
        for head, _segs in refs:
            if head not in _BUILTIN_HEADS:
                needed.add(head)
        for m in re.finditer(r"\b([A-Za-z_]\w*)\b", expr):
            tok = m.group(1)
            if (tok not in KNOWN_FUNCTIONS and tok not in _BUILTIN_HEADS
                    and not re.search(rf"{tok}\s*\(", expr)
                    and not re.search(rf"[.\"']{tok}", expr)):
                needed.add(tok)
    # %{ if cond }/%{ for x in y } directives
    for m in re.finditer(r"%\{[^}]*\}", tpl):
        body = m.group(0)[2:-1]
        refs, _funcs = expression_refs(body)
        for head, _segs in refs:
            if head not in _BUILTIN_HEADS:
                needed.add(head)
    return {t for t in needed
            if t not in loop_vars and t not in ("if", "for", "in", "else",
                                                "endif", "endfor", "true",
                                                "false", "null")}


def validate_modules_tree(root: str) -> Dict[str, List[str]]:
    """Validate every module directory under a tree root; returns
    {module_name: [errors]} for modules with problems."""
    bad: Dict[str, List[str]] = {}
    for entry in sorted(os.listdir(root)):
        path = os.path.join(root, entry)
        if not os.path.isdir(path) or entry == "files":
            continue
        errs = validate_module_dir(path)
        if errs:
            bad[entry] = errs
    return bad


# ---------------------------------------------------------------------------
# Root-document validation

_DOC_ROOT_KEYS = _ROOT_KEYS | {"driver", "executor", "catalog"}


def validate_document(doc: Any, modules_root: Optional[str] = None,
                      use_registry: bool = True) -> List[str]:
    """Validate a generated root document (the ``main.tf.json`` the executor
    emits): module sources resolve, required variables present, unknown
    variables flagged, every ``${module.k.out}`` names a declared module and
    a registered output."""
    data = doc.to_dict() if hasattr(doc, "to_dict") else doc
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["root document must be a JSON object"]
    for key in data:
        if key not in _DOC_ROOT_KEYS:
            errors.append(f"unknown root block {key!r}")

    modules = data.get("module", {}) or {}
    if not isinstance(modules, dict):
        return errors + ["'module' must be an object"]

    # Resolve each module's declared variables/outputs from the registry or
    # the on-disk HCL tree.
    known_outputs: Dict[str, Optional[Set[str]]] = {}
    for key, cfg in modules.items():
        if not isinstance(cfg, dict):
            errors.append(f"module.{key}: body must be an object")
            continue
        source = cfg.get("source", "")
        if not source:
            errors.append(f"module.{key}: missing 'source'")
            continue
        spec = _module_spec(source, modules_root, use_registry)
        if spec is None:
            known_outputs[key] = None  # unknown source: outputs unchecked
            continue
        var_names, required, outputs = spec
        known_outputs[key] = outputs
        given = {k for k in cfg if k not in ("source", "//")}
        for missing in sorted(required - given):
            errors.append(f"module.{key}: required variable {missing!r} "
                          f"not set")
        for unknown in sorted(given - var_names):
            errors.append(f"module.{key}: unknown variable {unknown!r} "
                          f"(declared: none of {sorted(var_names)[:8]}...)")

    # Interpolation cycles: the executor's topological sort would only
    # discover these at apply time; a hand-edited doc should fail the
    # validate verb first.
    from .interpolate import InterpolationError, topo_order

    try:
        topo_order({k: v for k, v in modules.items()
                    if isinstance(v, dict)})
    except InterpolationError as e:
        # KeyError subclass: str() would requote the message.
        errors.append(str(e.args[0]) if e.args else str(e))
    except RecursionError:
        errors.append("module dependency graph too deep to order "
                      "(suspect a pathological interpolation chain)")

    # ${module.k.out} references anywhere in the doc.
    for s in _walk_strings(data):
        for expr in interpolation_exprs(s):
            refs, _funcs = expression_refs(expr)
            for head, segs in refs:
                if head != "module" or not segs:
                    continue
                mkey = segs[0]
                if mkey not in modules:
                    errors.append(f"${{{expr[:70]}}}: unknown module "
                                  f"{mkey!r}")
                    continue
                outs = known_outputs.get(mkey)
                if outs is not None and len(segs) >= 2 and \
                        segs[1] not in outs:
                    errors.append(f"${{{expr[:70]}}}: module {mkey!r} has "
                                  f"no output {segs[1]!r}")
    return errors


def _module_spec(source: str, modules_root: Optional[str],
                 use_registry: bool
                 ) -> Optional[Tuple[Set[str], Set[str], Set[str]]]:
    """(variables, required-variables, outputs) for a module source.

    A document can be executed by either the in-process registry module or
    its HCL twin (the TerraformExecutor rewrites sources to the tree), and
    the twin may declare extra optional variables (ssh_user, registry
    creds). Validation must not reject a doc either path accepts, so the
    two specs are merged: variables and outputs are unioned, and a variable
    counts as required only if every spec that knows it requires it."""
    specs = []
    if use_registry:
        try:
            from ..modules import get_module
            mod = get_module(source)
            specs.append(({v.name for v in mod.VARIABLES},
                          {v.name for v in mod.VARIABLES if v.required},
                          set(mod.OUTPUTS)))
        # tk8s-lint: disable=TK8S106(the registry is an optional
        # cross-check: out-of-tree module sources are unknown to it and
        # still validate against the on-disk spec below)
        except Exception:
            pass
    if modules_root:
        try:
            from ..modules.registry import module_name_from_source
            name = module_name_from_source(source)
        except Exception:
            name = os.path.basename(source)
        path = os.path.join(modules_root, name)
        if os.path.isdir(path):
            mf = _ModuleFiles(path)
            variables = mf.docs["variables.tf.json"].get("variable", {})
            outputs = mf.docs["outputs.tf.json"].get("output", {})
            if isinstance(variables, dict) and isinstance(outputs, dict):
                specs.append((set(variables),
                              {v for v, b in variables.items()
                               if isinstance(b, dict) and "default" not in b},
                              set(outputs)))
    if not specs:
        return None
    var_names: Set[str] = set()
    outputs_u: Set[str] = set()
    for vs, _req, outs in specs:
        var_names |= vs
        outputs_u |= outs
    required = {v for v in var_names
                if all(v in req for vs, req, _ in specs if v in vs)}
    return var_names, required, outputs_u
