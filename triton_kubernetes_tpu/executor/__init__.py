"""L2 execution: plan/apply engine for the state document.

Reference analog: ``shell/`` — the reference writes the doc to a temp dir and
shells out to the external ``terraform`` binary
(shell/run_terraform.go:63-185). This rebuild keeps that escape hatch
(``TerraformExecutor``) but the primary engine is **in-process**
(``LocalExecutor``): it resolves the module graph, evaluates
``${module.x.y}`` interpolations, orders modules by dependency, and drives
provider drivers directly — which is what makes the whole workflow layer
testable (the single biggest gap in the reference, SURVEY.md §4: nothing below
shell.RunTerraform* had any coverage).
"""

from .interpolate import (
    InterpolationError,
    extract_dependencies,
    module_dependencies,
    resolve,
)
from .plan import Plan, PlanAction, diff_states
from .cloudsim import (
    FatalFaultError,
    FaultPlan,
    FaultPlanError,
    SimulatedKillError,
    TransientFaultError,
)
from .dagspec import DagSpecError, document_from_spec, tpu_slices
from .drivers import driver_names, make_driver, register_driver
from .engine import (
    ApplyError,
    ExecutorState,
    FatalApplyError,
    LocalExecutor,
    OutputError,
    RetryPolicy,
    TransientApplyError,
    modules_fingerprint,
    state_fingerprint,
)
from .terraform import TerraformExecutor

__all__ = [
    "ApplyError",
    "DagSpecError",
    "ExecutorState",
    "FatalApplyError",
    "FatalFaultError",
    "FaultPlan",
    "FaultPlanError",
    "InterpolationError",
    "LocalExecutor",
    "OutputError",
    "RetryPolicy",
    "SimulatedKillError",
    "TransientApplyError",
    "TransientFaultError",
    "Plan",
    "PlanAction",
    "TerraformExecutor",
    "diff_states",
    "document_from_spec",
    "driver_names",
    "make_driver",
    "modules_fingerprint",
    "register_driver",
    "state_fingerprint",
    "extract_dependencies",
    "module_dependencies",
    "resolve",
    "tpu_slices",
]
