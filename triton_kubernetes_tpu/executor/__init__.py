"""L2 execution: plan/apply engine for the state document.

Reference analog: ``shell/`` — the reference writes the doc to a temp dir and
shells out to the external ``terraform`` binary
(shell/run_terraform.go:63-185). This rebuild keeps that escape hatch
(``TerraformExecutor``) but the primary engine is **in-process**
(``LocalExecutor``): it resolves the module graph, evaluates
``${module.x.y}`` interpolations, orders modules by dependency, and drives
provider drivers directly — which is what makes the whole workflow layer
testable (the single biggest gap in the reference, SURVEY.md §4: nothing below
shell.RunTerraform* had any coverage).
"""

from .interpolate import (
    InterpolationError,
    extract_dependencies,
    module_dependencies,
    resolve,
)
from .plan import Plan, PlanAction, diff_states
from .cloudsim import FatalFaultError, FaultPlan, TransientFaultError
from .drivers import driver_names, make_driver, register_driver
from .engine import (
    ApplyError,
    ExecutorState,
    FatalApplyError,
    LocalExecutor,
    OutputError,
    RetryPolicy,
    TransientApplyError,
)
from .terraform import TerraformExecutor

__all__ = [
    "ApplyError",
    "ExecutorState",
    "FatalApplyError",
    "FatalFaultError",
    "FaultPlan",
    "InterpolationError",
    "LocalExecutor",
    "OutputError",
    "RetryPolicy",
    "TransientApplyError",
    "TransientFaultError",
    "Plan",
    "PlanAction",
    "TerraformExecutor",
    "diff_states",
    "driver_names",
    "make_driver",
    "register_driver",
    "extract_dependencies",
    "module_dependencies",
    "resolve",
]
