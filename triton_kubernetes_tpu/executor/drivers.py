"""Cloud-driver registry: the document names its driver, the engine builds it.

A state document may carry a top-level ``driver`` block::

    {"driver": {"name": "local-k8s", "provisioner": "kind"}}

Absent block (or ``name: sim``) keeps the in-process
:class:`~..executor.cloudsim.CloudSimulator` — the default everywhere, and
the only driver used by workflow unit tests. ``local-k8s`` swaps in the real
kind/k3d-backed :class:`~.k8s_local.LocalK8sDriver`; every module runs
unmodified because the driver API is a strict superset of the simulator's.

The driver choice is also persisted inside the executor state's cloud dict
(``to_dict()["driver"]``), so a destroy driven from a reloaded document
reconstructs the same driver even if the doc's block was hand-edited away —
destroying real clusters with the simulator would orphan them.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .cloudsim import CloudSimulator

DriverFactory = Callable[[Dict[str, Any], Dict[str, Any]], Any]

_DRIVERS: Dict[str, DriverFactory] = {}


def register_driver(name: str, factory: DriverFactory) -> None:
    _DRIVERS[name] = factory


def _make_sim(cfg: Dict[str, Any], state: Dict[str, Any]) -> CloudSimulator:
    # An optional ``fault_plan`` block in the driver config arms
    # deterministic fault injection; once armed, the plan's live state
    # (remaining fire-counts) rides the persisted cloud dict and wins over
    # the config spec, so fault sequences survive state round-trips.
    # ``op_latency`` (seconds per mutating op, or an {op: seconds} map)
    # arms the opt-in deterministic latency model — how apply concurrency
    # is measured without a real cloud.
    return CloudSimulator(state, fault_plan=cfg.get("fault_plan"),
                          op_latency=cfg.get("op_latency"))


def _make_local_k8s(cfg: Dict[str, Any], state: Dict[str, Any]):
    from .k8s_local import LocalK8sDriver

    return LocalK8sDriver(state, provisioner=cfg.get("provisioner", ""),
                          node_count=int(cfg.get("nodes") or 0))


register_driver("sim", _make_sim)
register_driver("local-k8s", _make_local_k8s)


def driver_names() -> list:
    return sorted(_DRIVERS)


def normalize_driver_config(raw: Any) -> Dict[str, Any]:
    """Accept the string shorthand (``driver: local-k8s``) or a mapping;
    reject anything else. Shared by the config layer and the document."""
    if raw is None:
        return {}
    if isinstance(raw, str):
        return {"name": raw}
    if isinstance(raw, dict):
        return dict(raw)
    raise ValueError(f"driver must be a name or a mapping, got {raw!r}")


def driver_config(doc) -> Dict[str, Any]:
    return normalize_driver_config(doc.get("driver"))


def make_driver(doc, cloud_state: Optional[Dict[str, Any]] = None):
    """Build the driver for a document + its persisted cloud state."""
    state = cloud_state or {}
    cfg = driver_config(doc)
    # Applied state wins: existing real resources must keep their driver.
    name = state.get("driver") or cfg.get("name") or "sim"
    if name not in _DRIVERS:
        raise ValueError(
            f"unknown driver {name!r} (choices: {sorted(_DRIVERS)})")
    return _DRIVERS[name](cfg, state)
