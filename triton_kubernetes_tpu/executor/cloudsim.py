"""In-process cloud + control-plane simulator.

SURVEY.md §4's top rebuild recommendation: the reference had *zero* coverage
below ``shell.RunTerraform*`` — terraform graph, Rancher API, VM boot and
agent self-registration were all validated by hand. This simulator is the
"fake in-process cloud+Rancher" that closes that gap: modules provision
against it, workflows integration-test against it, and its state round-trips
through the executor state file so targeted destroys work across invocations.

It models, deterministically (no wall clock, no randomness):

* instances / networks / disks per provider (the ``*-rancher-k8s-host`` and
  network-envelope resources);
* a Rancher-style control plane: manager bootstrap mints API credentials
  (setup_rancher.sh.tpl:22-63 analog), cluster create-or-get returns
  ``(cluster_id, registration_token, ca_checksum)`` idempotently
  (rancher_cluster.sh:17-100 analog), nodes join with roles + labels
  (install_rancher_agent.sh.tpl:44 analog);
* hosted-K8s control planes (GKE/AKS) incl. **TPU node pools** with slice
  topology -> per-node ICI mesh coordinate labels;
* applied Kubernetes manifests per cluster (DaemonSets, JobSets, Deployments).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

from ..manager import protocol


def _token(*parts: str) -> str:
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:40]


class CloudSimError(RuntimeError):
    pass


class CloudSimulator:
    def __init__(self, state: Optional[Dict[str, Any]] = None):
        s = state or {}
        self.resources: Dict[str, Dict[str, Any]] = s.get("resources", {})
        self.managers: Dict[str, Dict[str, Any]] = s.get("managers", {})
        self.clusters: Dict[str, Dict[str, Any]] = s.get("clusters", {})
        self.manifests: Dict[str, List[Dict[str, Any]]] = s.get("manifests", {})
        self.serial: int = s.get("serial", 0)

    # ------------------------------------------------------------- serialization
    def to_dict(self) -> Dict[str, Any]:
        return {
            "resources": self.resources,
            "managers": self.managers,
            "clusters": self.clusters,
            "manifests": self.manifests,
            "serial": self.serial,
        }

    # ---------------------------------------------------------------- resources
    def _rkey(self, rtype: str, name: str) -> str:
        return f"{rtype}:{name}"

    def create_resource(self, rtype: str, name: str, **attrs: Any) -> Dict[str, Any]:
        """Idempotent create-or-get of a generic cloud resource."""
        key = self._rkey(rtype, name)
        if key not in self.resources:
            self.serial += 1
            rec = {"type": rtype, "name": name, "id": f"{rtype}-{self.serial:04d}", **attrs}
            if rtype.endswith("instance") or rtype.endswith("machine"):
                rec.setdefault("ip", f"10.0.{(self.serial >> 8) & 255}.{self.serial & 255}")
            self.resources[key] = rec
        else:
            self.resources[key].update(attrs)
        return self.resources[key]

    def get_resource(self, rtype: str, name: str) -> Optional[Dict[str, Any]]:
        return self.resources.get(self._rkey(rtype, name))

    def delete_resource(self, rtype: str, name: str) -> None:
        self.resources.pop(self._rkey(rtype, name), None)
        if rtype == "manager":
            self.managers.pop(name, None)
        if rtype == "cluster":
            # "cluster" resources are keyed by cluster *id*, so deleting one
            # module's registration can never hit a same-named cluster under
            # another manager/provider.
            if name in self.clusters:
                del self.clusters[name]
                self.manifests.pop(name, None)

    # ------------------------------------------------------- control plane (mgr)
    def bootstrap_manager(self, name: str, url: str) -> Dict[str, str]:
        """Manager bootstrap: mints API credentials, idempotently.

        Reference analog: null_resource.setup_rancher_k8s + data.external
        rancher_server (modules/triton-rancher/main.tf:103-137) — the SSH'd
        bash that logs into a fresh Rancher, mints a token and stores it in
        ``~/rancher_api_key``.
        """
        if name not in self.managers:
            self.managers[name] = {
                "name": name,
                "url": url,
                # Shared credential derivation with the real control plane
                # (manager/protocol.py); empty salt keeps tests deterministic.
                **protocol.mint_credentials(name),
                "clusters": [],
            }
        self.managers[name]["url"] = url
        return {k: self.managers[name][k] for k in ("url", "access_key", "secret_key")}

    def _find_manager(self, url: str) -> Dict[str, Any]:
        for m in self.managers.values():
            if m["url"] == url:
                return m
        raise CloudSimError(f"no manager at {url!r} (apply the manager module first)")

    def create_or_get_cluster(self, manager_url: str, cluster_name: str,
                              **attrs: Any) -> Dict[str, Any]:
        """Create-or-get a cluster registration (idempotent).

        Reference analog: files/rancher_cluster.sh:17-100 — POST /v3/cluster
        if absent, then mint a clusterregistrationtoken and read the CA
        checksum from /v3/settings/cacerts.
        """
        mgr = self._find_manager(manager_url)
        # Shared semantic core with the real control plane: same idempotency,
        # same id/token/CA-checksum derivation (manager/protocol.py).
        cluster = protocol.create_or_get_cluster(
            self.clusters, mgr["name"], cluster_name, **attrs)
        if cluster["id"] not in mgr["clusters"]:
            mgr["clusters"].append(cluster["id"])
        return cluster

    def register_node(self, registration_token: str, hostname: str,
                      roles: List[str], labels: Optional[Dict[str, str]] = None,
                      ca_checksum: str = "") -> Dict[str, Any]:
        """Agent self-registration: a booted host joins its cluster.

        Reference analog: install_rancher_agent.sh.tpl:44 (``docker run
        rancher/rancher-agent --server ... --token ... --ca-checksum ...
        --worker|--etcd|--controlplane``). Token+checksum pinning enforced.
        """
        try:
            return protocol.register_node(
                self.clusters, registration_token, hostname, roles,
                labels, ca_checksum)
        except protocol.ProtocolError as e:
            raise CloudSimError(str(e)) from e

    def deregister_node(self, hostname: str) -> None:
        """Remove a host's registration (and its recorded health) from
        whichever cluster holds it — the node-module destroy path.
        Hostnames are unique per state doc (the create-node numbering
        contract), so a plain scan is unambiguous."""
        for c in self.clusters.values():
            c["nodes"].pop(hostname, None)

    def cluster_by_id(self, cluster_id: str) -> Dict[str, Any]:
        if cluster_id not in self.clusters:
            raise CloudSimError(f"no such cluster {cluster_id!r}")
        return self.clusters[cluster_id]

    # ------------------------------------------------------------ node health
    def set_node_health(self, cluster_id: str, hostname: str, ready: bool,
                        reason: str = "") -> None:
        """Record a health transition (what the slice-health probe's
        readiness flip or a failed agent heartbeat reports)."""
        c = self.cluster_by_id(cluster_id)
        if hostname not in c["nodes"]:
            raise CloudSimError(f"no node {hostname!r} in {cluster_id!r}")
        c["nodes"][hostname]["health"] = {"ready": ready, "reason": reason}

    def node_health(self, cluster_id: str) -> Dict[str, Dict[str, Any]]:
        """{node: {ready, reason}} — the consumer side of the health story
        (SURVEY.md §5: slice-health readiness + node-repair surfacing).
        Registered agents default Ready; the real local driver overrides
        this with actual kubelet conditions."""
        c = self.cluster_by_id(cluster_id)
        return {h: dict(n.get("health", {"ready": True, "reason": ""}))
                for h, n in c["nodes"].items()}

    # --------------------------------------------------------------- hosted k8s
    def create_hosted_cluster(self, kind: str, name: str, **attrs: Any) -> Dict[str, Any]:
        """Hosted control plane (GKE/AKS analog): no agent registration —
        nodes come from provider-managed node pools. Re-creates update attrs
        in place (k8s_version bumps etc.), preserving node pools."""
        key = self._rkey(f"{kind}_cluster", name)
        if key not in self.resources:
            self.create_resource(f"{kind}_cluster", name,
                                 endpoint=f"https://{name}.{kind}.local",
                                 node_pools={}, **attrs)
        else:
            self.resources[key].update(attrs)
        return self.resources[key]

    def create_node_pool(self, kind: str, cluster_name: str, pool_name: str,
                         node_count: int, node_labels: Optional[List[Dict[str, str]]] = None,
                         **attrs: Any) -> Dict[str, Any]:
        """Node pool on a hosted cluster; each node gets the provided labels
        (this is where TPU slice/ICI-coordinate labels land)."""
        cluster = self.get_resource(f"{kind}_cluster", cluster_name)
        if cluster is None:
            raise CloudSimError(f"no {kind} cluster {cluster_name!r}")
        nodes = []
        for i in range(node_count):
            labels = dict(node_labels[i]) if node_labels and i < len(node_labels) else {}
            nodes.append({"name": f"{cluster_name}-{pool_name}-{i}", "labels": labels})
        pool = {"name": pool_name, "node_count": node_count, "nodes": nodes, **attrs}
        cluster["node_pools"][pool_name] = pool
        return pool

    # ---------------------------------------------------------------- manifests
    def apply_manifest(self, cluster_id: str, manifest: Dict[str, Any]) -> None:
        """kubectl-apply analog, idempotent on (kind, metadata.name).

        Schema-validates first (topology/validate.py) so the simulator
        rejects what a real API server would — renders are exercised like
        ``kubectl apply --dry-run=server``, in every workflow test."""
        from ..topology.validate import validate_manifest

        validate_manifest(manifest)
        objs = self.manifests.setdefault(cluster_id, [])
        ident = (manifest.get("kind"), manifest.get("metadata", {}).get("name"))
        for i, existing in enumerate(objs):
            if (existing.get("kind"), existing.get("metadata", {}).get("name")) == ident:
                objs[i] = manifest
                return
        objs.append(manifest)

    def delete_manifest(self, cluster_id: str, kind: str, name: str) -> bool:
        """kubectl-delete analog; returns True if the object existed."""
        objs = self.manifests.get(cluster_id, [])
        for i, m in enumerate(objs):
            if (m.get("kind"), m.get("metadata", {}).get("name")) == (kind, name):
                del objs[i]
                return True
        return False

    def get_manifests(self, cluster_id: str, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        objs = self.manifests.get(cluster_id, [])
        if kind is None:
            return objs
        return [o for o in objs if o.get("kind") == kind]
