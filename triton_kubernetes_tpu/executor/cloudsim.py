"""In-process cloud + control-plane simulator.

SURVEY.md §4's top rebuild recommendation: the reference had *zero* coverage
below ``shell.RunTerraform*`` — terraform graph, Rancher API, VM boot and
agent self-registration were all validated by hand. This simulator is the
"fake in-process cloud+Rancher" that closes that gap: modules provision
against it, workflows integration-test against it, and its state round-trips
through the executor state file so targeted destroys work across invocations.

It models, deterministically (no wall clock, no randomness):

* instances / networks / disks per provider (the ``*-rancher-k8s-host`` and
  network-envelope resources);
* a Rancher-style control plane: manager bootstrap mints API credentials
  (setup_rancher.sh.tpl:22-63 analog), cluster create-or-get returns
  ``(cluster_id, registration_token, ca_checksum)`` idempotently
  (rancher_cluster.sh:17-100 analog), nodes join with roles + labels
  (install_rancher_agent.sh.tpl:44 analog);
* hosted-K8s control planes (GKE/AKS) incl. **TPU node pools** with slice
  topology -> per-node ICI mesh coordinate labels;
* applied Kubernetes manifests per cluster (DaemonSets, JobSets, Deployments).
"""

from __future__ import annotations

import contextlib
import copy
import hashlib
import os
import signal as _signal
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

from ..manager import protocol
from ..utils import metrics


def _token(*parts: str) -> str:
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:40]


class CloudSimError(RuntimeError):
    pass


class TransientFaultError(CloudSimError):
    """An injected fault a real fleet would retry through: a flaked
    control-plane call (429/503), a boot that fails and succeeds on the
    next attempt. The engine's retry/backoff loop consumes this type."""


class FatalFaultError(CloudSimError):
    """An injected fault retries cannot fix: quota exhausted, a config the
    provider permanently rejects. The engine fails fast on this type."""


class FaultPlanError(ValueError):
    """A fault-rule spec is malformed (unknown op/kind/mode, a preempt
    rule with no slice, a floating at_module_op anchor). Raised at plan
    *construction*, uniformly for every rule shape — a generated or
    hand-written plan must fail loudly before the first op, not fire
    nothing (or the wrong thing) mid-apply."""


class SimulatedKillError(BaseException):
    """An injected process death (the kill-mid-wave fault class).

    Derives from BaseException on purpose: the engine's retry loop
    catches ``Exception``, so a kill rides past retry/backoff exactly
    like a real SIGKILL would — the module is NOT retried, the wavefront
    unwinds, and whatever state was saved per completed module is what
    the resumed run starts from."""


class FaultPlan:
    """Deterministic fault injection for the simulator.

    No wall clock, no randomness: faults fire on exact operation matches
    and a monotonic mutation counter (``ops``), so a seeded plan produces
    the identical failure sequence on every run — and, because remaining
    fire-counts serialize with the cloud state, across executor
    invocations too (a re-run after a failed apply sees the plan exactly
    where the failed run left it).

    Spec format (JSON-able; see docs/guide/fault-tolerance.md)::

        {"faults": [
          # Fail an operation N times, then let it succeed (boot flake):
          {"op": "create_resource", "match": {"name": "c1-worker-1"},
           "times": 2, "kind": "transient", "error": "instance boot failed"},
          # Drop/5xx any control-plane call once:
          {"op": "register_node", "times": 1, "kind": "transient",
           "error": "503 service unavailable"},
          # Hard-fail (no retry can help):
          {"op": "create_node_pool", "match": {"pool": "huge"},
           "kind": "fatal", "error": "quota exceeded"},
          # Preempt a named TPU slice when the mutation clock reaches 7:
          {"op": "preempt", "slice_id": "ml-pool0", "at_op": 7},
          # Graceful-warning preemption: deliver the GKE-style SIGTERM
          # to the trainer process at the warning tick, reclaim the
          # slice grace_ops mutations later (0 = same tick):
          {"op": "preempt", "slice_id": "ml-pool0", "at_op": 7,
           "mode": "graceful-warning", "notify_pid": 12345,
           "signal": "SIGTERM", "grace_ops": 3},
        ]}

    ``match`` values substring-match the operation's info fields (type,
    name, cluster, pool, hostname, ...); an absent ``match`` matches every
    call of that op; ``op: "*"`` matches every mutating operation.

    **Per-module anchors (interleaving-safe).** Under the wavefront apply
    scheduler the *global* mutation clock interleaves differently at every
    ``--parallelism``, so rules anchored on it (``at_op``) are only
    deterministic for serial applies. Each rule may instead carry:

    * ``module`` — substring-match against the module key the engine has
      scoped around the current apply (``CloudSimulator.module_scope``);
    * ``at_module_op`` — the 1-based index of the operation *within that
      module's own op sequence* (op rules: fire exactly at that index;
      preempt rules: fire once the scoped module's counter reaches it).

    A module's own op sequence is fixed by its config, so per-module
    anchors fire identically at any parallelism — the property the
    parallel-vs-serial bitwise-equality tests pin. ``at_module_op``
    requires ``module`` (an anchor that floats to whichever module gets
    there first would defeat the point; rejected at plan build).

    As with the global clock, a pending preemption (and a
    graceful-warning reclaim in particular) only fires when its
    anchoring clock next *advances*: a ``grace_ops`` window that
    extends past the anchored module's (or, for ``at_op``, the whole
    apply's) last mutation never fires — budget grace windows inside
    the ops the run will actually make.
    """

    #: Every mutating operation the simulator exposes — the closed set an
    #: op rule may name. Kept in sync with the ``_mutate`` call sites
    #: below; a rule naming anything else is a typo that would silently
    #: never fire, so it is rejected at construction instead.
    MUTATING_OPS = frozenset({
        "create_resource", "delete_resource", "bootstrap_manager",
        "create_or_get_cluster", "register_node", "deregister_node",
        "set_node_health", "create_hosted_cluster", "create_node_pool",
        "apply_manifest", "delete_manifest",
    })

    # Key vocabularies per rule shape: a misspelled key ("slice" for
    # "slice_id") is as silently inert as a misspelled op. ``fired`` /
    # ``warned`` are the serialized live-state keys, accepted so a
    # persisted plan round-trips through its own to_dict().
    _OP_RULE_KEYS = frozenset({
        "op", "times", "kind", "error", "match", "module", "at_module_op",
        "fired"})
    _PREEMPT_RULE_KEYS = frozenset({
        "op", "slice_id", "at_op", "at_module_op", "module", "mode",
        "notify_pid", "signal", "grace_ops", "times", "kind", "fired",
        "warned"})

    def __init__(self, spec: Optional[Dict[str, Any]] = None):
        self.rules: List[Dict[str, Any]] = []
        for i, rule in enumerate((spec or {}).get("faults", [])):
            self.rules.append(self._validated(i, rule))

    @classmethod
    def _validated(cls, i: int, rule: Any) -> Dict[str, Any]:
        """One rule, checked and normalized. Every malformed shape raises
        the same typed :class:`FaultPlanError` naming the rule index and
        the offending field — the uniform error path the generated-plan
        machinery (chaos harness) and hand-written docs plans share."""
        def bad(msg: str) -> FaultPlanError:
            return FaultPlanError(f"fault rule #{i}: {msg} (got {rule!r})")

        if not isinstance(rule, dict):
            raise bad("must be a mapping")
        op = rule.get("op")
        if not isinstance(op, str) or not op:
            raise bad("must name its 'op'")
        r = dict(rule)
        r.setdefault("times", 1)
        r.setdefault("kind", "transient")
        r.setdefault("fired", 0)
        if "at_module_op" in r and not r.get("module"):
            # Without a module anchor the per-module op index matches
            # whichever module reaches it first — exactly the
            # interleaving-dependence this anchor exists to remove.
            raise bad("fault rule with at_module_op must name its module")
        for key in ("times", "fired", "at_op", "at_module_op", "grace_ops",
                    "notify_pid", "warned"):
            if key in r and not isinstance(r[key], int):
                raise bad(f"{key!r} must be an integer")
            if key in r and r[key] < 0:
                raise bad(f"{key!r} must be >= 0")
        if r["times"] < 1:
            raise bad("'times' must be >= 1")
        if "at_module_op" in r and r["at_module_op"] < 1:
            raise bad("'at_module_op' is a 1-based op index, must be >= 1")
        # kind is checked for EVERY rule shape (preempt rules carry the
        # serialized default too): a typo'd kind silently firing with
        # transient semantics is the exact class this validation kills.
        if r["kind"] not in ("transient", "fatal"):
            raise bad(f"unknown kind {r['kind']!r} "
                      "(choices: transient, fatal)")
        if op == "preempt":
            unknown = set(r) - cls._PREEMPT_RULE_KEYS
            if unknown:
                raise bad(f"unknown preempt-rule keys {sorted(unknown)}")
            if not isinstance(r.get("slice_id"), str) or not r["slice_id"]:
                raise bad("preempt rules must name their 'slice_id'")
            if r.get("mode") not in (None, "graceful-warning"):
                raise bad(f"unknown preempt mode {r.get('mode')!r} "
                          "(only 'graceful-warning')")
            return r
        unknown = set(r) - cls._OP_RULE_KEYS
        if unknown:
            raise bad(f"unknown rule keys {sorted(unknown)} "
                      "(mode/slice_id/grace_ops are preempt-rule keys)")
        if op != "*" and op not in cls.MUTATING_OPS:
            raise bad(f"unknown op {op!r} (choices: '*', 'preempt', "
                      f"{sorted(cls.MUTATING_OPS)})")
        if "match" in r and not isinstance(r["match"], dict):
            raise bad("'match' must be a mapping of info-field substrings")
        return r

    def to_dict(self) -> Dict[str, Any]:
        return {"faults": [dict(r) for r in self.rules]}

    @staticmethod
    def _matches(rule: Dict[str, Any], op: str, info: Dict[str, Any],
                 module: str, module_op: int) -> bool:
        if rule.get("op") not in ("*", op):
            return False
        if "module" in rule and str(rule["module"]) not in module:
            return False
        if "at_module_op" in rule and int(rule["at_module_op"]) != module_op:
            return False
        for key, want in (rule.get("match") or {}).items():
            if str(want) not in str(info.get(key, "")):
                return False
        return True

    @staticmethod
    def _preempt_due(rule: Dict[str, Any], sim: "CloudSimulator",
                     module: str, module_op: int, grace: int = 0) -> bool:
        """Whether a preempt rule's anchor (+grace window) has passed —
        the global mutation clock by default, the scoped module's own op
        counter when the rule carries ``at_module_op``."""
        if "at_module_op" in rule:
            if str(rule.get("module", "")) not in module:
                return False
            return module_op >= int(rule["at_module_op"]) + grace
        return sim.ops >= int(rule.get("at_op", 0)) + grace

    def check(self, sim: "CloudSimulator", op: str, info: Dict[str, Any],
              module: str = "", module_op: int = 0) -> None:
        """Called by the simulator before each mutating operation (the
        mutation clock has already ticked). Fires due preemptions, then
        raises if an armed fault rule matches this call. ``module`` /
        ``module_op`` identify the engine-scoped module issuing the call
        and its per-module op index (0 when unscoped)."""
        for rule in self.rules:
            if rule.get("op") != "preempt" or rule["fired"]:
                continue
            if rule.get("mode") == "graceful-warning":
                # The GKE contract: SIGTERM lands first, the reclaim
                # follows after the grace window. Both anchors are
                # deterministic clock ticks, so the sequence repeats
                # exactly and the warned/fired flags serialize.
                if not rule.get("warned") and self._preempt_due(
                        rule, sim, module, module_op):
                    rule["warned"] = 1
                    sim.warn_preemption(rule["slice_id"],
                                        pid=rule.get("notify_pid"),
                                        sig=rule.get("signal", "SIGTERM"))
                if self._preempt_due(rule, sim, module, module_op,
                                     grace=int(rule.get("grace_ops", 0))):
                    rule["fired"] = 1
                    sim.preempt_slice(rule["slice_id"])
            elif self._preempt_due(rule, sim, module, module_op):
                rule["fired"] = 1
                sim.preempt_slice(rule["slice_id"])
        for rule in self.rules:
            if rule.get("op") == "preempt" or rule["fired"] >= rule["times"]:
                continue
            if self._matches(rule, op, info, module, module_op):
                rule["fired"] += 1
                metrics.counter("tk8s_cloudsim_faults_total").inc(
                    kind=rule["kind"])
                msg = rule.get("error") or f"injected fault on {op}"
                exc = (FatalFaultError if rule["kind"] == "fatal"
                       else TransientFaultError)
                raise exc(f"{msg} (op={op}, "
                          f"attempt {rule['fired']}/{rule['times']})")


class CloudSimulator:
    # Declares the driver safe for the engine's wavefront scheduler:
    # every mutator is atomic under the instance lock and snapshot()
    # gives a consistent persistable view mid-flight. Drivers doing real
    # external work (subprocess provisioners) opt out and the engine
    # clamps them to serial.
    SUPPORTS_PARALLEL_APPLY = True

    def __init__(self, state: Optional[Dict[str, Any]] = None,
                 fault_plan: Optional[Dict[str, Any]] = None,
                 op_latency: Optional[Any] = None,
                 sleep: Callable[[float], None] = time.sleep):
        s = state or {}
        # Injectable sleeper (the executor/serve-engine pattern): tests
        # assert latency *accounting* against a recorder instead of
        # wall-clock thresholds that flake under concurrent machine load.
        # Not serialized — a timing implementation, not timing model.
        self._sleep = sleep
        self.resources: Dict[str, Dict[str, Any]] = s.get("resources", {})
        self.managers: Dict[str, Dict[str, Any]] = s.get("managers", {})
        self.clusters: Dict[str, Dict[str, Any]] = s.get("clusters", {})
        self.manifests: Dict[str, List[Dict[str, Any]]] = s.get("manifests", {})
        self.serial: int = s.get("serial", 0)
        # Mutation clock: every state-changing call ticks it exactly once.
        # It anchors at_op preemptions and lets tests assert the zero-
        # mutation no-op contract without wrapping the driver.
        self.ops: int = s.get("ops", 0)
        # Per-module op counters (ticked only inside an engine
        # ``module_scope``): the interleaving-independent clock that
        # per-module fault anchors fire on. Serialized with the state so
        # module-scoped fault sequences survive round-trips like the
        # global clock does.
        self.module_ops: Dict[str, int] = s.get("module_ops", {})
        # Lifetime preemption count per slice id. The live "preempted"
        # pool flag is consumed by repair (the replacement pool comes
        # back clean), so without this record past reclaims are
        # invisible — and the operator's preemption-risk weighting needs
        # exactly that history. Serialized with the state.
        self.preempt_history: Dict[str, int] = s.get("preempt_history", {})
        # One re-entrant lock makes every mutating operation atomic, so
        # the wavefront apply scheduler can drive modules concurrently:
        # clock tick + fault check + state mutation are indivisible.
        self._lock = threading.RLock()
        self._scope = threading.local()
        # Injectable process-death hook (the chaos harness's kill-mid-wave
        # fault): called after every mutation's clock tick + fault check
        # but BEFORE the op's state mutation lands, outside the lock; may
        # raise :class:`SimulatedKillError`. The death therefore leaves
        # the current op not-yet-applied (like an injected fault would) —
        # half-applied *modules* and mid-wave sibling commits are the
        # states it exercises, not a torn individual op. Never
        # serialized — a kill is an event, not state.
        self.kill_hook: Optional[Callable[[str, str, int], None]] = None
        # Opt-in deterministic per-op simulated latency (seconds): a float
        # applied to every mutating op, or an {op: seconds} map with "*"
        # as the default. Off (0) unless configured; serialized with the
        # sim so a reloaded state keeps the same timing model. The sleep
        # happens OUTSIDE the lock, so concurrent modules overlap their
        # latency — which is exactly what makes apply concurrency
        # measurable without a real cloud.
        self.op_latency: Optional[Any] = (
            op_latency if op_latency is not None else s.get("op_latency"))
        # Persisted plan state (with decremented fire-counts) wins over the
        # UNCHANGED spec it came from, so fault sequences stay deterministic
        # across the save/load round-trip of the executor state — but a
        # *different* spec in the driver config re-arms fresh (the operator
        # swapped injection scenarios on a live doc).
        self._fault_spec: Optional[Dict[str, Any]] = s.get("fault_plan_spec")
        if fault_plan and fault_plan != self._fault_spec:
            self.fault_plan: Optional[FaultPlan] = FaultPlan(fault_plan)
            self._fault_spec = fault_plan
        elif "fault_plan" in s:
            self.fault_plan = FaultPlan(s["fault_plan"])
        else:
            self.fault_plan = None

    @contextlib.contextmanager
    def module_scope(self, module_key: str) -> Iterator[None]:
        """Attribute this thread's mutations to one module: ticks that
        module's own op counter and lets fault rules anchor on it
        (``module`` / ``at_module_op``). The engine wraps each module
        apply/destroy in this scope; the scope is thread-local, so
        concurrent modules never see each other's attribution."""
        prev = getattr(self._scope, "module", "")
        self._scope.module = module_key
        try:
            yield
        finally:
            self._scope.module = prev

    def _op_latency_s(self, op: str) -> float:
        spec = self.op_latency
        if not spec:
            return 0.0
        if isinstance(spec, dict):
            return float(spec.get(op, spec.get("*", 0.0)))
        return float(spec)

    def _mutate(self, op: str, **info: Any) -> None:
        """Tick the mutation clock and give the fault plan its shot. Every
        mutating operation calls this first, before touching state, so an
        injected failure always leaves the op not-yet-applied (the module
        retries it via its own idempotent create-or-get)."""
        module = getattr(self._scope, "module", "")
        with self._lock:
            self.ops += 1
            module_op = 0
            if module:
                module_op = self.module_ops.get(module, 0) + 1
                self.module_ops[module] = module_op
            metrics.counter("tk8s_cloudsim_ops_total").inc(op=op)
            if self.fault_plan is not None:
                if module:
                    info = dict(info, module=module)
                self.fault_plan.check(self, op, info, module=module,
                                      module_op=module_op)
        if self.kill_hook is not None:
            self.kill_hook(op, module, module_op)
        latency = self._op_latency_s(op)
        if latency > 0:
            self._sleep(latency)

    # ------------------------------------------------------------- serialization
    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "resources": self.resources,
                "managers": self.managers,
                "clusters": self.clusters,
                "manifests": self.manifests,
                "serial": self.serial,
                "ops": self.ops,
            }
            if self.module_ops:
                out["module_ops"] = self.module_ops
            if self.preempt_history:
                out["preempt_history"] = self.preempt_history
            if self.op_latency:
                out["op_latency"] = self.op_latency
            if self.fault_plan is not None:
                out["fault_plan"] = self.fault_plan.to_dict()
                if self._fault_spec is not None:
                    out["fault_plan_spec"] = self._fault_spec
            return out

    def snapshot(self) -> Dict[str, Any]:
        """A deep, point-in-time copy of :meth:`to_dict` taken under the
        lock — what the engine persists after each completed module while
        sibling modules may still be mutating the live dicts."""
        with self._lock:
            return copy.deepcopy(self.to_dict())

    # ---------------------------------------------------------------- resources
    def _rkey(self, rtype: str, name: str) -> str:
        return f"{rtype}:{name}"

    def create_resource(self, rtype: str, name: str, **attrs: Any) -> Dict[str, Any]:
        """Idempotent create-or-get of a generic cloud resource."""
        self._mutate("create_resource", type=rtype, name=name)
        with self._lock:
            return self._create_resource_record(rtype, name, **attrs)

    def _create_resource_record(self, rtype: str, name: str,
                                **attrs: Any) -> Dict[str, Any]:
        """The create-or-get body, clock-free — for compound ops that have
        already ticked the mutation clock once for the whole call.

        Generated ids and ips are **content-addressed** (derived from the
        resource key, not a global creation counter), so the applied state
        is byte-identical no matter how concurrent modules interleave
        their creations — the wavefront scheduler's bitwise-parity
        contract rests on this.
        """
        key = self._rkey(rtype, name)
        if key not in self.resources:
            self.serial += 1
            rec = {"type": rtype, "name": name,
                   "id": f"{rtype}-{_token('id', rtype, name)[:8]}", **attrs}
            if rtype.endswith("instance") or rtype.endswith("machine"):
                addr = int(_token("ip", rtype, name)[:6], 16)
                rec.setdefault("ip", f"10.{(addr >> 16) & 255}."
                                     f"{(addr >> 8) & 255}.{addr & 255}")
            self.resources[key] = rec
        else:
            self.resources[key].update(attrs)
        return self.resources[key]

    def get_resource(self, rtype: str, name: str) -> Optional[Dict[str, Any]]:
        return self.resources.get(self._rkey(rtype, name))

    def delete_resource(self, rtype: str, name: str) -> None:
        self._mutate("delete_resource", type=rtype, name=name)
        with self._lock:
            self.resources.pop(self._rkey(rtype, name), None)
            if rtype == "manager":
                self.managers.pop(name, None)
            if rtype == "cluster":
                # "cluster" resources are keyed by cluster *id*, so deleting
                # one module's registration can never hit a same-named
                # cluster under another manager/provider.
                if name in self.clusters:
                    del self.clusters[name]
                    self.manifests.pop(name, None)

    # ------------------------------------------------------- control plane (mgr)
    def bootstrap_manager(self, name: str, url: str) -> Dict[str, str]:
        """Manager bootstrap: mints API credentials, idempotently.

        Reference analog: null_resource.setup_rancher_k8s + data.external
        rancher_server (modules/triton-rancher/main.tf:103-137) — the SSH'd
        bash that logs into a fresh Rancher, mints a token and stores it in
        ``~/rancher_api_key``.
        """
        self._mutate("bootstrap_manager", name=name, url=url)
        with self._lock:
            if name not in self.managers:
                self.managers[name] = {
                    "name": name,
                    "url": url,
                    # Shared credential derivation with the real control
                    # plane (manager/protocol.py); empty salt keeps tests
                    # deterministic.
                    **protocol.mint_credentials(name),
                    "clusters": [],
                }
            self.managers[name]["url"] = url
            return {k: self.managers[name][k]
                    for k in ("url", "access_key", "secret_key")}

    def _find_manager(self, url: str) -> Dict[str, Any]:
        for m in self.managers.values():
            if m["url"] == url:
                return m
        raise CloudSimError(f"no manager at {url!r} (apply the manager module first)")

    def create_or_get_cluster(self, manager_url: str, cluster_name: str,
                              **attrs: Any) -> Dict[str, Any]:
        """Create-or-get a cluster registration (idempotent).

        Reference analog: files/rancher_cluster.sh:17-100 — POST /v3/cluster
        if absent, then mint a clusterregistrationtoken and read the CA
        checksum from /v3/settings/cacerts.
        """
        self._mutate("create_or_get_cluster", name=cluster_name,
                     url=manager_url)
        with self._lock:
            mgr = self._find_manager(manager_url)
            # Shared semantic core with the real control plane: same
            # idempotency, same id/token/CA-checksum derivation
            # (manager/protocol.py).
            cluster = protocol.create_or_get_cluster(
                self.clusters, mgr["name"], cluster_name, **attrs)
            if cluster["id"] not in mgr["clusters"]:
                # Kept sorted, not append-ordered: parallel cluster modules
                # register in whatever order they finish, and the persisted
                # state must not depend on that race.
                mgr["clusters"].append(cluster["id"])
                mgr["clusters"].sort()
            return cluster

    def register_node(self, registration_token: str, hostname: str,
                      roles: List[str], labels: Optional[Dict[str, str]] = None,
                      ca_checksum: str = "") -> Dict[str, Any]:
        """Agent self-registration: a booted host joins its cluster.

        Reference analog: install_rancher_agent.sh.tpl:44 (``docker run
        rancher/rancher-agent --server ... --token ... --ca-checksum ...
        --worker|--etcd|--controlplane``). Token+checksum pinning enforced.
        """
        self._mutate("register_node", hostname=hostname)
        with self._lock:
            try:
                return protocol.register_node(
                    self.clusters, registration_token, hostname, roles,
                    labels, ca_checksum)
            except protocol.ProtocolError as e:
                raise CloudSimError(str(e)) from e

    def deregister_node(self, hostname: str) -> None:
        """Remove a host's registration (and its recorded health) from
        whichever cluster holds it — the node-module destroy path.
        Hostnames are unique per state doc (the create-node numbering
        contract), so a plain scan is unambiguous."""
        self._mutate("deregister_node", hostname=hostname)
        with self._lock:
            for c in self.clusters.values():
                c["nodes"].pop(hostname, None)

    def cluster_by_id(self, cluster_id: str) -> Dict[str, Any]:
        if cluster_id not in self.clusters:
            raise CloudSimError(f"no such cluster {cluster_id!r}")
        return self.clusters[cluster_id]

    # ------------------------------------------------------------ node health
    def set_node_health(self, cluster_id: str, hostname: str, ready: bool,
                        reason: str = "") -> None:
        """Record a health transition (what the slice-health probe's
        readiness flip or a failed agent heartbeat reports)."""
        self._mutate("set_node_health", cluster=cluster_id,
                     hostname=hostname)
        with self._lock:
            c = self.cluster_by_id(cluster_id)
            if hostname not in c["nodes"]:
                raise CloudSimError(f"no node {hostname!r} in {cluster_id!r}")
            c["nodes"][hostname]["health"] = {"ready": ready, "reason": reason}

    def node_health(self, cluster_id: str) -> Dict[str, Dict[str, Any]]:
        """{node: {ready, reason}} — the consumer side of the health story
        (SURVEY.md §5: slice-health readiness + node-repair surfacing).
        Registered agents default Ready; the real local driver overrides
        this with actual kubelet conditions."""
        c = self.cluster_by_id(cluster_id)
        return {h: dict(n.get("health", {"ready": True, "reason": ""}))
                for h, n in c["nodes"].items()}

    # --------------------------------------------------------------- hosted k8s
    def create_hosted_cluster(self, kind: str, name: str, **attrs: Any) -> Dict[str, Any]:
        """Hosted control plane (GKE/AKS analog): no agent registration —
        nodes come from provider-managed node pools. Re-creates update attrs
        in place (k8s_version bumps etc.), preserving node pools."""
        self._mutate("create_hosted_cluster", type=kind, name=name)
        with self._lock:
            key = self._rkey(f"{kind}_cluster", name)
            if key not in self.resources:
                # Clock-free inner create: this compound op already ticked
                # once.
                self._create_resource_record(
                    f"{kind}_cluster", name,
                    endpoint=f"https://{name}.{kind}.local",
                    node_pools={}, **attrs)
            else:
                self.resources[key].update(attrs)
            return self.resources[key]

    def create_node_pool(self, kind: str, cluster_name: str, pool_name: str,
                         node_count: int, node_labels: Optional[List[Dict[str, str]]] = None,
                         **attrs: Any) -> Dict[str, Any]:
        """Node pool on a hosted cluster; each node gets the provided labels
        (this is where TPU slice/ICI-coordinate labels land)."""
        self._mutate("create_node_pool", type=kind, cluster=cluster_name,
                     pool=pool_name)
        with self._lock:
            cluster = self.get_resource(f"{kind}_cluster", cluster_name)
            if cluster is None:
                raise CloudSimError(f"no {kind} cluster {cluster_name!r}")
            nodes = []
            for i in range(node_count):
                labels = (dict(node_labels[i])
                          if node_labels and i < len(node_labels) else {})
                nodes.append({"name": f"{cluster_name}-{pool_name}-{i}",
                              "labels": labels})
            pool = {"name": pool_name, "node_count": node_count,
                    "nodes": nodes, **attrs}
            cluster["node_pools"][pool_name] = pool
            return pool

    # ---------------------------------------------------------------- manifests
    def apply_manifest(self, cluster_id: str, manifest: Dict[str, Any]) -> None:
        """kubectl-apply analog, idempotent on (kind, metadata.name).

        Schema-validates first (topology/validate.py) so the simulator
        rejects what a real API server would — renders are exercised like
        ``kubectl apply --dry-run=server``, in every workflow test."""
        self._mutate("apply_manifest", cluster=cluster_id,
                     kind=manifest.get("kind", ""),
                     name=manifest.get("metadata", {}).get("name", ""))
        from ..topology.validate import validate_manifest

        validate_manifest(manifest)
        with self._lock:
            objs = self.manifests.setdefault(cluster_id, [])
            ident = (manifest.get("kind"),
                     manifest.get("metadata", {}).get("name"))
            for i, existing in enumerate(objs):
                if (existing.get("kind"),
                        existing.get("metadata", {}).get("name")) == ident:
                    objs[i] = manifest
                    return
            objs.append(manifest)
            # Kept sorted by (kind, name), not append-ordered: parallel
            # modules installing into the same cluster must leave the
            # same manifest list no matter which finished first.
            objs.sort(key=lambda m: (str(m.get("kind", "")),
                                     str(m.get("metadata", {}).get("name", ""))))

    def delete_manifest(self, cluster_id: str, kind: str, name: str) -> bool:
        """kubectl-delete analog; returns True if the object existed."""
        self._mutate("delete_manifest", cluster=cluster_id, kind=kind,
                     name=name)
        with self._lock:
            objs = self.manifests.get(cluster_id, [])
            for i, m in enumerate(objs):
                if (m.get("kind"),
                        m.get("metadata", {}).get("name")) == (kind, name):
                    del objs[i]
                    return True
            return False

    def get_manifests(self, cluster_id: str, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        objs = self.manifests.get(cluster_id, [])
        if kind is None:
            return objs
        return [o for o in objs if o.get("kind") == kind]

    # --------------------------------------------------------- TPU preemption
    def _slice_pools(self, slice_id: str):
        """(cluster_resource, pool) pairs for a slice: matched by the
        slice-id node label, or — for already-preempted pools whose labels
        are gone — by the "<cluster>-<pool>" slice naming contract
        (modules/gcp_tpu.py)."""
        from ..topology.labels import LABEL_PREFIX

        label = f"{LABEL_PREFIX}/slice-id"
        for rec in self.resources.values():
            for pool_name, pool in (rec.get("node_pools") or {}).items():
                if (any(n.get("labels", {}).get(label) == slice_id
                        for n in pool.get("nodes", []))
                        or f"{rec.get('name')}-{pool_name}" == slice_id):
                    yield rec, pool

    def warn_preemption(self, slice_id: str, pid: Optional[int] = None,
                        sig: Any = "SIGTERM") -> List[str]:
        """Graceful preemption warning: GKE sends the workload SIGTERM
        ~30s before reclaiming a TPU slice (the JobSet termination grace
        period). The simulator analog marks the slice's pool
        ``preempt_warning`` and — when ``pid`` names a live trainer
        process — delivers the real signal, so integration tests drive
        the trainer's preemption-aware emergency-checkpoint path with an
        actual SIGTERM, not a mock. Like :meth:`preempt_slice`, this IS
        the fault event: no clock tick, no fault-plan re-entry."""
        hit: List[str] = []
        with self._lock:
            for _, pool in self._slice_pools(slice_id):
                pool["preempt_warning"] = True
                hit.extend(n["name"] for n in pool.get("nodes", []))
        if not hit:
            raise CloudSimError(f"no node pool carries slice {slice_id!r}")
        metrics.counter("tk8s_cloudsim_preempt_warnings_total").inc()
        if pid:
            signum = getattr(_signal, sig) if isinstance(sig, str) else sig
            try:
                os.kill(int(pid), signum)
            except ProcessLookupError:
                pass  # workload already gone; the warning outlived it
        return hit

    def preempt_slice(self, slice_id: str) -> List[str]:
        """Preempt a TPU slice: every host VM in its node pool is
        reclaimed (the v5e/v5p spot/defragmentation event). The pool stays
        on record but its nodes lose their ICI coordinate labels — exactly
        what a real reclaim leaves behind: capacity gone, stale pool
        object, scheduler labels meaningless. Mutates state directly (it
        IS the fault), so it never ticks the mutation clock or re-enters
        the fault plan."""
        hit: List[str] = []
        with self._lock:
            for _, pool in self._slice_pools(slice_id):
                pool["preempted"] = True
                for node in pool.get("nodes", []):
                    node["preempted"] = True
                    node["labels"] = {}
                    hit.append(node["name"])
            if hit:
                self.preempt_history[slice_id] = \
                    self.preempt_history.get(slice_id, 0) + 1
        if not hit:
            raise CloudSimError(f"no node pool carries slice {slice_id!r}")
        metrics.counter("tk8s_cloudsim_preemptions_total").inc()
        return hit

    def cordon_slice(self, slice_id: str) -> List[str]:
        """Mark a slice's surviving node objects unschedulable before
        replacement (kubectl cordon analog) — repair must stop new pods
        landing on a half-dead slice before it tears the pool down."""
        hit: List[str] = []
        with self._lock:
            for _, pool in self._slice_pools(slice_id):
                for node in pool.get("nodes", []):
                    node["cordoned"] = True
                    hit.append(node["name"])
        return hit

    def preempted_slices(self) -> Dict[str, Dict[str, Any]]:
        """{slice_id: {cluster, pool, nodes}} for every pool currently
        marked preempted — what the slice-aware repair loop scans."""
        out: Dict[str, Dict[str, Any]] = {}
        for rec in self.resources.values():
            for pool_name, pool in (rec.get("node_pools") or {}).items():
                if not pool.get("preempted"):
                    continue
                # The label is gone post-preemption; reconstruct the slice
                # id from the naming contract (modules/gcp_tpu.py):
                # slice_id = "<cluster>-<pool>".
                slice_id = f"{rec['name']}-{pool_name}"
                out[slice_id] = {
                    "cluster": rec["name"],
                    "pool": pool_name,
                    "nodes": [n["name"] for n in pool.get("nodes", [])],
                }
        return out
