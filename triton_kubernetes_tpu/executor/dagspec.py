"""Spec-driven DAG loader: a JSON-able topology spec -> a StateDocument.

The chaos harness (``triton_kubernetes_tpu/chaos/``) generates random
module DAGs as *specs* — small structured dicts naming a manager, clusters
by provider family, and their nodes/pools/jobsets — rather than as
documents, so failing scenarios can be shrunk structurally (drop a
cluster, drop a node) and serialized into the regression corpus
(``tests/chaos_corpus/*.json``). This module is the single place a spec
is materialized into the real module configs the engine applies: every
consumer (the generator, corpus replay, CI evidence scripts) builds the
byte-identical document for the same spec.

Topology spec shape (all keys JSON-able)::

    {"manager": {"provider": "bare-metal", "name": "m1"},
     "clusters": [
       {"provider": "aws", "name": "c0", "nodes": ["w0", "w1"]},
       {"provider": "gke", "name": "h0"},
       {"provider": "gcp-tpu", "name": "ml",
        "pools": [{"name": "pool0", "accelerator": "v5e-16"}],
        "jobsets": [{"name": "j0", "pool": "pool0"}]},
     ]}

Provider families (the full driver shape matrix the modules layer ships):

* ``rancher`` — manager-registered clusters with per-VM host modules
  (aws, azure, triton, vsphere, bare-metal, gcp);
* ``hosted`` — provider-managed control planes imported into the manager
  (gke, aks), no host modules;
* ``tpu`` — GKE-TPU clusters whose capacity is slice node pools
  (gcp-tpu), plus optional JobSet workloads pinned to a slice.

Credentials are canned constants: the simulator never validates values,
and constant configs keep generated documents content-addressed (the
parity fingerprints cover the config bytes).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..state import StateDocument

#: provider -> (family, has_manager_module)
PROVIDER_SHAPES: Dict[str, Dict[str, Any]] = {
    "aws": {"family": "rancher", "manager": True},
    "azure": {"family": "rancher", "manager": True},
    "triton": {"family": "rancher", "manager": True},
    "gcp": {"family": "rancher", "manager": True},
    "bare-metal": {"family": "rancher", "manager": True},
    "vsphere": {"family": "rancher", "manager": False},
    "gke": {"family": "hosted", "manager": False},
    "aks": {"family": "hosted", "manager": False},
    "gcp-tpu": {"family": "tpu", "manager": False},
}

MANAGER_PROVIDERS = tuple(sorted(
    p for p, s in PROVIDER_SHAPES.items() if s["manager"]))

# Canned provider credential/config blocks (required variables only).
_CREDS: Dict[str, Dict[str, Any]] = {
    "aws": {"aws_access_key": "AKIA-chaos", "aws_secret_key": "chaos-secret"},
    "azure": {"azure_subscription_id": "sub-chaos",
              "azure_client_id": "client-chaos",
              "azure_client_secret": "secret-chaos",
              "azure_tenant_id": "tenant-chaos"},
    "triton": {"triton_account": "chaos",
               "triton_key_path": "/tmp/chaos_id_rsa",
               "triton_key_id": "aa:bb:cc"},
    "gcp": {"gcp_path_to_credentials": "/tmp/chaos-creds.json",
            "gcp_project_id": "chaos-project"},
    "bare-metal": {},
    "vsphere": {"vsphere_user": "chaos", "vsphere_password": "chaos-pw",
                "vsphere_server": "vc.chaos.local",
                "vsphere_datacenter_name": "dc1",
                "vsphere_datastore_name": "ds1",
                "vsphere_resource_pool_name": "rp1",
                "vsphere_network_name": "net1"},
    "gke": {"gcp_path_to_credentials": "/tmp/chaos-creds.json",
            "gcp_project_id": "chaos-project"},
    "aks": {"azure_subscription_id": "sub-chaos",
            "azure_client_id": "client-chaos",
            "azure_client_secret": "secret-chaos",
            "azure_tenant_id": "tenant-chaos"},
    "gcp-tpu": {"gcp_path_to_credentials": "/tmp/chaos-creds.json",
                "gcp_project_id": "chaos-project"},
}


class DagSpecError(ValueError):
    """The topology spec is malformed (unknown provider, a jobset naming a
    pool the cluster does not declare, a vsphere manager...)."""


def _manager_refs() -> Dict[str, str]:
    return {
        "manager_url": "${module.cluster-manager.manager_url}",
        "manager_access_key": "${module.cluster-manager.manager_access_key}",
        "manager_secret_key": "${module.cluster-manager.manager_secret_key}",
    }


def _host_ip(i: int) -> str:
    return f"192.168.{100 + i // 200}.{10 + i % 200}"


def document_from_spec(topology: Dict[str, Any], name: str,
                       driver: Optional[Dict[str, Any]] = None,
                       backend_name: Optional[str] = None) -> StateDocument:
    """Materialize a topology spec into a StateDocument.

    ``driver`` (fault plan, op_latency, ...) lands as the document's
    driver block; ``backend_name`` points ``terraform.backend`` at the
    in-process memory store (defaults to ``name``).
    """
    doc = StateDocument(name)
    doc.set_backend_config({"memory": {"name": backend_name or name}})
    if driver:
        doc.set("driver", driver)

    mgr = topology.get("manager") or {}
    mprov = mgr.get("provider", "bare-metal")
    shape = PROVIDER_SHAPES.get(mprov)
    if shape is None or not shape["manager"]:
        raise DagSpecError(
            f"provider {mprov!r} has no manager module "
            f"(choices: {list(MANAGER_PROVIDERS)})")
    mcfg: Dict[str, Any] = {
        "source": f"modules/{mprov}-manager",
        "name": mgr.get("name", "m1"),
        **_CREDS[mprov],
    }
    if mprov == "bare-metal":
        mcfg["host"] = "192.168.0.10"
    doc.set_manager(mcfg)

    host_serial = 0
    for cl in topology.get("clusters", []):
        prov = cl.get("provider", "")
        cname = cl.get("name", "")
        shape = PROVIDER_SHAPES.get(prov)
        if shape is None:
            raise DagSpecError(
                f"unknown cluster provider {prov!r} "
                f"(choices: {sorted(PROVIDER_SHAPES)})")
        family = shape["family"]
        if family == "rancher":
            ckey = doc.add_cluster(prov, cname, {
                "source": f"modules/{prov}-k8s", "name": cname,
                **_manager_refs(), **_CREDS[prov],
            })
            for hostname in cl.get("nodes", []):
                host_serial += 1
                hcfg: Dict[str, Any] = {
                    "source": f"modules/{prov}-k8s-host",
                    "hostname": hostname,
                    "rancher_host_labels": {"worker": True},
                    "rancher_cluster_registration_token":
                        f"${{module.{ckey}.registration_token}}",
                    "rancher_cluster_ca_checksum":
                        f"${{module.{ckey}.ca_checksum}}",
                    **_CREDS[prov],
                }
                if prov == "bare-metal":
                    hcfg["host"] = _host_ip(host_serial)
                if prov == "vsphere":
                    hcfg["vsphere_template_name"] = "ubuntu-tpl"
                doc.add_node(ckey, hostname, hcfg)
        elif family == "hosted":
            doc.add_cluster(prov, cname, {
                "source": f"modules/{prov}-k8s", "name": cname,
                "node_count": int(cl.get("node_count", 1)),
                **_manager_refs(), **_CREDS[prov],
            })
        elif family == "tpu":
            ckey = doc.add_cluster(prov, cname, {
                "source": "modules/gcp-tpu-k8s", "name": cname,
                **_manager_refs(), **_CREDS[prov],
            })
            pools = cl.get("pools", [])
            pool_keys: Dict[str, str] = {}
            pool_accels: Dict[str, str] = {}
            for pool in pools:
                pname = pool.get("name", "")
                pool_accels[pname] = pool.get("accelerator", "v5e-16")
                pool_keys[pname] = doc.add_node(ckey, pname, {
                    "source": "modules/gcp-tpu-nodepool",
                    "pool_name": pname,
                    "gke_cluster_name": cname,
                    "cluster_id": f"${{module.{ckey}.cluster_id}}",
                    "tpu_accelerator": pool_accels[pname],
                    "spot": True,
                    **_CREDS["gcp-tpu"],
                })
            for job in cl.get("jobsets", []):
                jname = job.get("name", "")
                pname = job.get("pool", "")
                if pname not in pool_keys:
                    raise DagSpecError(
                        f"jobset {jname!r} names pool {pname!r} which "
                        f"cluster {cname!r} does not declare")
                pkey = pool_keys[pname]
                doc.set(f"module.job_{cname}_{jname}", {
                    "source": "modules/tpu-jobset",
                    "job_name": jname,
                    "cluster_id": f"${{module.{ckey}.cluster_id}}",
                    # The jobset sizes itself (num_workers) from the
                    # accelerator of the slice it is pinned to.
                    "tpu_accelerator": pool_accels[pname],
                    "slice_id": f"${{module.{pkey}.slice_id}}",
                })
    return doc


def tpu_slices(topology: Dict[str, Any]) -> List[Dict[str, str]]:
    """Every TPU slice a topology declares, as
    ``{cluster, pool, slice_id, accelerator}`` rows (slice-id naming
    contract: ``<cluster>-<pool>``, modules/gcp_tpu.py). The accelerator
    rides along so consumers verify repaired ICI labels against the
    pool's REAL topology, not an assumed one."""
    out: List[Dict[str, str]] = []
    for cl in topology.get("clusters", []):
        if PROVIDER_SHAPES.get(cl.get("provider", ""), {}).get("family") \
                != "tpu":
            continue
        for pool in cl.get("pools", []):
            out.append({"cluster": cl["name"], "pool": pool["name"],
                        "slice_id": f"{cl['name']}-{pool['name']}",
                        "accelerator": pool.get("accelerator", "v5e-16")})
    return out
