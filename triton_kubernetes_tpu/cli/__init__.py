"""L5 CLI: ``create | destroy | get | version`` command tree.

Reference analog: ``cmd/`` (cobra root + subcommands, cmd/root.go:14-67,
cmd/create.go:14-96, cmd/destroy.go:15-82, cmd/get.go:15-75,
cmd/version.go:10-26). Run as ``python -m triton_kubernetes_tpu.cli``.
"""

from .main import build_parser, main

__all__ = ["build_parser", "main"]
