"""CLI entrypoint.

Command surface mirrors the reference exactly (cmd/create.go:89-93,
cmd/destroy.go:70, cmd/get.go:62):

    create  {manager|cluster|node|backup}
    destroy {manager|cluster|node}
    get     {manager|cluster}
    version

Global flags: ``--config <yaml>`` (silent-install file), ``--non-interactive``,
``--set k=v`` (highest-precedence override, e.g. ``--set backend_provider=local``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, List, Optional

from .. import __version__
from ..backends import Backend, LocalBackend, ObjectStoreBackend
from ..constants import (KV_DTYPES, MATMUL_DTYPES, OPERATOR_PORT,
                         ROUTE_PORT, WEIGHT_DTYPES)
from ..backends.objectstore import DirObjectStore
from ..backends.base import StateLockedError, StateNotFoundError
from ..backends.gcs import GcsConfigError
from ..config import (
    Config,
    InputResolver,
    InteractivePrompter,
    MissingInputError,
    ValidationError,
)
from ..config.config import parse_scalar
from ..executor import LocalExecutor
from ..executor.engine import ApplyError, OutputError
from ..executor.terraform import TerraformNotFoundError
from ..modules.base import ModuleError
from ..state import ClusterKeyError
from ..utils import configure
from ..workflows import (
    WorkflowContext,
    WorkflowError,
    delete_cluster,
    delete_manager,
    delete_node,
    get_cluster,
    get_manager,
    new_backup,
    new_cluster,
    new_manager,
    new_node,
    repair_node,
    repair_slice,
    restore_backup,
)

GIT_SHA = "dev"  # stamped by packaging (Makefile -ldflags analog, Makefile:2)

# Pinned copy of chaos.generator.PROFILES' keys (equality test-enforced,
# tests/test_chaos.py): argparse choices must not cost an eager import of
# the chaos/runner stack on every CLI start.
CHAOS_PROFILES = ("default", "quick", "soak", "tpu", "workload",
                  "workload-train")


def choose_backend(resolver: InputResolver) -> Backend:
    """Backend selection (util/backend_prompt.go:18-168 analog).

    ``local`` keeps everything under ~/.triton-kubernetes-tpu; ``gcs`` is a
    real GCS bucket (generation-locked, the Manta-backend analog);
    ``objectstore`` (alias ``manta``) is the directory-backed bucket
    emulation for air-gapped use.
    """
    kind = resolver.choose(
        "backend_provider", "Backend Provider",
        [("local", "local"), ("gcs", "gcs"),
         ("objectstore", "objectstore"), ("manta", "objectstore")],
        default="local")
    if kind == "local":
        root = resolver.config.get("backend_root", "~/.triton-kubernetes-tpu")
        return LocalBackend(root)
    if kind == "gcs":
        from ..backends.gcs import GcsObjectStore

        bucket = str(resolver.value(
            "backend_bucket", "GCS bucket",
            validate=lambda v: "bucket names cannot contain '/'"
            if "/" in str(v) else None))
        creds = str(resolver.value(
            "gcp_path_to_credentials", "Path to GCP credentials file",
            default=""))
        store = GcsObjectStore(bucket, credentials_path=creds)
        return ObjectStoreBackend(store, bucket_hint=bucket)
    bucket = resolver.value("backend_bucket", "Object-store bucket/path",
                            default="~/.triton-kubernetes-tpu-bucket")
    return ObjectStoreBackend(DirObjectStore(str(bucket)), bucket_hint=str(bucket))


def choose_executor(resolver: InputResolver, logger):
    """Executor selection via the ``executor:`` config key.

    Like the ``driver:`` key this is never prompted — the default
    (in-process :class:`LocalExecutor`) is always valid. ``executor:
    terraform`` swaps in :class:`TerraformExecutor`, which writes the doc as
    ``main.tf.json`` and shells out to a real ``terraform`` binary — the
    reference's only execution path (shell/run_terraform.go:63-104, called
    from create/manager.go:146). Tuning keys: ``terraform_binary``,
    ``terraform_plugin_dir``, ``terraform_modules_root``.
    """
    cfg = resolver.config
    kind = cfg.get("executor") if cfg.is_set("executor") else "local"
    if kind == "local":
        from ..executor.engine import RetryPolicy

        # Wavefront width (terraform's -parallelism analog, default 10
        # there; 4 here). 1 reproduces the serial apply exactly.
        workers = (int(cfg.get("parallelism"))
                   if cfg.is_set("parallelism") else 4)
        return LocalExecutor(log=logger.info, logger=logger,
                             retry=RetryPolicy.from_config(cfg),
                             parallelism=workers)
    if kind == "terraform":
        from ..executor.terraform import TerraformExecutor

        # The retry/backoff/parallelism knobs belong to the in-process
        # engine; a real terraform run manages its own. Explicitly-set
        # knobs must not be silently inert.
        for knob in ("max_retries", "apply_deadline", "retry_backoff",
                     "parallelism"):
            if cfg.is_set(knob):
                logger.log("warn",
                           f"{knob} has no effect with executor: terraform "
                           "(transient-fault retry is a local-executor "
                           "feature)")
        kwargs = {}
        if cfg.is_set("terraform_binary"):
            kwargs["binary"] = str(cfg.get("terraform_binary"))
        if cfg.is_set("terraform_plugin_dir"):
            kwargs["plugin_dir"] = str(cfg.get("terraform_plugin_dir"))
        if cfg.is_set("terraform_modules_root"):
            kwargs["modules_root"] = str(cfg.get("terraform_modules_root"))
        return TerraformExecutor(**kwargs)
    raise ValidationError(
        f"executor: {kind!r} is not a valid choice "
        f"(valid: ['local', 'terraform'])")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="triton-kubernetes-tpu",
        description="TPU-native multi-cloud Kubernetes cluster manager",
    )
    p.add_argument("--config", metavar="FILE",
                   help="silent-install YAML configuration file")
    p.add_argument("--non-interactive", action="store_true",
                   help="fail instead of prompting for missing inputs")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="KEY=VALUE", help="config override (repeatable)")
    p.add_argument("--json", action="store_true",
                   help="structured JSON-lines log output")
    p.add_argument("--log-level", choices=["debug", "info", "warn", "error"],
                   default="info", help="log verbosity (default: info)")
    p.add_argument("--trace-out", metavar="FILE",
                   help="write every span of this run as Chrome trace-event "
                        "JSON (open in ui.perfetto.dev)")
    p.add_argument("--max-retries", type=int, metavar="N",
                   help="per-module retries for transient apply faults "
                        "(default: 3; config key max_retries)")
    p.add_argument("--apply-deadline", type=float, metavar="SECONDS",
                   help="cap on total retry backoff per module apply "
                        "(default: 120; config key apply_deadline)")
    p.add_argument("--parallelism", type=int, metavar="N",
                   help="max modules applied/destroyed concurrently once "
                        "their dependencies are satisfied (default: 4; "
                        "1 = serial; config key parallelism)")

    sub = p.add_subparsers(dest="command")

    create = sub.add_parser("create", help="create resources")
    create.add_argument("kind", choices=["manager", "cluster", "node", "backup"])

    destroy = sub.add_parser("destroy", help="destroy resources")
    destroy.add_argument("kind", choices=["manager", "cluster", "node"])

    get = sub.add_parser("get", help="display resource information")
    get.add_argument("kind", choices=["manager", "cluster"])

    restore = sub.add_parser("restore", help="restore from a backup")
    restore.add_argument("kind", choices=["backup"])

    repair = sub.add_parser(
        "repair",
        help="replace a dead node or preempted TPU slice (destroy + "
             "re-create, same config); auto-targets the NotReady node / "
             "preempted pool the state reports")
    repair.add_argument("kind", choices=["node", "slice"])

    sub.add_parser(
        "validate",
        help="structurally validate the shipped terraform module tree and "
             "every stored state document (no terraform binary needed)")

    sub.add_parser(
        "metrics",
        help="dump the in-process metrics registry (Prometheus text; "
             "--json for the snapshot)")

    lint = sub.add_parser(
        "lint",
        help="run the repo-native TK8S1xx static invariant checkers "
             "(docs/guide/static-analysis.md); exits 1 on findings")
    lint.add_argument("--format", choices=["human", "json"],
                      default="human", dest="lint_format",
                      help="report format (default: human; json is the "
                           "CI evidence document)")
    lint.add_argument("--root", default=".", metavar="DIR",
                      help="repo root to lint (default: current "
                           "directory)")
    lint.add_argument("--list-rules", action="store_true",
                      help="print the active rule catalog and exit")
    lint.add_argument("paths", nargs="*", metavar="PATH",
                      help="restrict the per-file scan to these "
                           "root-relative files/dirs (cross-file rules "
                           "still read their pinned sites)")

    chaos = sub.add_parser(
        "chaos",
        help="property-based chaos sweep: seeded random module DAGs + "
             "fault plans against cloudsim, checking the pinned "
             "robustness invariants; failing seeds shrink into "
             "tests/chaos_corpus (docs/guide/fault-tolerance.md)")
    chaos.add_argument("--seed", type=int, default=0, metavar="N",
                       help="base seed of the sweep (default: 0; scenario "
                            "i derives its own seed deterministically)")
    chaos.add_argument("--runs", type=int, default=25, metavar="N",
                       help="generated scenarios to run (default: 25)")
    chaos.add_argument("--profile", choices=sorted(CHAOS_PROFILES),
                       default="default",
                       help="generation profile: DAG sizes, provider mix, "
                            "fault density (default: default)")
    chaos.add_argument("--shrink", action="store_true",
                       help="shrink failing seeds to minimal specs and "
                            "write them as corpus entries under "
                            "--corpus-dir")
    chaos.add_argument("--corpus-dir", default=None, metavar="DIR",
                       help="where shrunk counterexamples land (default: "
                            "tests/chaos_corpus; implies nothing unless "
                            "--shrink finds failures)")

    serve = sub.add_parser(
        "serve",
        help="run the TPU-native inference endpoint: continuous batching "
             "over a paged KV cache, HTTP /generate + /metrics + /healthz "
             "(docs/guide/serving.md)")
    serve.add_argument("--model", default="llama-test", metavar="NAME",
                       help="model config name (default: llama-test; see "
                            "models/config.py CONFIGS)")
    serve.add_argument("--serve-host", default="127.0.0.1", metavar="ADDR",
                       help="bind address (default: 127.0.0.1; manifests "
                            "use 0.0.0.0)")
    serve.add_argument("--port", type=int, default=8000, metavar="N",
                       help="bind port (default: 8000; 0 = ephemeral)")
    serve.add_argument("--block-size", type=int, default=16, metavar="N",
                       help="KV-cache page size in tokens (default: 16)")
    serve.add_argument("--num-blocks", type=int, default=256, metavar="N",
                       help="KV-cache pool size in pages, page 0 reserved "
                            "(default: 256)")
    serve.add_argument("--max-batch", type=int, default=8, metavar="N",
                       help="decode slots batched per step (default: 8)")
    serve.add_argument("--max-model-len", type=int, default=None,
                       metavar="N",
                       help="cap on prompt + generated tokens per sequence "
                            "(default: the model's max_seq_len)")
    serve.add_argument("--kv-dtype", default="auto",
                       choices=list(KV_DTYPES), metavar="DTYPE",
                       help="KV-cache page storage: auto = the model "
                            "config's activation dtype, bf16 = force "
                            "bfloat16 pages, int8/fp8 = quantized pages "
                            "(int8 or float8_e4m3fn) with per-page-per-"
                            "head f32 scales — ~4x fewer pool bytes "
                            "than f32 (~2x vs bf16), i.e. that many "
                            "more concurrent sequences per chip; fp8 "
                            "fails loudly where this jax build lacks "
                            "the dtype (docs/guide/serving.md "
                            "§Quantization)")
    serve.add_argument("--weight-dtype", default="auto",
                       choices=list(WEIGHT_DTYPES), metavar="DTYPE",
                       help="decode weight storage: int8/fp8 = per-"
                            "channel symmetric quantization of the big "
                            "matmuls to int8 or float8_e4m3fn (embed/"
                            "norms/router stay full precision; the "
                            "caller's f32 master tree is untouched; fp8 "
                            "fails loudly where this jax build lacks "
                            "the dtype)")
    serve.add_argument("--matmul-dtype", default="auto",
                       choices=list(MATMUL_DTYPES), metavar="DTYPE",
                       help="ARITHMETIC dtype for the big serving "
                            "matmuls (storage is --weight-dtype): f32 = "
                            "dequantize then full-precision einsum (the "
                            "pinned reference), int8/fp8 = contract the "
                            "stored quantized weights directly (low-"
                            "precision dot, f32/int32 accumulate, "
                            "scales folded into the epilogue — requires "
                            "the matching --weight-dtype), auto = "
                            "quantized arithmetic on TPU when weights "
                            "are quantized, bitwise-f32 elsewhere "
                            "(docs/guide/performance.md §Quantized "
                            "arithmetic)")
    serve.add_argument("--sequential", action="store_true",
                       help="serve one request at a time (the continuous-"
                            "batching A/B baseline; scripts/ci/"
                            "serving_evidence.py)")
    serve.add_argument("--prefill-chunk", type=int, default=None,
                       metavar="N",
                       help="chunked prefill: split prompts into N-token "
                            "windows interleaved with decode steps so a "
                            "long prompt cannot stall in-flight decodes; "
                            "must be a multiple of --block-size; 0 = "
                            "legacy whole-prompt prefill at admission "
                            "(default: 256, adapted to --block-size; "
                            "docs/guide/serving.md §Chunked prefill)")
    serve.add_argument("--prefix-cache", dest="prefix_cache",
                       action="store_true", default=None,
                       help="share full-page-aligned prompt prefixes "
                            "across requests via the refcounted radix KV "
                            "index — a common system prompt prefills "
                            "once, not once per user (default: on "
                            "whenever chunked prefill is; requires "
                            "--prefill-chunk > 0; docs/guide/serving.md "
                            "§Prefix caching)")
    serve.add_argument("--no-prefix-cache", dest="prefix_cache",
                       action="store_false",
                       help="disable shared-prefix KV reuse (outputs are "
                            "identical either way — the cache is a pure "
                            "prefill-compute save)")
    serve.add_argument("--spec-k", type=int, default=0, metavar="N",
                       help="speculative self-drafting decode: propose "
                            "up to N tokens per sequence per step from "
                            "an n-gram match over its own prompt + "
                            "generated text and verify all N+1 "
                            "positions in one widened pass — one "
                            "weight/KV read for several tokens on "
                            "repetitive text, with outputs bitwise "
                            "identical to 0 (the default, speculation "
                            "off; docs/guide/serving.md §Speculative "
                            "decoding)")
    serve.add_argument("--seed", type=int, default=0, metavar="N",
                       help="parameter-init seed for the randomly "
                            "initialized model (default: 0)")
    serve.add_argument("--pool", default="colocated",
                       choices=["colocated", "prefill", "decode"],
                       metavar="ROLE",
                       help="disaggregation role label for this replica: "
                            "colocated (default) serves prefill + decode; "
                            "prefill replicas answer the first token and "
                            "hand sessions off, decode replicas import "
                            "migrated KV pages and stream the rest — the "
                            "router drives the handoff, the engine "
                            "behaves identically either way "
                            "(docs/guide/serving.md §Disaggregation)")
    serve.add_argument("--dcn-gbps", type=float, default=0.0,
                       metavar="GBPS",
                       help="simulated datacenter-network bandwidth "
                            "(gigabits/s) charged per outbound migration "
                            "payload — 0 (default) disables the model; "
                            "single-host disaggregation A/Bs otherwise "
                            "ship KV sessions over loopback for free "
                            "(docs/guide/serving.md §Disaggregation)")
    serve.add_argument("--dcn-rtt-ms", type=float, default=0.0,
                       metavar="MS",
                       help="simulated per-transfer round-trip latency "
                            "(milliseconds) added on top of --dcn-gbps "
                            "(default: 0)")
    serve.add_argument("--dcn-jitter-ms", type=float, default=0.0,
                       metavar="MS",
                       help="uniform [0, MS) jitter added per transfer, "
                            "drawn from a generator seeded by --seed so "
                            "runs replay identically (default: 0)")
    serve.add_argument("--trace-jsonl", default=None, metavar="FILE",
                       help="append this replica's request-lifecycle "
                            "spans (admit/prefill/first-token/preempt/"
                            "finish + engine ticks) as trace JSON "
                            "lines — one input of `tk8s trace merge` "
                            "(docs/guide/observability.md §Fleet "
                            "tracing)")

    route = sub.add_parser(
        "route",
        help="run the session-affine router over N serving replicas: "
             "consistent-hash affinity, least-loaded spill, health-aware "
             "ejection (docs/guide/serving.md §Router)")
    route.add_argument("--replica", action="append", required=True,
                       metavar="URL", dest="replicas",
                       help="replica base URL (repeatable), e.g. "
                            "http://10.0.0.7:8000; with --decode-replica "
                            "these become the prefill pool")
    route.add_argument("--decode-replica", action="append", default=[],
                       metavar="URL", dest="decode_replicas",
                       help="decode-pool replica base URL (repeatable); "
                            "any present switches the router to "
                            "disaggregated mode — prompts prefill on a "
                            "--replica, then the session's KV pages "
                            "migrate to a decode replica for the "
                            "remaining tokens (docs/guide/serving.md "
                            "§Disaggregation)")
    route.add_argument("--route-host", default="127.0.0.1", metavar="ADDR",
                       help="bind address (default: 127.0.0.1; manifests "
                            "use 0.0.0.0)")
    route.add_argument("--port", type=int, default=ROUTE_PORT, metavar="N",
                       help=f"bind port (default: {ROUTE_PORT}; "
                            "0 = ephemeral)")
    route.add_argument("--spill-threshold", type=int, default=4,
                       metavar="N",
                       help="router-tracked in-flight requests at the "
                            "affine replica beyond which a request "
                            "spills to the least-loaded healthy replica "
                            "(default: 4)")
    route.add_argument("--virtual-nodes", type=int, default=64,
                       metavar="N",
                       help="consistent-hash ring points per replica — "
                            "more points, smoother key spread (default: "
                            "64)")
    route.add_argument("--health-interval", type=float, default=0.5,
                       metavar="SECONDS",
                       help="background /healthz probe period; a probe "
                            "failure ejects the replica, a later 200 "
                            "re-admits it (default: 0.5)")
    route.add_argument("--request-timeout", type=float, default=120.0,
                       metavar="SECONDS",
                       help="per-attempt timeout for proxied /generate "
                            "calls (default: 120)")
    route.add_argument("--trace-jsonl", default=None, metavar="FILE",
                       help="append every placement decision as a "
                            "route.place span (replica + "
                            "affine/spill/eject reason, trace id) — "
                            "one input of `tk8s trace merge`")
    route.add_argument("--trace-seed", type=int, default=0, metavar="N",
                       help="seed of the router's trace-id minting "
                            "stream: requests arriving without an "
                            "X-TK8S-Trace header get deterministic ids "
                            "(default: 0)")

    operate = sub.add_parser(
        "operate",
        help="run the reconcile operator: a continuous observe->diff->"
             "act loop converging desired state against the cloud, with "
             "an optional metrics-driven TPU autoscaler "
             "(docs/guide/operator.md)")
    operate.add_argument("--interval", type=float, default=10.0,
                         metavar="SECONDS",
                         help="seconds between reconcile ticks "
                              "(default: 10)")
    operate.add_argument("--max-ticks", type=int, default=None,
                         metavar="N",
                         help="stop after N ticks (default: run forever; "
                              "CI and smoke runs bound themselves here)")
    operate.add_argument("--until-converged", action="store_true",
                         help="stop at the first tick that observes no "
                              "drift and acts on nothing (one-shot "
                              "convergence, the `apply`-like mode)")
    operate.add_argument("--scrape", action="append", default=[],
                         metavar="URL", dest="scrape_urls",
                         help="serving-fleet /metrics endpoint to scrape "
                              "each tick (repeatable); the autoscaler is "
                              "blind — and holds — without at least one")
    operate.add_argument("--autoscale-cluster", default=None,
                         metavar="NAME",
                         help="TPU cluster whose slice node pools the "
                              "autoscaler may grow/drain (default: "
                              "reconcile-only, no scaling)")
    operate.add_argument("--ttft-slo", type=float, default=0.5,
                         metavar="SECONDS",
                         help="TTFT p99 SLO the autoscaler defends, "
                              "quantiled over each tick's scrape window "
                              "(default: 0.5)")
    operate.add_argument("--queue-high", type=float, default=8.0,
                         metavar="N",
                         help="fleet queue depth treated as a breach "
                              "(default: 8)")
    operate.add_argument("--queue-low", type=float, default=1.0,
                         metavar="N",
                         help="fleet queue depth treated as calm — "
                              "drain-eligible (default: 1)")
    operate.add_argument("--min-pools", type=int, default=1, metavar="N",
                         help="autoscaler floor on TPU pools (default: 1)")
    operate.add_argument("--max-pools", type=int, default=4, metavar="N",
                         help="autoscaler ceiling on TPU pools "
                              "(default: 4)")
    operate.add_argument("--scale-up-after", type=int, default=2,
                         metavar="TICKS",
                         help="consecutive breached ticks before a grow "
                              "(hysteresis; default: 2)")
    operate.add_argument("--scale-down-after", type=int, default=5,
                         metavar="TICKS",
                         help="consecutive calm ticks before a drain "
                              "(hysteresis; default: 5)")
    operate.add_argument("--cooldown", type=float, default=60.0,
                         metavar="SECONDS",
                         help="hold after any grow/drain so the fleet's "
                              "response is judged, not the action "
                              "(default: 60)")
    operate.add_argument("--rebalance-gap", type=float, default=0.0,
                         metavar="FRACTION",
                         help="KV-pressure spread between the hottest "
                              "and coolest scraped replica beyond which "
                              "the operator live-migrates one session "
                              "per tick from hot to cool (default: 0 = "
                              "rebalancing off; docs/guide/operator.md "
                              "§Rebalance)")
    operate.add_argument("--rebalance-high", type=float, default=0.75,
                         metavar="FRACTION",
                         help="KV-pool utilization the hottest replica "
                              "must exceed before a rebalance fires — "
                              "a cold fleet is never shuffled "
                              "(default: 0.75)")
    operate.add_argument("--operator-host", default="127.0.0.1",
                         metavar="ADDR",
                         help="bind address for the operator's own "
                              "/metrics+/healthz endpoint (default: "
                              "127.0.0.1; manifests use 0.0.0.0)")
    operate.add_argument("--operator-port", type=int, default=None,
                         metavar="N",
                         help=f"port for the operator endpoint "
                              f"(default: no endpoint; manifests use "
                              f"{OPERATOR_PORT}; 0 = ephemeral)")
    operate.add_argument("--train-desired", type=int, default=0,
                         metavar="N",
                         help="train-fleet worker count the operator "
                              "defends (default: 0 = no train fleet; "
                              "with it, the replace/shrink-instead-of-"
                              "wait/regrow rules run each tick — "
                              "docs/guide/operator.md §Train fleet)")
    operate.add_argument("--train-min", type=int, default=1, metavar="N",
                         help="smallest worker count worth an elastic "
                              "restart; below it the policy holds for "
                              "capacity instead of shrinking "
                              "(default: 1)")
    operate.add_argument("--train-status", default=None, metavar="FILE",
                         help="JSON file the operator reads each tick "
                              "for the train fleet's observed state "
                              "({\"running_workers\": N, "
                              "\"capacity_workers\": M, ...}); missing "
                              "or torn = no signal, the policy holds")
    operate.add_argument("--train-regrow-cooldown", type=float,
                         default=60.0, metavar="SECONDS",
                         help="hold between a landed train resize and "
                              "the next regrow; replace/shrink recovery "
                              "is never throttled (default: 60)")
    operate.add_argument("--train-jobset-dir", default=None,
                         metavar="DIR",
                         help="actuate train resizes by rendering the "
                              "resized Job manifest into DIR "
                              "(topology resize_jobset; default: "
                              "decisions journal but nothing actuates)")
    operate.add_argument("--train-jobset-name", default="train",
                         metavar="NAME",
                         help="Job/Service name for --train-jobset-dir "
                              "renders (default: train)")
    operate.add_argument("--train-accelerator", default="v5e-16",
                         metavar="TYPE",
                         help="accelerator of the train slice backing "
                              "--train-jobset-dir renders "
                              "(default: v5e-16)")
    operate.add_argument("--train-image",
                         default="tk8s/jax-tpu-runtime:0.1.0",
                         metavar="IMAGE",
                         help="container image for --train-jobset-dir "
                              "renders (default: the runtime image)")
    operate.add_argument("--journal-out", default=None, metavar="FILE",
                         help="append every reconcile tick's journal "
                              "record as a JSON line (the decision "
                              "audit trail CI evidence reads)")
    operate.add_argument("--trace-jsonl", default=None, metavar="FILE",
                         help="append every reconcile tick and "
                              "autoscale actuation as operator.tick/"
                              "operator.scale spans — one input of "
                              "`tk8s trace merge`, putting operator "
                              "actions on the same timeline as router "
                              "placements and replica engine ticks")

    goodput = sub.add_parser(
        "goodput",
        help="goodput-ledger tooling: `goodput report` reads per-process "
             "trace JSONL files (serve/route/operate/train --trace-jsonl) "
             "and rolls their <source>.goodput segments into per-process "
             "and fleet chip-second attribution — useful vs waste, by "
             "category (docs/guide/observability.md §Goodput ledger)")
    goodput.add_argument("action", choices=["report"])
    goodput.add_argument("inputs", nargs="+", metavar="JSONL",
                         help="per-process trace JSONL files to report "
                              "over (the same files `tk8s trace merge` "
                              "takes)")
    goodput.add_argument("--metrics", action="append", default=[],
                         metavar="FILE", dest="metrics_files",
                         help="Prometheus text scrape (a saved /metrics "
                              "body) to fold in (repeatable): its "
                              "tk8s_goodput_seconds_total samples are "
                              "reported alongside the trace-derived "
                              "ledger for cross-checking the two sinks")

    tracecmd = sub.add_parser(
        "trace",
        help="fleet-trace tooling: `trace merge` aligns the per-process "
             "trace JSONL files (serve/route/operate --trace-jsonl) "
             "through their clock anchors and writes ONE Perfetto "
             "timeline (docs/guide/observability.md §Fleet tracing)")
    tracecmd.add_argument("action", choices=["merge"])
    tracecmd.add_argument("inputs", nargs="+", metavar="JSONL",
                          help="per-process trace JSONL files to merge")
    tracecmd.add_argument("--out", "-o", default="fleet-trace.json",
                          metavar="FILE",
                          help="merged Chrome/Perfetto trace output "
                               "(default: fleet-trace.json; open in "
                               "ui.perfetto.dev)")

    sub.add_parser("version", help="print version")
    return p


def _sigterm_runs_finally() -> None:
    """Long-running verbs (serve/route/operate) install this before
    blocking: SIGTERM — how Kubernetes stops a pod — becomes
    SystemExit(143) so the verb's ``finally`` runs and buffered trace
    JSONL reaches disk. Without it the default handler kills the
    process mid-buffer and a terminated pod's trace file holds only
    its meta anchor."""
    import signal

    def _exit(signum: int, frame: Any) -> None:
        raise SystemExit(143)

    try:
        signal.signal(signal.SIGTERM, _exit)
    except ValueError:
        # Not the main thread (embedded callers drive main() from
        # worker threads in tests): the caller owns signal handling.
        pass


def main(argv: Optional[List[str]] = None,
         prompter=None, backend: Optional[Backend] = None,
         executor=None) -> int:
    args = build_parser().parse_args(argv)

    if args.command == "version":
        # cmd/version.go format: "<semver> (<git sha>)"
        print(f"{__version__} ({GIT_SHA})")
        return 0

    if args.command is None:
        build_parser().print_help()
        return 1

    trace = None
    if args.trace_out:
        from ..utils.trace import TraceCollector

        trace = TraceCollector()
    logger = configure(json_mode=args.json, level=args.log_level,
                       trace=trace)

    if args.command == "metrics":
        # The full catalog (docs/guide/observability.md), zero-valued
        # families included, from this process's default registry.
        from ..utils import metrics as m

        reg = m.get_registry()
        reg.register_catalog()
        if args.json:
            print(json.dumps(reg.snapshot(), indent=2, sort_keys=True))
        else:
            print(reg.render_prometheus(), end="")
        if trace is not None:
            # Honor the global contract (a file always lands) even though
            # this command opens no spans.
            trace.write(args.trace_out)
        return 0

    if args.command == "lint":
        # Pure stdlib-ast tree walk: needs no backend, no config, no jax.
        from ..analysis import RULES, lint_project, render_human, render_json

        if args.list_rules:
            for r in sorted(RULES, key=lambda r: r.code):
                print(f"{r.code}  {r.name}: {r.summary}")
            if trace is not None:
                # Honor the global contract: a --trace-out file always
                # lands, even from a command that opens no spans.
                trace.write(args.trace_out)
            return 0
        findings, stats = lint_project(args.root, paths=args.paths or None)
        if args.lint_format == "json":
            print(render_json(findings, stats))
        else:
            print(render_human(findings, stats))
        if trace is not None:
            trace.write(args.trace_out)
        return 1 if findings else 0

    if args.command == "trace":
        # Pure JSON alignment work: no backend, no config, no jax.
        from ..utils.trace import (
            TraceMergeError,
            merge_trace_files,
            validate_chrome_trace,
        )

        try:
            doc = merge_trace_files(args.inputs)
        except (TraceMergeError, OSError) as e:
            logger.error(str(e), kind=type(e).__name__)
            return 1
        problems = validate_chrome_trace(doc)
        if problems:  # merge emitted something malformed: a bug, loudly
            for problem in problems:
                logger.error(problem, kind="TraceValidation")
            return 1
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        spans = sum(1 for e in doc["traceEvents"] if e["ph"] != "M")
        print(f"merged {len(args.inputs)} trace files -> {args.out} "
              f"({spans} spans; open in ui.perfetto.dev)")
        if trace is not None:
            trace.write(args.trace_out)
        return 0

    if args.command == "goodput":
        # Pure JSON ledger work: no backend, no config, no jax — the
        # report runs where the trace files landed, accelerator or not.
        from ..utils.trace import (
            TraceMergeError,
            summarize_goodput,
            validate_goodput_trace,
        )

        try:
            problems = validate_goodput_trace(args.inputs)
            report = summarize_goodput(args.inputs)
        except (TraceMergeError, OSError) as e:
            logger.error(str(e), kind=type(e).__name__)
            return 1
        if problems:
            # A ledger that fails the partition oracle is lying about
            # chip time: report it loudly, not as a rollup.
            for problem in problems:
                logger.error(problem, kind="GoodputValidation")
            return 1
        if args.metrics_files:
            from ..utils.metrics import PrometheusParseError, parse_prometheus

            scraped: dict = {}
            try:
                for path in args.metrics_files:
                    with open(path, encoding="utf-8") as f:
                        fams = parse_prometheus(f.read())
                    fam = fams.get("tk8s_goodput_seconds_total")
                    for s in (fam or {}).get("series", []):
                        labels = s.get("labels", {})
                        key = (labels.get("source", "?"),
                               labels.get("category", "?"))
                        scraped[key] = (scraped.get(key, 0.0)
                                        + float(s.get("value", 0.0)))
            except (PrometheusParseError, OSError) as e:
                logger.error(str(e), kind=type(e).__name__)
                return 1
            report["scraped_seconds"] = {
                s: {c: round(v, 9)
                    for (src, c), v in sorted(scraped.items()) if src == s}
                for s in sorted({src for src, _ in scraped})}
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            fleet = report["fleet"]
            for proc in report["processes"]:
                cats = " ".join(f"{c}={v:.3f}s"
                                for c, v in proc["seconds"].items())
                print(f"{proc['path']} [{proc['role']}] "
                      f"source={proc['source']} wall={proc['wall_s']:.3f}s "
                      f"useful={proc['useful_fraction']:.1%} "
                      f"waste={proc['waste_fraction']:.1%}  {cats}")
            waste = " ".join(f"{c}={v:.3f}s" for c, v in
                             fleet["waste_by_category"].items()) or "none"
            print(f"fleet: accounted={fleet['accounted_s']:.3f} chip-s, "
                  f"useful={fleet['useful_fraction']:.1%}, "
                  f"waste={fleet['waste_fraction']:.1%} ({waste})")
            for src, cats in report.get("scraped_seconds", {}).items():
                pairs = " ".join(f"{c}={v:.3f}s" for c, v in cats.items())
                print(f"scraped[{src}]: {pairs}")
        if trace is not None:
            trace.write(args.trace_out)
        return 0

    if args.command == "chaos":
        # Pure cloudsim work: needs no backend choice, no config, no jax.
        from ..chaos import CORPUS_DIR, run_sweep

        corpus_dir = args.corpus_dir if args.corpus_dir is not None \
            else CORPUS_DIR
        report = run_sweep(
            seed=args.seed, runs=args.runs, profile=args.profile,
            shrink=args.shrink,
            corpus_dir=corpus_dir if args.shrink else None,
            log=lambda m: logger.info(m))
        if args.json:
            print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
        else:
            print(f"chaos sweep: {report.passed}/{report.runs} scenarios "
                  f"passed (profile={args.profile}, seed={args.seed}, "
                  f"simulated {report.simulated_seconds:.1f}s)")
            for r in report.results:
                print(f"  seed {r.spec['seed']}: violated "
                      + ", ".join(sorted({v['invariant']
                                          for v in r.violations})))
            for path in report.corpus_written:
                print(f"  corpus entry written: {path}")
        if trace is not None:
            trace.write(args.trace_out)
        return 1 if report.failed else 0

    if args.command == "serve":
        # Workload-stack imports stay lazy: the provisioning verbs must
        # keep working on machines without jax (pyproject's split).
        import jax as _jax

        from ..models import get_config, init_params
        from ..serve import ServeEngine, ServeHTTPServer
        from ..utils import metrics as _metrics

        try:
            model_config = get_config(args.model)
        except KeyError as e:
            logger.error(str(e), kind="KeyError")
            return 1
        _metrics.get_registry().register_catalog()
        logger.info("initializing model", model=args.model,
                    backend=_jax.default_backend())
        if args.prefill_chunk is None:
            # The default adapts to the block size; an EXPLICIT value is
            # validated strictly below — a silently rewritten chunk size
            # is a benchmark run measuring something the operator did
            # not ask for.
            prefill_chunk = max(args.block_size, 256 - 256 % args.block_size)
        elif args.prefill_chunk < 0:
            # Only 0 is the legacy sentinel; a negative value is a typo
            # that would otherwise silently benchmark the wrong engine.
            logger.error(
                f"--prefill-chunk must be >= 0, got {args.prefill_chunk}",
                kind="ValueError")
            return 2
        else:
            prefill_chunk = args.prefill_chunk or None
        if prefill_chunk is not None and (
                prefill_chunk % args.block_size != 0):
            logger.error(
                f"--prefill-chunk {prefill_chunk} is not a multiple of "
                f"--block-size {args.block_size}", kind="ValueError")
            return 2
        if args.prefix_cache and prefill_chunk is None:
            logger.error(
                "--prefix-cache requires chunked prefill: prefix reuse "
                "skips whole chunk windows (set --prefill-chunk > 0)",
                kind="ValueError")
            return 2
        prefix_cache = (prefill_chunk is not None
                        if args.prefix_cache is None
                        else args.prefix_cache)
        if args.spec_k < 0:
            logger.error(
                f"--spec-k must be >= 0, got {args.spec_k}",
                kind="ValueError")
            return 2
        engine = ServeEngine(
            init_params(model_config, _jax.random.PRNGKey(args.seed)),
            model_config,
            block_size=args.block_size, num_blocks=args.num_blocks,
            max_batch=args.max_batch, max_model_len=args.max_model_len,
            sequential=args.sequential,
            kv_dtype=args.kv_dtype, weight_dtype=args.weight_dtype,
            matmul_dtype=args.matmul_dtype,
            prefill_chunk=prefill_chunk,
            prefix_cache=prefix_cache, spec_k=args.spec_k)
        dcn = None
        if args.dcn_gbps or args.dcn_rtt_ms or args.dcn_jitter_ms:
            from ..serve.server import DcnTransferModel

            dcn = DcnTransferModel(
                bytes_per_s=args.dcn_gbps * 1e9 / 8,
                rtt_s=args.dcn_rtt_ms / 1e3,
                jitter_s=args.dcn_jitter_ms / 1e3, seed=args.seed)
        server = ServeHTTPServer(engine, host=args.serve_host,
                                 port=args.port, dcn=dcn)
        host, port = server.address
        if args.trace_jsonl:
            from ..utils.trace import GoodputRecorder, TraceWriter

            # The served engine always has a bounded flight recorder
            # (ServeHTTPServer attaches one); the writer upgrades it to
            # spill every lifecycle event to disk for `trace merge`.
            engine.flight.writer = TraceWriter(
                args.trace_jsonl, role=f"replica:{host}:{port}")
            # The goodput ledger rides the same writer: every engine
            # tick books its compute into serve.goodput segments that
            # tile this replica's wall window (and tick the
            # tk8s_goodput_seconds_total counter the operator scrapes).
            engine.goodput = GoodputRecorder(
                "serve", clock=engine.clock, writer=engine.flight.writer)
        logger.info("serving", url=f"http://{host}:{port}",
                    model=args.model, block_size=args.block_size,
                    num_blocks=args.num_blocks, max_batch=args.max_batch,
                    kv_dtype=args.kv_dtype, weight_dtype=args.weight_dtype,
                    matmul_dtype=args.matmul_dtype,
                    prefill_chunk=prefill_chunk,
                    prefix_cache=prefix_cache, spec_k=args.spec_k,
                    pool=args.pool)
        print(f"serving {args.model} on http://{host}:{port} "
              f"(POST /generate, GET /metrics, GET /healthz, "
              f"pool={args.pool})", flush=True)
        _sigterm_runs_finally()
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            print("\nstopped", file=sys.stderr)
        finally:
            if engine.goodput is not None:
                # Close the ledger BEFORE the writer: the final segment
                # is what makes the categories tile the wall window.
                engine.goodput.close()
            if engine.flight is not None and engine.flight.writer is not None:
                engine.flight.writer.close()
            if trace is not None:
                trace.write(args.trace_out)
        return 0

    if args.command == "route":
        # The router is jax-free on purpose: it speaks HTTP to replicas
        # and runs fine on a machine with no accelerator stack at all.
        from ..serve.router import RouterHTTPServer
        from ..utils import metrics as _metrics

        _metrics.get_registry().register_catalog()
        route_writer = None
        route_goodput = None
        if args.trace_jsonl:
            from ..utils.trace import GoodputRecorder, TraceWriter

            route_writer = TraceWriter(args.trace_jsonl, role="router")
            route_goodput = GoodputRecorder("route", writer=route_writer)
        try:
            router = RouterHTTPServer(
                args.replicas, host=args.route_host, port=args.port,
                health_interval_s=args.health_interval,
                spill_threshold=args.spill_threshold,
                virtual_nodes=args.virtual_nodes,
                request_timeout_s=args.request_timeout,
                trace_seed=args.trace_seed,
                trace=route_writer,
                decode_urls=args.decode_replicas or None)
        except ValueError as e:
            logger.error(str(e), kind="ValueError")
            return 2
        if route_goodput is not None:
            # Handler threads overlap: the router books forward time
            # through the recorder's depth-counted enter/exit edges.
            router.router.goodput = route_goodput
        host, port = router.address
        logger.info("routing", url=f"http://{host}:{port}",
                    replicas=len(args.replicas),
                    decode_replicas=len(args.decode_replicas),
                    spill_threshold=args.spill_threshold)
        print(f"routing {len(args.replicas)} replicas on "
              f"http://{host}:{port} (POST /generate, GET /metrics, "
              f"GET /healthz, GET /stats)", flush=True)
        _sigterm_runs_finally()
        try:
            router.serve_forever()
        except KeyboardInterrupt:
            print("\nstopped", file=sys.stderr)
        finally:
            if route_goodput is not None:
                route_goodput.close()
            if route_writer is not None:
                route_writer.close()
            if trace is not None:
                trace.write(args.trace_out)
        return 0

    config = Config(config_file=args.config)
    for item in args.overrides:
        key, sep, value = item.partition("=")
        if not sep:
            print(f"error: --set expects KEY=VALUE, got {item!r}", file=sys.stderr)
            return 2
        # Same scalar coercion as YAML/env values, so --set confirm=false
        # really is False (a raw "false" string would be truthy).
        config.set(key, parse_scalar(value))
    # Dedicated flags outrank --set only by being later: both land in the
    # overrides layer, so the usual precedence story holds.
    if args.max_retries is not None:
        config.set("max_retries", args.max_retries)
    if args.apply_deadline is not None:
        config.set("apply_deadline", args.apply_deadline)
    if args.parallelism is not None:
        if args.parallelism < 1:
            print(f"error: --parallelism must be >= 1, got "
                  f"{args.parallelism}", file=sys.stderr)
            return 2
        config.set("parallelism", args.parallelism)

    if prompter is None:
        prompter = InteractivePrompter()
    resolver = InputResolver(config, prompter, args.non_interactive)

    try:
        from ..catalogs import make_catalog

        if args.command == "validate":
            from ..executor.terraform import default_modules_root
            from ..executor.tf_validate import (validate_document,
                                                validate_modules_tree)

            root = (str(config.get("terraform_modules_root"))
                    if config.is_set("terraform_modules_root")
                    else default_modules_root())
            if os.path.isdir(root):
                problems = validate_modules_tree(root)
            else:
                # A missing tree is an error, not vacuously clean — a
                # typo'd terraform_modules_root must not print OK.
                problems = {root: ["modules root does not exist"]}
            be = backend if backend is not None else choose_backend(resolver)
            for name in be.states():
                errs = validate_document(be.state(name), modules_root=root)
                if errs:
                    problems[f"state:{name}"] = errs
            if problems:
                for target, errs in sorted(problems.items()):
                    for e in errs:
                        logger.error(e, target=target)
                return 1
            print("validated: module tree and all state documents OK")
            return 0

        be = backend if backend is not None else choose_backend(resolver)
        ex = executor if executor is not None else choose_executor(
            resolver, logger)
        ctx = WorkflowContext(backend=be, executor=ex, resolver=resolver,
                              catalog=make_catalog(config))

        if args.command == "operate":
            from ..operator import (
                Autoscaler,
                AutoscalerConfig,
                OperatorError,
                OperatorHTTPServer,
                Reconciler,
                http_rebalancer,
            )
            from ..utils import metrics as _metrics
            from ..workflows.common import select_manager

            _metrics.get_registry().register_catalog()
            manager = select_manager(ctx)
            autoscaler = None
            if args.autoscale_cluster:
                try:
                    autoscaler = Autoscaler(AutoscalerConfig(
                        ttft_slo_p99_s=args.ttft_slo,
                        queue_high=args.queue_high,
                        queue_low=args.queue_low,
                        min_pools=args.min_pools,
                        max_pools=args.max_pools,
                        scale_up_after=args.scale_up_after,
                        scale_down_after=args.scale_down_after,
                        cooldown_s=args.cooldown))
                except ValueError as e:
                    logger.error(str(e), kind="ValueError")
                    return 2
            operate_writer = None
            if args.trace_jsonl:
                from ..utils.trace import TraceWriter

                operate_writer = TraceWriter(args.trace_jsonl,
                                             role="operator")
            rebalancer = None
            if args.rebalance_gap > 0:
                if not args.scrape_urls:
                    logger.error(
                        "--rebalance-gap needs at least one --scrape: "
                        "KV pressure is read from the serving fleet's "
                        "/metrics", kind="ValueError")
                    return 2
                rebalancer = http_rebalancer(list(args.scrape_urls))
            train_policy = None
            train_status = None
            train_actuator = None
            if args.train_desired > 0:
                from ..operator import (
                    TrainFleetConfig, TrainFleetPolicy, file_train_status,
                    jobset_actuator)

                if not args.train_status:
                    logger.error(
                        "--train-desired needs --train-status: the "
                        "policy is blind without the train fleet's "
                        "observed state", kind="ValueError")
                    return 2
                train_policy = TrainFleetPolicy(TrainFleetConfig(
                    desired_workers=args.train_desired,
                    min_workers=args.train_min,
                    regrow_cooldown_s=args.train_regrow_cooldown,
                    serve_queue_high=args.queue_high,
                    ttft_slo_p99_s=args.ttft_slo))
                train_status = file_train_status(args.train_status)
                if args.train_jobset_dir:
                    from ..topology.slices import SliceSpec

                    train_actuator = jobset_actuator(
                        args.train_jobset_dir, args.train_jobset_name,
                        SliceSpec.from_accelerator(args.train_accelerator),
                        args.train_image,
                        ["python", "-m", "triton_kubernetes_tpu.train",
                         "--resume", "--elastic"])
            reconciler = Reconciler(
                be, ex, manager,
                autoscaler=autoscaler,
                autoscale_cluster=args.autoscale_cluster,
                metrics_sources=list(args.scrape_urls),
                interval_s=args.interval,
                journal_path=args.journal_out,
                trace=operate_writer,
                rebalancer=rebalancer,
                rebalance_gap=args.rebalance_gap,
                rebalance_high=args.rebalance_high,
                train_policy=train_policy,
                train_status=train_status,
                train_actuator=train_actuator,
                log=logger.info)
            server = None
            if args.operator_port is not None:
                server = OperatorHTTPServer(
                    reconciler, host=args.operator_host,
                    port=args.operator_port).start()
                # Heartbeat liveness: a tick completed recently (on the
                # loop's own monotonic clock). A wedged observe/apply
                # stops the heartbeat and /healthz flips 503, which is
                # what the rendered Deployment's liveness probe
                # restarts — without this a stuck loop would answer
                # 200 forever while the fleet drifts. The staleness
                # budget covers the worst HEALTHY tick: every scrape
                # timing out sequentially (the blind-fleet case the
                # autoscaler is designed to hold through) must not read
                # as a dead loop. A first tick that never completes
                # counts stale too (measured from startup).
                import time as _time

                stale_after = (max(60.0, 5 * args.interval)
                               + len(args.scrape_urls)
                               * reconciler.watcher.timeout_s)
                started_at = _time.monotonic()
                server.set_liveness(
                    lambda: _time.monotonic()
                    - (reconciler.last_tick_at
                       if reconciler.last_tick_at is not None
                       else started_at) < stale_after)
                host, port = server.address
                logger.info("operator endpoint",
                            url=f"http://{host}:{port}")
            logger.info("operating", manager=manager,
                        autoscale_cluster=args.autoscale_cluster or "",
                        interval_s=args.interval,
                        scrapes=len(args.scrape_urls))
            _sigterm_runs_finally()
            try:
                ticks = reconciler.run(
                    max_ticks=args.max_ticks,
                    until_converged=args.until_converged)
                print(f"operate: stopped after {ticks} ticks "
                      f"(converged={reconciler.converged})")
            except KeyboardInterrupt:
                print("\nstopped", file=sys.stderr)
            except OperatorError as e:
                logger.error(str(e), kind="OperatorError")
                return 1
            finally:
                if operate_writer is not None:
                    operate_writer.close()
                if server is not None:
                    server.close()
            return 0

        if args.command == "create":
            result = {"manager": new_manager, "cluster": new_cluster,
                      "node": new_node, "backup": new_backup}[args.kind](ctx)
            if result:
                print(f"created: {result}")
        elif args.command == "destroy":
            result = {"manager": delete_manager, "cluster": delete_cluster,
                      "node": delete_node}[args.kind](ctx)
            if result:
                print(f"destroyed: {result}")
        elif args.command == "get":
            outputs = {"manager": get_manager, "cluster": get_cluster}[args.kind](ctx)
            print(json.dumps(outputs, indent=2, sort_keys=True))
        elif args.command == "restore":
            result = restore_backup(ctx)
            if result:
                print(f"restored: {result}")
        elif args.command == "repair":
            result = {"node": repair_node, "slice": repair_slice}[args.kind](ctx)
            if result:
                print(f"repaired: {result}")
    except (WorkflowError, MissingInputError, ValidationError,
            ClusterKeyError, ApplyError, OutputError, ModuleError,
            StateLockedError, StateNotFoundError, TerraformNotFoundError,
            GcsConfigError, EOFError) as e:
        logger.error(str(e), kind=type(e).__name__)
        return 1
    except KeyboardInterrupt:
        print("\naborted", file=sys.stderr)
        return 130
    finally:
        # Written even when the command failed: the trace of a crashed
        # apply is the one the operator most wants to open in Perfetto.
        if trace is not None:
            trace.write(args.trace_out)
            logger.info("trace written", file=args.trace_out,
                        spans=len(trace.events()))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
