"""Image build pipeline (reference packer/ analog, TPU-era).

The reference bakes VM images from YAML templates converted to packer JSON by
``packer/packer-config`` (~100-LoC Python with ``!include`` support). The TPU
rebuild's images are **containers** — the jax/libtpu runtime image that the
device DaemonSet and workload JobSets run — so the pipeline converts the same
style of YAML (+ ``!include``) into a container build config and renders a
Dockerfile.
"""

from .pipeline import ImageConfigError, load_template, render_dockerfile

__all__ = ["ImageConfigError", "load_template", "render_dockerfile"]
