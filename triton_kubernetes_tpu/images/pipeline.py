"""YAML → container build config converter.

Reference analog: packer/packer-config (yaml→json with ``!include``
support). Same contract, new target: instead of packer builder JSON this
emits a dict with ``image``/``base``/``packages``/``pip``/``env``/
``entrypoint`` and can render it as a Dockerfile.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import yaml


class ImageConfigError(ValueError):
    pass


class _IncludeLoader(yaml.SafeLoader):
    """SafeLoader + ``!include other.yaml`` resolved relative to the
    including file (packer-config's !include semantics)."""


def _include(loader: _IncludeLoader, node: yaml.Node) -> Any:
    rel = loader.construct_scalar(node)
    base = os.path.dirname(getattr(loader, "_filename", "."))
    path = os.path.join(base, rel)
    if not os.path.isfile(path):
        raise ImageConfigError(f"!include target not found: {path}")
    return _load_file(path)


_IncludeLoader.add_constructor("!include", _include)


def _load_file(path: str) -> Any:
    with open(path) as f:
        loader = _IncludeLoader(f)
        loader._filename = path
        try:
            return loader.get_single_data()
        finally:
            loader.dispose()


_REQUIRED = ("image", "base")


def load_template(path: str) -> Dict[str, Any]:
    """Load + validate one image template. ``variables:`` (possibly included)
    are substituted into string values as ``{{name}}``."""
    data = _load_file(path)
    if not isinstance(data, dict):
        raise ImageConfigError(f"{path}: template must be a mapping")
    variables = data.pop("variables", {}) or {}
    if not isinstance(variables, dict):
        raise ImageConfigError(f"{path}: variables must be a mapping")

    def subst(v: Any) -> Any:
        if isinstance(v, str):
            for k, val in variables.items():
                v = v.replace("{{%s}}" % k, str(val))
            return v
        if isinstance(v, list):
            return [subst(x) for x in v]
        if isinstance(v, dict):
            return {k: subst(x) for k, x in v.items()}
        return v

    data = subst(data)
    for key in _REQUIRED:
        if key not in data:
            raise ImageConfigError(f"{path}: missing required key {key!r}")
    data.setdefault("packages", [])
    data.setdefault("pip", [])
    data.setdefault("env", {})
    data.setdefault("entrypoint", [])
    return data


def render_dockerfile(config: Dict[str, Any]) -> str:
    lines = [f"FROM {config['base']}"]
    if config["packages"]:
        pkgs = " ".join(config["packages"])
        lines.append(
            "RUN apt-get update && apt-get install -y --no-install-recommends "
            f"{pkgs} && rm -rf /var/lib/apt/lists/*")
    if config["pip"]:
        lines.append("RUN pip install --no-cache-dir " +
                     " ".join(f"'{p}'" for p in config["pip"]))
    for k, v in config["env"].items():
        lines.append(f"ENV {k}={v}")
    for script in config.get("scripts", []):
        lines.append(f"COPY {script} /tmp/build/")
        lines.append(f"RUN sh /tmp/build/{os.path.basename(script)}")
    if config["entrypoint"]:
        lines.append("ENTRYPOINT " + json.dumps(config["entrypoint"]))
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:  # pragma: no cover - thin script shell
    import argparse

    p = argparse.ArgumentParser(
        description="convert an image YAML template to build JSON/Dockerfile")
    p.add_argument("template")
    p.add_argument("--dockerfile", action="store_true",
                   help="emit a Dockerfile instead of JSON")
    args = p.parse_args(argv)
    cfg = load_template(args.template)
    print(render_dockerfile(cfg) if args.dockerfile
          else json.dumps(cfg, indent=2, sort_keys=True))
    return 0
