"""Shared step-timing harness for bench.py and the sweep scripts.

One implementation of the measurement subtleties so every number that
might get baked into bench.py is produced the same way:

- host-scalar sync: on the tunneled axon backend ``block_until_ready``
  can return before the computation finishes; only a device->host fetch
  (``float(metrics["loss"])``) is a reliable barrier;
- two-point timing: (t_long - t_short) cancels the fixed dispatch+fetch
  overhead of the tunnel (up to ~0.5 s per window);
- pipelined execution: each window runs through
  :func:`..train.pipeline.run_pipelined` — steps dispatch back to back
  with zero per-step host syncs, exactly like the production loop being
  measured, and the run feeds the ``tk8s_train_*`` metric families so a
  bench number comes with its step-duration histogram attached.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Tuple


@dataclass(frozen=True)
class ThroughputReport:
    """One timed measurement, in the units the scale-out story is told
    in: ``tokens_per_sec`` is the AGGREGATE rate (``tokens_per_step``
    counts the *global* batch, so under a DCN data-parallel mesh the
    number already sums over every process), ``steps_per_sec`` the
    global-step rate, and ``n_processes`` records how many
    ``jax.distributed`` processes produced it — the context a bare
    tokens/s is meaningless without."""

    steps_per_sec: float
    tokens_per_sec: float
    loss: float
    n_processes: int
    steps_timed: int        # n_long - n_short (the two-point window)
    window_seconds: float   # t_long - t_short


def measure_throughput(step, state, batches: List[Dict[str, Any]],
                       tokens_per_step: int, warmup: int,
                       n_short: int, n_long: int,
                       sync_every: int = 0,
                       config_name: str = "",
                       on_window=None,
                       ) -> Tuple[ThroughputReport, Any]:
    """Two-point timed measurement through the pipelined loop; returns
    ``(ThroughputReport, final_state)``. ``n_long`` must exceed
    ``n_short`` (the timed window is their difference). ``sync_every``
    sets the host-sync cadence inside each window; 0 syncs once at the
    window end (the historical behavior — the whole window is in
    flight). ``on_window(name, steps, seconds)`` fires as each window
    completes (warmup/short/long) — bench.py's partial-progress markers,
    so a measurement killed mid-run still reports the windows it
    finished."""
    import jax

    from .pipeline import run_pipelined

    if n_long <= n_short:
        raise ValueError(
            f"n_long ({n_long}) must exceed n_short ({n_short})")

    def run(name, n):
        nonlocal state
        t0 = time.perf_counter()
        loss = float("nan")
        if n:
            state, report = run_pipelined(
                step, state, list(batches), max_steps=n,
                sync_every=sync_every or n,
                tokens_per_step=tokens_per_step, config_name=config_name)
            loss = report.losses[-1]  # fetched at the window's sync point
        dt = time.perf_counter() - t0
        if on_window is not None and n:
            on_window(name, n, dt)
        return dt, loss

    run("warmup", warmup)
    t_short, _ = run("short", n_short)
    t_long, loss = run("long", n_long)
    dt = max(t_long - t_short, 1e-9)
    steps = n_long - n_short
    return ThroughputReport(
        steps_per_sec=steps / dt,
        tokens_per_sec=tokens_per_step * steps / dt,
        loss=loss,
        n_processes=jax.process_count(),
        steps_timed=steps,
        window_seconds=dt,
    ), state


def measure_tokens_per_sec(step, state, batches: List[Dict[str, Any]],
                           tokens_per_step: int, warmup: int,
                           n_short: int, n_long: int,
                           sync_every: int = 0,
                           config_name: str = "",
                           on_window=None,
                           ) -> Tuple[float, float, Any]:
    """Historical surface: ``(tokens/sec, last loss, final state)`` —
    see :func:`measure_throughput` for the full report (steps/s,
    process count) the multi-host harness reads."""
    report, state = measure_throughput(
        step, state, batches, tokens_per_step, warmup, n_short, n_long,
        sync_every=sync_every, config_name=config_name,
        on_window=on_window)
    return report.tokens_per_sec, report.loss, state
