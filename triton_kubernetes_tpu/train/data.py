"""Data pipeline for the bundled trainer.

Two sources, both yielding ``{"tokens": [B, S+1] int32}`` host batches that
the trainer shards over (data, fsdp):

- ``synthetic_batches`` — deterministic structured sequences (an order-2
  Markov walk over the vocab). Structured rather than uniform noise so
  "loss decreases" is a meaningful test/bench signal: a real model can
  learn the transition table, uniform noise it cannot.
- ``PackedDataset`` — zero-copy np.memmap over a flat binary token file
  (the MaxText-style pretokenized format): fixed-length windows, no Python
  per-token work, so host input never gates the device step.

Either source can be wrapped in :class:`DevicePrefetch`, the
double-buffered host->device staging layer of the pipelined training loop
(train/pipeline.py): a background thread assembles host batches while
``jax.device_put`` keeps the next sharded batch's transfer in flight
under the current step, so the loop's input wait is ~0 whenever the
producer keeps up (measured, not assumed: ``wait_seconds`` feeds the
``tk8s_train_prefetch_wait_seconds`` gauge).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator

import numpy as np


def synthetic_batches(
    vocab_size: int,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Order-2 Markov sequences: next = (a*prev + b*prev2 + noise) % V."""
    rng = np.random.default_rng(seed)
    a, b = 31, 17
    while True:
        out = np.empty((batch_size, seq_len + 1), dtype=np.int32)
        prev = rng.integers(0, vocab_size, size=batch_size)
        prev2 = rng.integers(0, vocab_size, size=batch_size)
        for t in range(seq_len + 1):
            noise = rng.integers(0, 4, size=batch_size)
            cur = (a * prev + b * prev2 + noise) % vocab_size
            out[:, t] = cur
            prev2, prev = prev, cur
        yield {"tokens": out}


class PackedDataset:
    """Flat binary token file (little-endian int32 or uint16) → windows."""

    def __init__(self, path: str, seq_len: int, dtype: str = "int32"):
        self.seq_len = seq_len
        self.tokens = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        if len(self.tokens) < seq_len + 1:
            raise ValueError(
                f"{path}: {len(self.tokens)} tokens < window {seq_len + 1}")

    def __len__(self) -> int:
        return (len(self.tokens) - 1) // self.seq_len

    def batches(
        self, batch_size: int, seed: int = 0, shuffle: bool = True,
    ) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self)
        rng = np.random.default_rng(seed)
        while True:
            order = rng.permutation(n) if shuffle else np.arange(n)
            for i in range(0, n - batch_size + 1, batch_size):
                idx = order[i:i + batch_size]
                rows = [
                    np.asarray(
                        self.tokens[j * self.seq_len:
                                    j * self.seq_len + self.seq_len + 1],
                        dtype=np.int32)
                    for j in idx
                ]
                yield {"tokens": np.stack(rows)}


def write_packed(path: str, tokens: np.ndarray, dtype: str = "int32") -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.asarray(tokens, dtype=np.dtype(dtype)).tofile(path)


# --------------------------------------------------------------------------
# Sharded pipeline: native C++ fast path + exactly-mirrored Python fallback.
# --------------------------------------------------------------------------

_M64 = (1 << 64) - 1
_GOLD = 0x9E3779B97F4A7C15


def _xorshift64star(state: int):
    """One step of xorshift64*; returns (new_state, output). MUST stay in
    lockstep with native/data_pipeline.cpp:xorshift64star."""
    state ^= state >> 12
    state ^= (state << 25) & _M64
    state ^= state >> 27
    return state, (state * 0x2545F4914F6CDD1D) & _M64


def epoch_order(n: int, seed: int, epoch: int) -> np.ndarray:
    """The deterministic per-epoch sequence order shared by the native and
    Python pipelines: Fisher-Yates driven by xorshift64* seeded with
    seed ^ epoch*GOLD (native/data_pipeline.cpp:Pipeline::reshuffle)."""
    order = list(range(n))
    s = (seed ^ ((epoch * _GOLD) & _M64)) & _M64
    if s == 0:
        s = _GOLD
    for i in range(n - 1, 0, -1):
        s, r = _xorshift64star(s)
        j = r % (i + 1)
        order[i], order[j] = order[j], order[i]
    return np.asarray(order, dtype=np.int64)


def _find_native_lib() -> str | None:
    cand = os.environ.get("TK8S_NATIVE_LIB")
    if cand and os.path.isfile(cand):
        return cand
    here = os.path.dirname(os.path.abspath(__file__))
    cand = os.path.join(here, "..", "..", "native", "libtkdata.so")
    return cand if os.path.isfile(cand) else None


class ShardedTokenPipeline:
    """Batches from a directory of ``*.bin`` int32 token shards.

    Uses the native C++ pipeline (``native/libtkdata.so``: shard indexing,
    epoch shuffle, batch assembly, background prefetch) when the library is
    present; otherwise a pure-Python implementation with bit-identical
    output. ``native=True`` requires the library, ``native=False`` forces
    the fallback, ``None`` auto-detects.

    ``next()`` returns ``(tokens[batch, seq_len+1] int32, epoch)`` where
    epoch is the epoch the batch *started* in.
    """

    def __init__(self, directory: str, batch_size: int, seq_len: int,
                 seed: int = 0, native: bool | None = None):
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.seed = seed
        self._handle = None
        self._lib = None

        lib_path = _find_native_lib() if native is not False else None
        if native is True and lib_path is None:
            raise RuntimeError(
                "native pipeline requested but native/libtkdata.so not "
                "built (run `make native`)")
        if lib_path is not None:
            self._open_native(lib_path, directory)
        else:
            self._open_python(directory)

    # -------------------------------------------------------------- native
    def _open_native(self, lib_path: str, directory: str) -> None:
        import ctypes

        lib = ctypes.CDLL(lib_path)
        lib.dp_open.restype = ctypes.c_void_p
        lib.dp_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_uint64]
        lib.dp_next.restype = ctypes.c_int
        lib.dp_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_int32)]
        lib.dp_num_sequences.restype = ctypes.c_long
        lib.dp_num_sequences.argtypes = [ctypes.c_void_p]
        lib.dp_close.argtypes = [ctypes.c_void_p]
        lib.dp_error.restype = ctypes.c_char_p

        handle = lib.dp_open(directory.encode(), self.batch_size,
                             self.seq_len, self.seed)
        if not handle:
            raise ValueError(lib.dp_error().decode() or
                             f"dp_open failed for {directory}")
        self._lib = lib
        self._handle = handle
        self._n = int(lib.dp_num_sequences(handle))
        self.native = True

    # -------------------------------------------------------------- python
    def _open_python(self, directory: str) -> None:
        width = self.seq_len + 1
        self._shards = []
        self._index = []  # (shard_i, offset)
        try:
            names = sorted(os.listdir(directory))
        except OSError as e:
            raise ValueError(f"cannot read directory: {directory}") from e
        for name in names:
            if not name.endswith(".bin"):
                continue
            toks = np.memmap(os.path.join(directory, name),
                             dtype=np.int32, mode="r")
            shard_i = len(self._shards)
            for k in range(len(toks) // width):
                self._index.append((shard_i, k * width))
            self._shards.append(toks)
        if not self._index:
            raise ValueError(
                "no sequences found (need *.bin shards each >= "
                "(seq_len+1)*4 bytes)")
        self._n = len(self._index)
        self._epoch = 0
        self._order = epoch_order(self._n, self.seed, 0)
        self._cursor = 0
        self.native = False

    def __len__(self) -> int:
        return self._n

    def next(self):
        width = self.seq_len + 1
        if self._handle is not None:
            out = np.empty((self.batch_size, width), dtype=np.int32)
            import ctypes

            epoch = self._lib.dp_next(
                self._handle,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return out, epoch
        out = np.empty((self.batch_size, width), dtype=np.int32)
        batch_epoch = self._epoch
        for b in range(self.batch_size):
            if self._cursor >= self._n:
                self._epoch += 1
                self._order = epoch_order(self._n, self.seed, self._epoch)
                self._cursor = 0
            shard_i, off = self._index[int(self._order[self._cursor])]
            self._cursor += 1
            out[b] = self._shards[shard_i][off:off + width]
        return out, batch_epoch

    def batches(self) -> Iterator[Dict[str, np.ndarray]]:
        """Trainer-compatible iterator view (drops the epoch tag)."""
        while True:
            tokens, _ = self.next()
            yield {"tokens": tokens}

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dp_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        # tk8s-lint: disable=TK8S106(GC-time close: the interpreter may
        # be tearing down, raising here would mask the real exit path)
        except Exception:
            pass


# --------------------------------------------------------------------------
# Device prefetch: the input half of the step-pipelined training loop.
# --------------------------------------------------------------------------

class _Drained:
    """Queue sentinel: the producer finished the source."""


class _ProducerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchProducerError(RuntimeError):
    """The prefetch producer died mid-stream. Raised on the consumer side
    with the producer's original exception chained (``raise ... from``),
    so the real cause — the generator frame that blew up, possibly on a
    background thread — stays visible in the traceback instead of being
    reduced to a bare re-raise at the queue boundary."""


class DevicePrefetch:
    """Double-buffered host->device prefetch over a host-batch iterator.

    Two overlaps, both ahead of the device step that will consume them:

    1. **Host batch assembly** — a daemon thread drains ``source`` into a
       bounded queue (``buffer_size`` deep), so Python-side batch work
       (Markov generation, memmap gathers) runs during device compute.
    2. **Host->device transfer** — each dequeued batch is staged with
       ``jax.device_put`` (against ``sharding`` when given) as soon as a
       buffer slot frees up. ``device_put`` is asynchronous, so the DMA
       of batch i+1 rides under step i; by the time the loop asks for the
       next batch its buffers are already resident.

    The iterator yields whatever structure ``source`` yields (dicts of
    arrays), with every leaf placed on device. Finite sources terminate
    the iterator normally (StopIteration) — short-epoch runs just end
    early instead of crashing the loop.

    Measurement: ``wait_seconds`` accumulates the time ``__next__`` spent
    blocked on the producer (the loop's only input stall); ``last_wait``
    holds the most recent one. The pipelined loop mirrors ``wait_seconds``
    into the ``tk8s_train_prefetch_wait_seconds`` gauge at each sync.
    ``threaded=False`` runs the producer inline (deterministic tests,
    single-threaded embedders) — staging still happens one batch ahead.
    """

    def __init__(self, source: Iterable[Any], sharding=None,
                 buffer_size: int = 2, threaded: bool = True,
                 clock: Callable[[], float] = time.perf_counter,
                 place: Callable[[Any], Any] | None = None):
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        if place is not None and sharding is not None:
            raise ValueError("pass either sharding or place, not both")
        self.sharding = sharding
        # Custom staging hook: multi-process feeds swap the plain sharded
        # device_put for parallel.multihost.make_batch_placer, which
        # slices out this process's rows and forms the global jax.Array
        # from process-local data — same double-buffered overlap, but no
        # host ever transfers rows it does not own.
        self._place_fn = place
        self.buffer_size = buffer_size
        self.threaded = threaded
        self.wait_seconds = 0.0
        self.last_wait = 0.0
        self.batches_out = 0
        self._clock = clock
        self._source = iter(source)
        self._staged: list = []  # device-put batches, oldest first
        self._exhausted = False
        self._pending_error: BaseException | None = None
        if threaded:
            self._queue: queue.Queue = queue.Queue(maxsize=buffer_size)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._produce, name="tk8s-prefetch", daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ producer
    def _produce(self) -> None:
        try:
            for item in self._source:
                if self._stop.is_set():
                    return
                self._queue.put(item)
            self._queue.put(_Drained)
        except BaseException as e:  # surfaced on the consumer side
            self._queue.put(_ProducerError(e))

    def _next_host(self):
        """One host batch from the producer, or _Drained; blocks (timed).
        Producer failures surface as :class:`PrefetchProducerError` with
        the original exception as ``__cause__``."""
        if not self.threaded:
            try:
                return next(self._source, _Drained)
            except Exception as e:
                raise PrefetchProducerError(
                    f"prefetch producer failed after {self.batches_out} "
                    f"batches: {e}") from e
        item = self._queue.get()
        if isinstance(item, _ProducerError):
            self._exhausted = True
            if isinstance(item.exc, Exception):
                raise PrefetchProducerError(
                    f"prefetch producer failed after {self.batches_out} "
                    f"batches: {item.exc}") from item.exc
            raise item.exc  # KeyboardInterrupt etc: pass through unwrapped
        return item

    # ------------------------------------------------------------ consumer
    def _place(self, batch):
        import jax

        if self._place_fn is not None:
            return self._place_fn(batch)
        if self.sharding is None:
            return jax.tree.map(jax.device_put, batch)
        return jax.tree.map(
            lambda leaf: jax.device_put(leaf, self.sharding), batch)

    def _fill(self, block: bool) -> None:
        """Stage batches until the buffer is full. Only an *empty* buffer
        under ``block`` is allowed to wait on the producer (and that wait
        is the measured input stall); top-ups are opportunistic."""
        while not self._exhausted and len(self._staged) < self.buffer_size:
            must_wait = block and not self._staged
            if self.threaded and not must_wait and self._queue.empty():
                return  # opportunistic top-up only; never block here
            t0 = self._clock()
            try:
                item = self._next_host()
            except BaseException as e:
                if not self._staged:
                    raise
                # Hand out the batches produced before the failure first;
                # re-raise once the buffer drains.
                self._pending_error = e
                self._exhausted = True
                return
            wait = self._clock() - t0
            if must_wait:
                self.last_wait = wait
                self.wait_seconds += wait
            if item is _Drained:
                self._exhausted = True
                return
            self._staged.append(self._place(item))

    def __iter__(self):
        return self

    def __next__(self):
        self._fill(block=True)
        if not self._staged:
            self.close()
            if self._pending_error is not None:
                e, self._pending_error = self._pending_error, None
                raise e
            raise StopIteration
        out = self._staged.pop(0)
        self.batches_out += 1
        self._fill(block=False)  # start the next transfer before returning
        return out

    def close(self) -> None:
        self._exhausted = True
        if self.threaded:
            self._stop.set()
            # Unblock a producer parked on a full queue.
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
