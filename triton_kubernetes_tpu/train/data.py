"""Data pipeline for the bundled trainer.

Two sources, both yielding ``{"tokens": [B, S+1] int32}`` host batches that
the trainer shards over (data, fsdp):

- ``synthetic_batches`` — deterministic structured sequences (an order-2
  Markov walk over the vocab). Structured rather than uniform noise so
  "loss decreases" is a meaningful test/bench signal: a real model can
  learn the transition table, uniform noise it cannot.
- ``PackedDataset`` — zero-copy np.memmap over a flat binary token file
  (the MaxText-style pretokenized format): fixed-length windows, no Python
  per-token work, so host input never gates the device step.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator

import numpy as np


def synthetic_batches(
    vocab_size: int,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Order-2 Markov sequences: next = (a*prev + b*prev2 + noise) % V."""
    rng = np.random.default_rng(seed)
    a, b = 31, 17
    while True:
        out = np.empty((batch_size, seq_len + 1), dtype=np.int32)
        prev = rng.integers(0, vocab_size, size=batch_size)
        prev2 = rng.integers(0, vocab_size, size=batch_size)
        for t in range(seq_len + 1):
            noise = rng.integers(0, 4, size=batch_size)
            cur = (a * prev + b * prev2 + noise) % vocab_size
            out[:, t] = cur
            prev2, prev = prev, cur
        yield {"tokens": out}


class PackedDataset:
    """Flat binary token file (little-endian int32 or uint16) → windows."""

    def __init__(self, path: str, seq_len: int, dtype: str = "int32"):
        self.seq_len = seq_len
        self.tokens = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        if len(self.tokens) < seq_len + 1:
            raise ValueError(
                f"{path}: {len(self.tokens)} tokens < window {seq_len + 1}")

    def __len__(self) -> int:
        return (len(self.tokens) - 1) // self.seq_len

    def batches(
        self, batch_size: int, seed: int = 0, shuffle: bool = True,
    ) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self)
        rng = np.random.default_rng(seed)
        while True:
            order = rng.permutation(n) if shuffle else np.arange(n)
            for i in range(0, n - batch_size + 1, batch_size):
                idx = order[i:i + batch_size]
                rows = [
                    np.asarray(
                        self.tokens[j * self.seq_len:
                                    j * self.seq_len + self.seq_len + 1],
                        dtype=np.int32)
                    for j in idx
                ]
                yield {"tokens": np.stack(rows)}


def write_packed(path: str, tokens: np.ndarray, dtype: str = "int32") -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.asarray(tokens, dtype=np.dtype(dtype)).tofile(path)


# --------------------------------------------------------------------------
# Sharded pipeline: native C++ fast path + exactly-mirrored Python fallback.
# --------------------------------------------------------------------------

_M64 = (1 << 64) - 1
_GOLD = 0x9E3779B97F4A7C15


def _xorshift64star(state: int):
    """One step of xorshift64*; returns (new_state, output). MUST stay in
    lockstep with native/data_pipeline.cpp:xorshift64star."""
    state ^= state >> 12
    state ^= (state << 25) & _M64
    state ^= state >> 27
    return state, (state * 0x2545F4914F6CDD1D) & _M64


def epoch_order(n: int, seed: int, epoch: int) -> np.ndarray:
    """The deterministic per-epoch sequence order shared by the native and
    Python pipelines: Fisher-Yates driven by xorshift64* seeded with
    seed ^ epoch*GOLD (native/data_pipeline.cpp:Pipeline::reshuffle)."""
    order = list(range(n))
    s = (seed ^ ((epoch * _GOLD) & _M64)) & _M64
    if s == 0:
        s = _GOLD
    for i in range(n - 1, 0, -1):
        s, r = _xorshift64star(s)
        j = r % (i + 1)
        order[i], order[j] = order[j], order[i]
    return np.asarray(order, dtype=np.int64)


def _find_native_lib() -> str | None:
    cand = os.environ.get("TK8S_NATIVE_LIB")
    if cand and os.path.isfile(cand):
        return cand
    here = os.path.dirname(os.path.abspath(__file__))
    cand = os.path.join(here, "..", "..", "native", "libtkdata.so")
    return cand if os.path.isfile(cand) else None


class ShardedTokenPipeline:
    """Batches from a directory of ``*.bin`` int32 token shards.

    Uses the native C++ pipeline (``native/libtkdata.so``: shard indexing,
    epoch shuffle, batch assembly, background prefetch) when the library is
    present; otherwise a pure-Python implementation with bit-identical
    output. ``native=True`` requires the library, ``native=False`` forces
    the fallback, ``None`` auto-detects.

    ``next()`` returns ``(tokens[batch, seq_len+1] int32, epoch)`` where
    epoch is the epoch the batch *started* in.
    """

    def __init__(self, directory: str, batch_size: int, seq_len: int,
                 seed: int = 0, native: bool | None = None):
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.seed = seed
        self._handle = None
        self._lib = None

        lib_path = _find_native_lib() if native is not False else None
        if native is True and lib_path is None:
            raise RuntimeError(
                "native pipeline requested but native/libtkdata.so not "
                "built (run `make native`)")
        if lib_path is not None:
            self._open_native(lib_path, directory)
        else:
            self._open_python(directory)

    # -------------------------------------------------------------- native
    def _open_native(self, lib_path: str, directory: str) -> None:
        import ctypes

        lib = ctypes.CDLL(lib_path)
        lib.dp_open.restype = ctypes.c_void_p
        lib.dp_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_uint64]
        lib.dp_next.restype = ctypes.c_int
        lib.dp_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_int32)]
        lib.dp_num_sequences.restype = ctypes.c_long
        lib.dp_num_sequences.argtypes = [ctypes.c_void_p]
        lib.dp_close.argtypes = [ctypes.c_void_p]
        lib.dp_error.restype = ctypes.c_char_p

        handle = lib.dp_open(directory.encode(), self.batch_size,
                             self.seq_len, self.seed)
        if not handle:
            raise ValueError(lib.dp_error().decode() or
                             f"dp_open failed for {directory}")
        self._lib = lib
        self._handle = handle
        self._n = int(lib.dp_num_sequences(handle))
        self.native = True

    # -------------------------------------------------------------- python
    def _open_python(self, directory: str) -> None:
        width = self.seq_len + 1
        self._shards = []
        self._index = []  # (shard_i, offset)
        try:
            names = sorted(os.listdir(directory))
        except OSError as e:
            raise ValueError(f"cannot read directory: {directory}") from e
        for name in names:
            if not name.endswith(".bin"):
                continue
            toks = np.memmap(os.path.join(directory, name),
                             dtype=np.int32, mode="r")
            shard_i = len(self._shards)
            for k in range(len(toks) // width):
                self._index.append((shard_i, k * width))
            self._shards.append(toks)
        if not self._index:
            raise ValueError(
                "no sequences found (need *.bin shards each >= "
                "(seq_len+1)*4 bytes)")
        self._n = len(self._index)
        self._epoch = 0
        self._order = epoch_order(self._n, self.seed, 0)
        self._cursor = 0
        self.native = False

    def __len__(self) -> int:
        return self._n

    def next(self):
        width = self.seq_len + 1
        if self._handle is not None:
            out = np.empty((self.batch_size, width), dtype=np.int32)
            import ctypes

            epoch = self._lib.dp_next(
                self._handle,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return out, epoch
        out = np.empty((self.batch_size, width), dtype=np.int32)
        batch_epoch = self._epoch
        for b in range(self.batch_size):
            if self._cursor >= self._n:
                self._epoch += 1
                self._order = epoch_order(self._n, self.seed, self._epoch)
                self._cursor = 0
            shard_i, off = self._index[int(self._order[self._cursor])]
            self._cursor += 1
            out[b] = self._shards[shard_i][off:off + width]
        return out, batch_epoch

    def batches(self) -> Iterator[Dict[str, np.ndarray]]:
        """Trainer-compatible iterator view (drops the epoch tag)."""
        while True:
            tokens, _ = self.next()
            yield {"tokens": tokens}

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dp_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
