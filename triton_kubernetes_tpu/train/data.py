"""Data pipeline for the bundled trainer.

Two sources, both yielding ``{"tokens": [B, S+1] int32}`` host batches that
the trainer shards over (data, fsdp):

- ``synthetic_batches`` — deterministic structured sequences (an order-2
  Markov walk over the vocab). Structured rather than uniform noise so
  "loss decreases" is a meaningful test/bench signal: a real model can
  learn the transition table, uniform noise it cannot.
- ``PackedDataset`` — zero-copy np.memmap over a flat binary token file
  (the MaxText-style pretokenized format): fixed-length windows, no Python
  per-token work, so host input never gates the device step.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator

import numpy as np


def synthetic_batches(
    vocab_size: int,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Order-2 Markov sequences: next = (a*prev + b*prev2 + noise) % V."""
    rng = np.random.default_rng(seed)
    a, b = 31, 17
    while True:
        out = np.empty((batch_size, seq_len + 1), dtype=np.int32)
        prev = rng.integers(0, vocab_size, size=batch_size)
        prev2 = rng.integers(0, vocab_size, size=batch_size)
        for t in range(seq_len + 1):
            noise = rng.integers(0, 4, size=batch_size)
            cur = (a * prev + b * prev2 + noise) % vocab_size
            out[:, t] = cur
            prev2, prev = prev, cur
        yield {"tokens": out}


class PackedDataset:
    """Flat binary token file (little-endian int32 or uint16) → windows."""

    def __init__(self, path: str, seq_len: int, dtype: str = "int32"):
        self.seq_len = seq_len
        self.tokens = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        if len(self.tokens) < seq_len + 1:
            raise ValueError(
                f"{path}: {len(self.tokens)} tokens < window {seq_len + 1}")

    def __len__(self) -> int:
        return (len(self.tokens) - 1) // self.seq_len

    def batches(
        self, batch_size: int, seed: int = 0, shuffle: bool = True,
    ) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self)
        rng = np.random.default_rng(seed)
        while True:
            order = rng.permutation(n) if shuffle else np.arange(n)
            for i in range(0, n - batch_size + 1, batch_size):
                idx = order[i:i + batch_size]
                rows = [
                    np.asarray(
                        self.tokens[j * self.seq_len:
                                    j * self.seq_len + self.seq_len + 1],
                        dtype=np.int32)
                    for j in idx
                ]
                yield {"tokens": np.stack(rows)}


def write_packed(path: str, tokens: np.ndarray, dtype: str = "int32") -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.asarray(tokens, dtype=np.dtype(dtype)).tofile(path)
