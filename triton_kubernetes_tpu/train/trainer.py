"""Sharded train step: one jitted SPMD program per mesh.

The parallelism recipe is the scaling-book one: annotate param/activation
shardings (via the logical-axis rules), ``jit`` the whole step, and let XLA
insert the collectives — psum for data-parallel grads, all-gathers for FSDP
params, all-to-alls for MoE dispatch, ppermutes inside ring attention. No
hand-written communication outside ``ops/ring_attention.py``.

State layout notes:
- master params f32, sharded per ``models.logical_axes`` (FSDP shards the
  embed dim; TP shards heads/mlp/vocab).
- optimizer moments inherit param shardings automatically: they are created
  by ``zeros_like`` inside the jitted init, so XLA propagates the
  constraint. ZeRO comes for free this way.
- the step is donated: params/moments update in place in HBM.
"""

from __future__ import annotations

import os
import time
import warnings
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama
from ..models.config import ModelConfig
from ..ops.attention import auto_attention
from ..parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQ, AXIS_TENSOR
from ..parallel.sharding import DEFAULT_RULES, spec_tree_from_logical
from .pipeline import pipeline_degree, pipeline_forward


def _resolve_attention(attention_fn, mesh: Mesh, config=None):
    """None -> the best kernel for the mesh: ring attention when the seq
    axis is sharded, the Pallas flash kernel on multi-device TPU meshes,
    dense einsum otherwise. ``config.attention`` overrides the heuristic
    ("flash" forces the Pallas kernel — interpret-mode off TPU — and
    "dense" forces the einsum); a sharded seq axis still takes ring
    attention, which IS the blockwise flash recurrence.

    On a multi-device mesh the pallas call must be wrapped in shard_map —
    GSPMD cannot partition a Mosaic custom-call, so an unwrapped kernel
    would silently all-gather q/k/v and run replicated per chip. Attention
    is independent across batch and heads, so the per-shard view over
    (data+fsdp batch, tensor heads) is exact. Under the pipeline, the stage
    map is a *partial-manual* shard_map over ``stage`` only, so the kernel
    shard_map is built against the ambient mesh with disjoint manual axes
    and nests inside it (train/pipeline.py) — pp no longer forfeits the
    kernel.
    """
    if attention_fn is not None:
        return attention_fn
    mode = getattr(config, "attention", "auto") if config is not None \
        else "auto"
    if mode == "dense":
        # Forced einsum baseline, honored on EVERY mesh — including a
        # sharded seq axis, where GSPMD partitions the einsum correctly
        # (via all-gathers; slow is the point of a baseline arm).
        return None
    pp = pipeline_degree(mesh) > 1
    if mesh.shape[AXIS_SEQ] > 1:
        # Sequence-sharded: ring attention IS the flash path (blockwise
        # online-softmax over rotating KV blocks) and is exact. Head/batch
        # dims that the tensor/data axes don't divide stay unsharded in the
        # ring spec (replicated there, still seq-sharded) instead of
        # crashing the shard_map. NOTE: the auto ring assumes standard
        # broadcast positions (every batch row identical) — callers with
        # per-row positions (packed sequences) must pass their own fn.
        from ..ops.ring_attention import make_ring_attention

        dp = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
        tensor = mesh.shape[AXIS_TENSOR]
        cache: Dict[Tuple[bool, bool], Any] = {}

        def ring_attn(q, k, v, positions):
            use_batch = dp > 1 and q.shape[0] % dp == 0
            use_head = (tensor > 1 and q.shape[2] % tensor == 0
                        and k.shape[2] % tensor == 0)
            ring = cache.get((use_batch, use_head))
            if ring is None:
                ring = make_ring_attention(
                    mesh,
                    batch_axes=(AXIS_DATA, AXIS_FSDP) if use_batch else (),
                    head_axis=AXIS_TENSOR if use_head else None,
                    nested=pp)
                cache[(use_batch, use_head)] = ring
            # Inside the stage map the body must be axis-index-free; the
            # positions operand carries what the axis index would compute.
            return ring(q, k, v, positions if pp else None)

        ring_attn.forfeits = []  # ring IS the kernel path; nothing forfeited
        return ring_attn
    platform = mesh.devices.flat[0].platform
    if mode in ("flash", "flash-interpret"):
        flash = llama.resolve_attention(config, platform)
    else:
        # "auto" dispatches through the trainer-global auto_attention on
        # purpose (not llama.resolve_attention): tests and the flagship
        # AOT harness monkeypatch trainer.auto_attention to substitute
        # the interpret-mode kernel, and the heuristic is trainer-owned.
        flash = auto_attention(platform)
    if flash is None or mesh.size == 1:
        return flash
    spec = P((AXIS_DATA, AXIS_FSDP), None, AXIS_TENSOR, None)
    sm_kwargs: Dict[str, Any] = dict(
        in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    if pp:
        sm_kwargs["axis_names"] = {AXIS_DATA, AXIS_FSDP, AXIS_TENSOR}
    else:
        sm_kwargs["mesh"] = mesh
    from ..utils.jaxcompat import shard_map as _shard_map

    kernel = _shard_map(
        lambda q, k, v: flash(q, k, v, None), **sm_kwargs)
    tensor = mesh.shape[AXIS_TENSOR]

    def attn(q, k, v, positions):
        # The per-shard view is exact only when the tensor axis divides
        # every head count. GQA kv heads with hkv < tensor (llama3's hkv=4
        # on a tensor=8 mesh — exactly the large-mesh configs where the
        # kernel matters) are repeated up to `tensor` first: each shard then
        # holds the one kv head its q-head group reads, the kernel's GQA
        # grouping handles the (hq/tensor):1 ratio, and repeat's transpose
        # group-sums dk/dv exactly. Remaining misfits fall back to the
        # dense einsum — loudly, because the ~2x step-time cost would
        # otherwise look like a mystery regression (round-3 verdict).
        hq, hkv = q.shape[2], k.shape[2]
        if hq % tensor == 0 and hkv % tensor != 0 and tensor % hkv == 0:
            reps = tensor // hkv
            k = jnp.repeat(k, reps, axis=2)
            v = jnp.repeat(v, reps, axis=2)
        if q.shape[2] % tensor or k.shape[2] % tensor:
            reason = (f"attention falls back to the dense einsum: head "
                      f"counts (hq={hq}, hkv={hkv}) are not divisible by "
                      f"the tensor axis ({tensor}) and kv heads cannot be "
                      f"repeated to cover it; expect ~2x attention cost")
            attn.forfeits.append(reason)
            warnings.warn(reason, stacklevel=2)
            return llama._dense_attention(q, k, v, positions)
        return kernel(q, k, v)

    # Trace-time record of every kernel forfeit, for bench/telemetry.
    attn.forfeits = []
    return attn


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any


def make_optimizer(
    learning_rate: float = 3e-4,
    warmup_steps: int = 100,
    decay_steps: int = 10_000,
    weight_decay: float = 0.1,
    max_grad_norm: float = 1.0,
    b1: float = 0.9,
    b2: float = 0.95,
) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=learning_rate,
        warmup_steps=warmup_steps, decay_steps=max(decay_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def batch_spec() -> P:
    return P((AXIS_DATA, AXIS_FSDP), None)


def param_shardings(mesh: Mesh, config: ModelConfig, rules=None):
    specs = spec_tree_from_logical(
        llama.logical_axes(config), rules or DEFAULT_RULES, mesh=mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def _constrain_params(params, mesh: Mesh, config: ModelConfig, rules=None):
    shardings = param_shardings(mesh, config, rules)
    return jax.tree.map(jax.lax.with_sharding_constraint, params, shardings)


def _constrain_opt_state(opt_state, optimizer, mesh, config, rules=None):
    """Pin optimizer moments to their params' shardings (ZeRO): XLA does not
    reliably propagate constraints through optimizer.init's zeros_like."""
    shardings = param_shardings(mesh, config, rules)
    return optax.tree_map_params(
        optimizer,
        lambda leaf, sh: jax.lax.with_sharding_constraint(leaf, sh),
        opt_state,
        shardings,
    )


def init_state(
    config: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    key: Optional[jax.Array] = None,
    rules=None,
) -> TrainState:
    """Jit-compiled sharded init: params materialize directly in their
    target layout (no host-side full copy — required for 70B-class)."""
    key = key if key is not None else jax.random.PRNGKey(0)

    def init_fn(k):
        params = llama.init_params(config, k)
        params = _constrain_params(params, mesh, config, rules)
        opt_state = optimizer.init(params)
        opt_state = _constrain_opt_state(opt_state, optimizer, mesh, config, rules)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state)

    return jax.jit(init_fn)(key)


def loss_fn(
    params,
    tokens: jnp.ndarray,  # [B, S+1]
    config: ModelConfig,
    attention_fn=None,
    num_stages: int = 1,
    microbatches: int = 1,
    mesh: Optional[Mesh] = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    if num_stages > 1:
        logits, aux = pipeline_forward(
            params, inputs, config, num_stages, microbatches,
            attention_fn=attention_fn, mesh=mesh)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    elif config.fused_ce:
        from ..ops.fused_ce import fused_cross_entropy

        hidden, aux = llama.forward_hidden(params, inputs, config,
                                           attention_fn=attention_fn)
        hidden = llama.final_norm_hidden(hidden, params, config)
        b, s, d = hidden.shape
        ce = fused_cross_entropy(
            hidden.reshape(b * s, d), llama.head_weights(params, config),
            targets.reshape(-1), config.ce_chunk)
    else:
        logits, aux = llama.forward(params, inputs, config,
                                    attention_fn=attention_fn)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    ce = ce.mean()
    total = ce + config.aux_loss_weight * aux
    return total, {"loss": ce, "aux_loss": aux}


def make_train_step(
    config: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    attention_fn=None,
    rules=None,
    microbatches: int = 0,
    precision=None,
) -> Callable[[TrainState, Dict[str, jnp.ndarray]], Tuple[TrainState, Dict]]:
    """Returns jitted (state, batch) -> (state, metrics); donates state.

    On a mesh with ``stage`` > 1 the forward runs the GPipe schedule in
    ``train.pipeline``; ``microbatches`` defaults to the stage count (set it
    higher to shrink the pipeline bubble). ``precision`` names a
    :mod:`.precision` policy ("f32"/"bf16"; None/"auto" keeps the
    config's own dtypes) applied to the config before the step is built —
    the state from ``init_state`` must have been built against the same
    policy-applied config.
    """
    from .precision import apply_policy

    config = apply_policy(config, precision)
    b_sharding = NamedSharding(mesh, batch_spec())
    num_stages = pipeline_degree(mesh)
    attention_fn = _resolve_attention(attention_fn, mesh, config)
    microbatches = microbatches or num_stages

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
        tokens = jax.lax.with_sharding_constraint(batch["tokens"], b_sharding)
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (_, metrics), grads = grad_fn(
            state.params, tokens, config, attention_fn,
            num_stages, microbatches, mesh)
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_params = _constrain_params(new_params, mesh, config, rules)
        new_opt = _constrain_opt_state(new_opt, optimizer, mesh, config, rules)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt), metrics

    # tk8s: donate-safe(state is device-owned — built by jitted init or
    # an orbax restore, never a zero-copy device_put of host numpy — and
    # every caller rebinds the returned TrainState, so the donated
    # buffers are dead after the step)
    return jax.jit(step, donate_argnums=(0,))


@dataclass(frozen=True)
class CompileTimings:
    """Where the pre-step wall clock went, so a slow start (or a bench
    timeout) is attributable: tracing/lowering vs XLA compilation. On a
    warm persistent compilation cache ``compile_seconds`` collapses to
    ~0 while ``lower_seconds`` (pure tracing) stays."""

    lower_seconds: float
    compile_seconds: float
    cache_dir: Optional[str]

    @property
    def total_seconds(self) -> float:
        return self.lower_seconds + self.compile_seconds


def enable_compile_cache(cache_dir: str) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir`` (created
    if missing) and drop the size/time floors so every entry persists —
    the bench child is short-lived, so a second attempt or a second round
    must be able to reuse the first's XLA output. Returns the directory,
    or None when this jax build has no persistent cache (the knobs are
    best-effort: an old jax is a slow warm start, not a crash)."""
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except (AttributeError, OSError):
        return None
    for knob, value in (
            ("jax_persistent_cache_min_entry_size_bytes", -1),
            ("jax_persistent_cache_min_compile_time_secs", 0.0)):
        try:
            jax.config.update(knob, value)
        except AttributeError:
            pass
    return cache_dir


@dataclass(frozen=True)
class MemoryStats:
    """Per-device byte accounting of one compiled step, straight from
    XLA's ``compiled.memory_analysis()``. ``temp_bytes`` is the number a
    rematerialization policy moves (live activations + collective
    buffers); ``argument_bytes`` is what a precision policy's storage
    dtypes move; ``peak_bytes`` is the fit-in-HBM total (donated args
    alias their outputs, so un-aliased output bytes are the residual)."""

    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    alias_bytes: int

    @property
    def peak_bytes(self) -> int:
        return (self.argument_bytes + self.temp_bytes
                + max(self.output_bytes - self.alias_bytes, 0))


def memory_stats(compiled: Any) -> Optional[MemoryStats]:
    """MemoryStats of an AOT-compiled step, or None when this backend /
    jax build exposes no analysis (the knob is evidence, not load-bearing:
    a missing analysis must never fail a training run)."""
    try:
        ma = compiled.memory_analysis()
        return MemoryStats(
            argument_bytes=int(ma.argument_size_in_bytes),
            output_bytes=int(ma.output_size_in_bytes),
            temp_bytes=int(ma.temp_size_in_bytes),
            alias_bytes=int(ma.alias_size_in_bytes))
    except Exception:
        return None


def aot_compile_step(
    step_fn: Callable,
    state: Any,
    batch: Any,
    config_name: str = "",
    clock: Callable[[], float] = time.perf_counter,
) -> Tuple[Callable, CompileTimings]:
    """Explicit ``jit(...).lower().compile()`` of a train step, with the
    lower-vs-compile wall-clock split measured and published through the
    ``tk8s_train_compile_seconds`` gauge, and the compiled program's
    memory analysis through ``tk8s_train_memory_bytes`` (the evidence
    remat/precision A/Bs read). The returned executable keeps the jitted
    step's donation (state updates in place in HBM) and runs with zero
    retracing risk — the loop can't silently recompile."""
    from ..utils import metrics as _metrics

    t0 = clock()
    lowered = step_fn.lower(state, batch)
    t1 = clock()
    compiled = lowered.compile()
    t2 = clock()
    cache_dir = None
    try:
        cache_dir = jax.config.jax_compilation_cache_dir
    except AttributeError:
        pass
    timings = CompileTimings(lower_seconds=t1 - t0, compile_seconds=t2 - t1,
                             cache_dir=cache_dir)
    gauge = _metrics.gauge("tk8s_train_compile_seconds")
    gauge.set(timings.lower_seconds, config=config_name, phase="lower")
    gauge.set(timings.compile_seconds, config=config_name, phase="compile")
    mem = memory_stats(compiled)
    if mem is not None:
        mem_gauge = _metrics.gauge("tk8s_train_memory_bytes")
        for kind in ("argument", "output", "temp", "alias"):
            mem_gauge.set(getattr(mem, f"{kind}_bytes"),
                          config=config_name, kind=kind)
        mem_gauge.set(mem.peak_bytes, config=config_name, kind="peak")
    return compiled, timings


def make_eval_step(config: ModelConfig, mesh: Mesh, attention_fn=None,
                   microbatches: int = 0, precision=None):
    from .precision import apply_policy

    config = apply_policy(config, precision)
    b_sharding = NamedSharding(mesh, batch_spec())
    attention_fn = _resolve_attention(attention_fn, mesh, config)
    num_stages = pipeline_degree(mesh)
    microbatches = microbatches or num_stages

    def step(params, batch):
        tokens = jax.lax.with_sharding_constraint(batch["tokens"], b_sharding)
        _, metrics = loss_fn(params, tokens, config, attention_fn,
                             num_stages, microbatches, mesh)
        return metrics

    return jax.jit(step)
