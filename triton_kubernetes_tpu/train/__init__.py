"""Training runtime for the bundled workloads (MaxText-equivalent slice).

The reference ships no workload runtime (SURVEY.md §2.5); BASELINE.md's
acceptance gates are training jobs on the provisioned slices, so this
package provides the trainer those jobs run: sharded train step, MFU
accounting, data pipeline, and orbax checkpointing.
"""

from .data import DevicePrefetch
from .mfu import flops_per_token, mfu, tokens_per_sec_for_mfu
from .pipeline import LoopReport, run_pipelined
from .trainer import (
    CompileTimings,
    TrainState,
    aot_compile_step,
    enable_compile_cache,
    init_state,
    make_optimizer,
    make_train_step,
)

__all__ = [
    "flops_per_token",
    "mfu",
    "tokens_per_sec_for_mfu",
    "TrainState",
    "make_optimizer",
    "make_train_step",
    "init_state",
    "DevicePrefetch",
    "LoopReport",
    "run_pipelined",
    "CompileTimings",
    "aot_compile_step",
    "enable_compile_cache",
]
