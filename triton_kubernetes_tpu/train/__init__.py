"""Training runtime for the bundled workloads (MaxText-equivalent slice).

The reference ships no workload runtime (SURVEY.md §2.5); BASELINE.md's
acceptance gates are training jobs on the provisioned slices, so this
package provides the trainer those jobs run: sharded train step, MFU
accounting, data pipeline, and orbax checkpointing.
"""

from .checkpoint import (
    CheckpointIntegrityError,
    CheckpointManager,
    MeshMismatchError,
    ReshapeError,
    mesh_spec_of,
    peek_newest_manifest,
    restore_newest_verified,
)
from .data import DevicePrefetch, PrefetchProducerError
from .mfu import flops_per_token, mfu, tokens_per_sec_for_mfu
from .pipeline import LoopReport, run_pipelined
from .resilience import (
    EXIT_RESUME,
    AnomalyAbortedError,
    LossAnomalyGuard,
    PreemptionGuard,
    ResilienceReport,
    negotiate_mesh_config,
    run_resilient,
)
from .precision import (
    POLICIES,
    PrecisionPolicy,
    apply_policy,
    get_policy,
    grads_all_finite,
    policy_of,
    remat_policy_of,
)
from .trainer import (
    CompileTimings,
    MemoryStats,
    TrainState,
    aot_compile_step,
    enable_compile_cache,
    init_state,
    make_optimizer,
    make_train_step,
    memory_stats,
)

__all__ = [
    "flops_per_token",
    "mfu",
    "tokens_per_sec_for_mfu",
    "TrainState",
    "make_optimizer",
    "make_train_step",
    "init_state",
    "CheckpointManager",
    "CheckpointIntegrityError",
    "MeshMismatchError",
    "ReshapeError",
    "mesh_spec_of",
    "peek_newest_manifest",
    "negotiate_mesh_config",
    "restore_newest_verified",
    "DevicePrefetch",
    "PrefetchProducerError",
    "LoopReport",
    "run_pipelined",
    "EXIT_RESUME",
    "AnomalyAbortedError",
    "LossAnomalyGuard",
    "PreemptionGuard",
    "ResilienceReport",
    "run_resilient",
    "CompileTimings",
    "aot_compile_step",
    "enable_compile_cache",
    "MemoryStats",
    "memory_stats",
    "POLICIES",
    "PrecisionPolicy",
    "apply_policy",
    "get_policy",
    "grads_all_finite",
    "policy_of",
    "remat_policy_of",
]
