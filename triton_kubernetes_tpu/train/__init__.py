"""Training runtime for the bundled workloads (MaxText-equivalent slice).

The reference ships no workload runtime (SURVEY.md §2.5); BASELINE.md's
acceptance gates are training jobs on the provisioned slices, so this
package provides the trainer those jobs run: sharded train step, MFU
accounting, data pipeline, and orbax checkpointing.
"""

from .mfu import flops_per_token, mfu, tokens_per_sec_for_mfu
from .trainer import TrainState, make_optimizer, make_train_step, init_state

__all__ = [
    "flops_per_token",
    "mfu",
    "tokens_per_sec_for_mfu",
    "TrainState",
    "make_optimizer",
    "make_train_step",
    "init_state",
]
