"""Model-FLOPs-utilization accounting.

Convention: the PaLM-appendix formula — a training step costs
``6 * N_active`` matmul FLOPs per token (fwd + bwd) plus attention's
``12 * L * H * d_head * S`` per token, halved for causal masking. Peak
figures come from ``topology.slices.TPU_GENERATIONS`` (public bf16 specs),
so the BASELINE "≥40% MFU on v5p" gate is computed against the same table
the provisioner uses to label node pools.
"""

from __future__ import annotations

from ..models.config import ModelConfig
from ..topology.slices import SliceSpec


def flops_per_token(config: ModelConfig, seq_len: int, causal: bool = True) -> float:
    """Training (fwd+bwd) FLOPs per token."""
    matmul = 6.0 * config.active_params()
    attn = (12.0 * config.num_layers * config.num_heads
            * config.head_dim * seq_len)
    if causal:
        attn *= 0.5
    return matmul + attn


def mfu(
    tokens_per_sec: float,
    config: ModelConfig,
    seq_len: int,
    peak_tflops_total: float,
) -> float:
    """Fraction of peak achieved, e.g. 0.4 == the BASELINE v5p gate."""
    achieved = tokens_per_sec * flops_per_token(config, seq_len)
    return achieved / (peak_tflops_total * 1e12)


def mfu_on_slice(
    tokens_per_sec: float, config: ModelConfig, seq_len: int, spec: SliceSpec,
) -> float:
    return mfu(tokens_per_sec, config, seq_len, spec.peak_bf16_tflops)


def attention_flops_fraction(config: ModelConfig, seq_len: int) -> float:
    """Share of training FLOPs in the attention score/value matmuls (the
    part that runs in the flash kernel at sub-matmul efficiency)."""
    total = flops_per_token(config, seq_len)
    return (total - 6.0 * config.active_params()) / total


def project_mfu(measured_mfu: float, proxy: ModelConfig, proxy_seq: int,
                target: ModelConfig, target_seq: int,
                kernel_rel_efficiency: float = 0.7) -> float:
    """Conservative roofline transfer of a proxy-measured MFU to a target
    (model, seq) — the argued bound tying the llama3-bench number to the
    BASELINE 8B/v5p ≥0.40 gate (docs/guide/workloads.md derivation).

    Every factor that differs proxy -> 8B/v5p except attention share moves
    MFU UP and is clamped to 1.0 (no credit taken): matmul operand dims
    grow 4x (embed 1024 -> 4096: better MXU tiling, higher per-matmul
    arithmetic intensity), and the hardware ridge drops ~3x (v5e peak/BW
    ~481 FLOPs/byte vs v5p ~166 — more bandwidth per FLOP). The one debit
    kept is attention: its FLOPs share (attention_flops_fraction) runs at
    ``kernel_rel_efficiency`` of the dense-matmul rate (0.7 is the flash
    kernel's measured v5e ratio, scripts/tpu block sweeps), and the target
    trains 4x longer sequences, so its share is larger. The matmul-only
    efficiency is inferred from the proxy measurement and re-applied under
    the target's mix."""
    debit = 1.0 - kernel_rel_efficiency
    proxy_mix = 1.0 - attention_flops_fraction(proxy, proxy_seq) * debit
    target_mix = 1.0 - attention_flops_fraction(target, target_seq) * debit
    matmul_mfu = min(1.0, measured_mfu / proxy_mix)
    return matmul_mfu * target_mix


def tokens_per_sec_for_mfu(
    target_mfu: float, config: ModelConfig, seq_len: int, peak_tflops_total: float,
) -> float:
    """Inverse: the throughput a slice must sustain to hit ``target_mfu``."""
    return target_mfu * peak_tflops_total * 1e12 / flops_per_token(config, seq_len)
