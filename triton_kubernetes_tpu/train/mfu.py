"""Model-FLOPs-utilization accounting.

Convention: the PaLM-appendix formula — a training step costs
``6 * N_active`` matmul FLOPs per token (fwd + bwd) plus attention's
``12 * L * H * d_head * S`` per token, halved for causal masking. Peak
figures come from ``topology.slices.TPU_GENERATIONS`` (public bf16 specs),
so the BASELINE "≥40% MFU on v5p" gate is computed against the same table
the provisioner uses to label node pools.
"""

from __future__ import annotations

from ..models.config import ModelConfig
from ..topology.slices import SliceSpec


def flops_per_token(config: ModelConfig, seq_len: int, causal: bool = True) -> float:
    """Training (fwd+bwd) FLOPs per token."""
    matmul = 6.0 * config.active_params()
    attn = (12.0 * config.num_layers * config.num_heads
            * config.head_dim * seq_len)
    if causal:
        attn *= 0.5
    return matmul + attn


def mfu(
    tokens_per_sec: float,
    config: ModelConfig,
    seq_len: int,
    peak_tflops_total: float,
) -> float:
    """Fraction of peak achieved, e.g. 0.4 == the BASELINE v5p gate."""
    achieved = tokens_per_sec * flops_per_token(config, seq_len)
    return achieved / (peak_tflops_total * 1e12)


def mfu_on_slice(
    tokens_per_sec: float, config: ModelConfig, seq_len: int, spec: SliceSpec,
) -> float:
    return mfu(tokens_per_sec, config, seq_len, spec.peak_bf16_tflops)


def tokens_per_sec_for_mfu(
    target_mfu: float, config: ModelConfig, seq_len: int, peak_tflops_total: float,
) -> float:
    """Inverse: the throughput a slice must sustain to hit ``target_mfu``."""
    return target_mfu * peak_tflops_total * 1e12 / flops_per_token(config, seq_len)
