"""Mixed-precision policies for the training step.

The TPU recipe ("Fine-Tuning and Serving Gemma on Cloud TPU", PAPERS.md)
is bf16 compute over f32 master state: matmul operands and activations in
bfloat16 so the MXU runs at full rate and live activation bytes halve,
while everything that accumulates — master params, Adam moments, the
attention softmax, RMS-norm reductions, the CE logsumexp — stays float32.
The model layer already enforces the reduction side (ops/attention.py
casts logits to f32 before softmax, the flash kernel accumulates in f32
VMEM scratch, ops/fused_ce.py runs its online logsumexp and the logit
cotangent in f32, ops/norms.py reduces in f32); what a policy chooses is
the *storage and matmul operand* dtypes, i.e. exactly the
``param_dtype``/``dtype`` pair of :class:`..models.config.ModelConfig`.

A policy is therefore applied by rewriting the config
(:func:`apply_policy`) before the step is built — no tracing-time dtype
threading, no chance of a half-applied policy: the one config object the
model reads is the policy. ``jax.grad`` cotangents inherit the f32 leaf
dtype of the master params, so the optimizer update runs in f32 without
any explicit upcast, and bf16's f32-sized exponent range means no loss
scaling is needed (unlike fp16).

Parity contracts live in tests/test_precision.py: the bf16 loss
trajectory tracks f32 within a pinned tolerance and every gradient leaf
stays finite. The CI A/B (scripts/ci/precision_remat_evidence.py)
re-proves both on every push through the pipelined loop.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, Union

import jax
import jax.numpy as jnp

from ..constants import WEIGHT_DTYPES
from ..models.config import ModelConfig


@dataclass(frozen=True)
class PrecisionPolicy:
    """Storage/compute dtype pair for one training step.

    ``param_dtype`` is what the master params and (by zeros_like
    inheritance) the optimizer moments are stored in; ``compute_dtype``
    is what weights are cast to at their point of use and what
    activations flow in. Softmax/norm/CE reductions are f32 by
    construction in the ops layer regardless of policy.
    """

    name: str
    param_dtype: str
    compute_dtype: str

    def describe(self) -> str:
        return (f"{self.name}: params/opt {self.param_dtype}, "
                f"compute/activations {self.compute_dtype}, "
                f"reductions float32")


POLICIES: Dict[str, PrecisionPolicy] = {
    # Everything f32: the numerics baseline the bf16 trajectory is
    # pinned against, and the debugging escape hatch.
    "f32": PrecisionPolicy("f32", "float32", "float32"),
    # The production TPU recipe: f32 master state, bf16 matmuls.
    "bf16": PrecisionPolicy("bf16", "float32", "bfloat16"),
}


def get_policy(policy: Union[str, PrecisionPolicy]) -> PrecisionPolicy:
    if isinstance(policy, PrecisionPolicy):
        return policy
    try:
        return POLICIES[policy]
    except KeyError:
        raise KeyError(
            f"unknown precision policy {policy!r}; know "
            f"{sorted(POLICIES)}") from None


def apply_policy(config: ModelConfig,
                 policy: Union[str, PrecisionPolicy, None],
                 ) -> ModelConfig:
    """Config with the policy's dtypes applied; None/"auto" is identity
    (the config's own dtypes — llama3-bench ships bf16, the test
    miniatures f32 — stay authoritative unless a policy overrides)."""
    if policy is None or policy == "auto":
        return config
    p = get_policy(policy)
    if (config.dtype == p.compute_dtype
            and config.param_dtype == p.param_dtype):
        return config
    return replace(config, dtype=p.compute_dtype, param_dtype=p.param_dtype)


def policy_of(config: ModelConfig) -> str:
    """Classify a config's dtype pair back to a policy name ("custom"
    when no named policy matches) — for logs and bench JSON."""
    for name, p in POLICIES.items():
        if (config.dtype == p.compute_dtype
                and config.param_dtype == p.param_dtype):
            return name
    return "custom"


def remat_policy_of(config: ModelConfig) -> str:
    """The effective rematerialization policy name ("none" when remat is
    disabled either way) — the single normalization bench.py and the
    trainer log share, matching models.llama.remat_block's gating."""
    return "none" if not config.remat else config.remat_policy


# ---------------------------------------------------------------------
# Decode-time quantization policies (the serving counterpart of the
# training policies above; `tk8s serve --kv-dtype/--weight-dtype`).
# The KV-page dtype knob lives with the cache it configures
# (models.paged.KV_DTYPES); both tuples are pinned in constants.py so
# the jax-less CLI parser registers the same choices the engine
# validates.
# ---------------------------------------------------------------------

# Decode weight storage (WEIGHT_DTYPES, imported above): "auto" leaves
# the params tree exactly as handed in; "int8" applies
# models.llama.quantize_weights.


def quantize_for_decode(params: Any, config: ModelConfig,
                        weight_dtype: str) -> tuple:
    """Apply a decode weight policy: returns ``(params, config)``.

    The quantization twin of :func:`apply_policy`, with the same
    cannot-be-half-applied shape: "auto" is the identity on BOTH params
    and config, "int8"/"fp8" rewrite both together via
    ``models.llama.quantize_weights`` (per-channel symmetric int8 or
    float8_e4m3fn for the big matmuls; the caller's f32 master tree is
    untouched). "fp8" raises ``Fp8UnavailableError`` where this jax
    build lacks the dtype — loud and typed, never a silent fallback.
    """
    if weight_dtype not in WEIGHT_DTYPES:
        raise KeyError(
            f"unknown weight_dtype {weight_dtype!r}; know "
            f"{list(WEIGHT_DTYPES)}")
    if weight_dtype == "auto":
        return params, config
    from ..models.llama import quantize_weights

    return quantize_weights(params, config, weight_dtype)


def grads_all_finite(grads: Any) -> jnp.ndarray:
    """Scalar bool: every leaf of the gradient tree is NaN/Inf-free.
    Jit-safe (a device scalar, no host sync) — the grads-finite contract
    the precision tests and the CI evidence script assert."""
    leaves = jax.tree.leaves(grads)
    ok = jnp.bool_(True)
    for leaf in leaves:
        ok = ok & jnp.all(jnp.isfinite(leaf.astype(jnp.float32)))
    return ok
