"""Trainer entrypoint: ``python -m triton_kubernetes_tpu.train``.

This is the command the provisioned JobSets run (docs/guide/gcp-tpu,
modules/gcp_tpu.py training-job manifests): every worker starts the same
program, ``jax.distributed`` initializes from the env the JobSet injects
(``JAX_COORDINATOR_ADDRESS`` + ``TPU_WORKER_ID``/job completion index), and
the whole slice executes one SPMD program over the requested mesh.

Single-process runs (laptop smoke, one-host slice) skip distributed init
automatically. Data comes from the native sharded token pipeline when
``--data-dir`` is given (falls back to the pure-Python reader), else from
the synthetic Markov generator, so the entrypoint always has something to
train on — the BASELINE "cluster-up then train" gates assume that.

The loop itself is the resilient one (train/resilience.py): SIGTERM (the
GKE preemption warning) force-syncs the window, writes a synchronous
emergency checkpoint, and exits with code 75 so the JobSet restart policy
resumes instead of fails; restores are manifest-verified with automatic
fallback past corrupt steps; and ``--anomaly-factor`` arms a loss guard
that rolls back to the last good checkpoint instead of training through a
NaN.
"""

from __future__ import annotations

import argparse
import itertools
import os
import sys


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m triton_kubernetes_tpu.train",
        description="Bundled sharded trainer for the provisioned TPU slice.")
    p.add_argument("--model", default="llama3-bench",
                   help="config name from models.CONFIGS")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=0,
                   help="global batch across all chips "
                        "(0 = 4 per data*fsdp shard, fits any slice)")
    p.add_argument("--seq-len", type=int, default=0,
                   help="0 = the model's max_seq_len")
    p.add_argument("--learning-rate", type=float, default=3e-4)
    p.add_argument("--warmup-steps", type=int, default=100)
    # Mesh axes: -1 absorbs remaining devices (at most one axis).
    p.add_argument("--data", type=int, default=1)
    p.add_argument("--stage", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=-1)
    p.add_argument("--seq", type=int, default=1)
    p.add_argument("--expert", type=int, default=1)
    p.add_argument("--tensor", type=int, default=1)
    p.add_argument("--microbatches", type=int, default=0,
                   help="pipeline microbatches (0 = stage count)")
    p.add_argument("--ring-attention", action="store_true",
                   help="sequence-parallel attention (required when seq>1)")
    p.add_argument("--data-dir", default="",
                   help="dir of *.bin token shards; empty = synthetic data")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="steps between saves (0 = only at the end)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--emergency-dir", default="",
                   help="directory for preemption emergency checkpoints "
                        "(default: the checkpoint dir); --resume considers "
                        "both and restores the newest verified step")
    p.add_argument("--anomaly-factor", type=float, default=0.0,
                   help="loss-anomaly guard: roll back to the last good "
                        "checkpoint when a synced loss exceeds this factor "
                        "times the running median (NaN/Inf always trip); "
                        "0 disables the guard")
    p.add_argument("--max-rollbacks", type=int, default=3,
                   help="abort (exit 4) after this many consecutive "
                        "anomaly rollbacks without a clean window")
    p.add_argument("--skip-anomalous-window", action="store_true",
                   help="on anomaly rollback, resume the data stream after "
                        "the offending window instead of replaying it")
    p.add_argument("--model-opt", action="append", default=[],
                   metavar="K=V",
                   help="ModelConfig override, repeatable (e.g. "
                        "--model-opt fused_ce=true --model-opt "
                        "remat_policy=dots); values coerce like YAML "
                        "scalars")
    p.add_argument("--precision", choices=["auto", "f32", "bf16"],
                   default="auto",
                   help="precision policy (train/precision.py): f32 "
                        "master params + optimizer state always; bf16 "
                        "casts compute/activations (softmax and CE "
                        "accumulation stay f32); auto keeps the model "
                        "config's own dtypes")
    p.add_argument("--remat-policy",
                   choices=["none", "dots", "full"], default="",
                   help="rematerialization of the transformer block: "
                        "none saves every activation (fastest step, "
                        "largest memory), dots saves MXU outputs and "
                        "recomputes elementwise ops, full recomputes "
                        "whole blocks (max memory savings); default: "
                        "the model config's policy")
    p.add_argument("--profile-dir", default="",
                   help="capture a jax.profiler trace of steady-state "
                        "steps into this directory (view with "
                        "tensorboard/xprof; SURVEY.md §5 tracing "
                        "obligation)")
    p.add_argument("--sync-every", type=int, default=0,
                   help="steps between device->host metric syncs; also "
                        "the in-flight bound of the pipelined loop "
                        "(0 = --log-every)")
    p.add_argument("--prefetch", type=int, default=2,
                   help="device-prefetch buffer depth (0 disables the "
                        "background producer + jax.device_put staging)")
    p.add_argument("--compile-cache-dir", default="",
                   help="JAX persistent compilation cache directory; "
                        "reused across runs so restarts skip XLA "
                        "recompilation")
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--json-logs", action="store_true")
    p.add_argument("--distributed", choices=["auto", "on", "off"],
                   default="auto")
    p.add_argument("--dry-run", action="store_true",
                   help="build everything, run one step, exit")
    return p


def _maybe_init_distributed(mode: str, log) -> None:
    """JobSet workers carry JAX_COORDINATOR_ADDRESS + TPU_WORKER_ID
    (topology/jobset.py:53-70); initialize jax.distributed from them."""
    import jax

    coord = os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    if mode == "off" or (mode == "auto" and not coord):
        return
    if not coord:
        # --distributed on without the JobSet env: let jax auto-detect
        # (it knows the GKE TPU pod metadata).
        log.log("info", "jax.distributed init (auto-detect)")
        jax.distributed.initialize()
        return
    worker = int(os.environ.get(
        "TPU_WORKER_ID", os.environ.get("JOB_COMPLETION_INDEX", "0")))
    num = int(os.environ.get("NUM_TPU_WORKERS", "0")) or None
    log.log("info", "jax.distributed init",
            coordinator=coord, process_id=worker, num_processes=num)
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=num, process_id=worker)


def _batches(args, config, batch_size: int, seq_len: int):
    if args.data_dir:
        from .data import ShardedTokenPipeline

        return ShardedTokenPipeline(
            args.data_dir, batch_size, seq_len).batches()
    from .data import synthetic_batches

    gen = synthetic_batches(config.vocab_size, batch_size, seq_len)
    return gen


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    from ..utils.logging import Logger

    log = Logger(json_mode=args.json_logs)
    _maybe_init_distributed(args.distributed, log)

    import jax

    from ..models import get_config
    from ..ops.ring_attention import make_ring_attention
    from ..parallel import MeshConfig, create_mesh
    from ..parallel.mesh import describe_mesh
    from .trainer import (
        aot_compile_step, enable_compile_cache, init_state, make_optimizer,
        make_train_step)
    from .mfu import flops_per_token, mfu as compute_mfu

    from ..config.config import parse_scalar

    overrides = {}
    for item in args.model_opt:
        key, sep, value = item.partition("=")
        if not sep:
            raise SystemExit(f"--model-opt expects K=V, got {item!r}")
        overrides[key] = parse_scalar(value)
    if args.compile_cache_dir:
        cache = enable_compile_cache(args.compile_cache_dir)
        log.log("info", "persistent compile cache",
                dir=cache or "(unsupported by this jax)")
    if args.remat_policy:
        # The dedicated flag wins outright: it both selects the policy
        # and arms/disarms remat itself, so a stale --model-opt
        # remat=false cannot silently turn "dots"/"full" into a no-op.
        overrides["remat_policy"] = args.remat_policy
        overrides["remat"] = args.remat_policy != "none"
    config = get_config(args.model, **overrides)
    from .precision import apply_policy, policy_of, remat_policy_of

    config = apply_policy(config, args.precision)
    log.log("info", "precision policy", policy=policy_of(config),
            compute_dtype=config.dtype, param_dtype=config.param_dtype,
            remat=remat_policy_of(config))
    seq_len = args.seq_len or config.max_seq_len
    mesh_cfg = MeshConfig(
        data=args.data, stage=args.stage, fsdp=args.fsdp, seq=args.seq,
        expert=args.expert, tensor=args.tensor)
    mesh = create_mesh(mesh_cfg)
    n_devices = mesh.size
    batch_shards = max(mesh.shape["data"] * mesh.shape["fsdp"], 1)
    batch_size = args.batch_size or 4 * batch_shards
    log.log("info", "trainer starting", model=config.name,
            mesh=describe_mesh(mesh), devices=n_devices,
            process=jax.process_index(), batch=batch_size,
            seq_len=seq_len, steps=args.steps)

    if batch_size % batch_shards:
        log.log("error", "global batch must divide the data*fsdp axes",
                batch=batch_size, shards=batch_shards)
        return 2
    stages = mesh.shape["stage"]
    if stages > 1:
        # The per-stage kernel shard_maps split each microbatch over
        # (data, fsdp): validate here so misconfigurations are a friendly
        # error, not a shard_map traceback from deep inside tracing.
        m = args.microbatches or stages
        if batch_size % m or (batch_size // m) % batch_shards:
            log.log("error",
                    "batch/microbatches must divide the data*fsdp axes "
                    "under pipeline stages",
                    batch=batch_size, microbatches=m, shards=batch_shards)
            return 2

    attention_fn = None
    if args.ring_attention and mesh.shape["seq"] == 1:
        # seq > 1 meshes get ring automatically (trainer._resolve_attention,
        # incl. the nested-under-pipeline form); this flag covers the
        # unusual request for ring on an unsharded sequence.
        ring = make_ring_attention(mesh)
        attention_fn = lambda q, k, v, positions: ring(q, k, v)

    opt = make_optimizer(
        learning_rate=args.learning_rate, warmup_steps=args.warmup_steps,
        decay_steps=max(args.steps, args.warmup_steps + 1))
    state = init_state(config, mesh, opt)
    step_fn = make_train_step(
        config, mesh, opt, attention_fn=attention_fn,
        microbatches=args.microbatches)

    from .checkpoint import CheckpointManager
    from .resilience import (
        EXIT_RESUME, AnomalyAbortedError, LossAnomalyGuard, PreemptionGuard,
        run_resilient)

    ckpt = None
    em_ckpt = None
    if args.checkpoint_dir:
        ckpt = CheckpointManager(args.checkpoint_dir)
    if args.emergency_dir and (
            ckpt is None
            or os.path.abspath(args.emergency_dir) != ckpt.directory):
        # Path-normalized: two orbax managers on one directory would race
        # each other's GC/finalize and double-list every resume candidate.
        em_ckpt = CheckpointManager(args.emergency_dir)
    start_is_checkpointed = False
    if args.resume and (ckpt is not None or em_ckpt is not None):
        # The newest *verified* step wins, scheduled or emergency — a torn
        # emergency save is quarantined and resume falls back to the last
        # scheduled checkpoint automatically. All-corrupt is a typed,
        # loud CheckpointIntegrityError, not a silent retrain.
        from .checkpoint import restore_newest_verified

        try:
            state, best, best_step = restore_newest_verified(
                state, ckpt, em_ckpt)
        except FileNotFoundError:
            pass  # nothing saved yet: a fresh start under --resume is fine
        else:
            # The restored step was just verified end-to-end; when it
            # lives in the scheduled dir, the guard's baseline check can
            # skip re-hashing it.
            start_is_checkpointed = best is ckpt
            log.log("info", "resumed", step=int(state.step),
                    source=best.directory,
                    emergency=best is em_ckpt)

    fpt = flops_per_token(config, seq_len)
    from ..topology.slices import peak_bf16_tflops_for_kind

    # 0 off-TPU: the mfu field is then omitted rather than wrong.
    peak = peak_bf16_tflops_for_kind(
        jax.devices()[0].device_kind) * n_devices

    start_step = int(state.step)
    tokens_per_step = batch_size * seq_len
    last_loss = None  # None until the first sync: never log a fake NaN
    tracing = False
    max_steps = max(args.steps - start_step, 0)
    if args.dry_run:
        max_steps = min(max_steps, 1)
    target_step = start_step + max_steps
    sync_every = 1 if args.dry_run else \
        max(args.sync_every or args.log_every, 1)

    # Step-pipelined hot path (train/pipeline.py): steps dispatch back to
    # back with the next batch's host->device transfer already in flight
    # (DevicePrefetch) and ONE host sync per window — never one per step.
    # The resilient driver (train/resilience.py) rebuilds this stream at a
    # rolled-back step by deterministic replay: same source, same seed,
    # skip to the step index.
    from .data import DevicePrefetch
    from .trainer import batch_spec
    from jax.sharding import NamedSharding

    def make_batches(start: int):
        gen = _batches(args, config, batch_size, seq_len)
        if start:
            log.log("info", "skipping consumed batches", count=start)
            for _ in range(start):
                next(gen)
        host = ({"tokens": b["tokens"]} for b in gen)
        # device_put with a mesh sharding needs the whole array
        # addressable; multi-host slices keep the historical feed (jit
        # stages per step).
        if args.prefetch > 0 and jax.process_count() == 1:
            pf = DevicePrefetch(
                host, sharding=NamedSharding(mesh, batch_spec()),
                buffer_size=args.prefetch)
            return pf, pf
        return host, None

    first_iter, first_pf = (None, None)
    if max_steps:
        # AOT compile against the exact first batch: the compile cost is
        # measured and attributed (lower vs XLA) instead of silently
        # diluting the first window, and the loop cannot retrace.
        first_iter, first_pf = make_batches(start_step)
        first = next(first_iter, None)
        if first is None:
            max_steps = 0
            target_step = start_step
        else:
            step_fn, timings = aot_compile_step(
                step_fn, state, first, config_name=config.name)
            from .trainer import memory_stats

            mem = memory_stats(step_fn)
            mem_fields = {}
            if mem is not None:
                mem_fields = dict(
                    temp_mib=round(mem.temp_bytes / 2**20, 1),
                    peak_mib=round(mem.peak_bytes / 2**20, 1))
            log.log("info", "train step compiled",
                    lower_s=round(timings.lower_seconds, 3),
                    compile_s=round(timings.compile_seconds, 3),
                    cache_dir=timings.cache_dir or "", **mem_fields)
            first_iter = itertools.chain([first], first_iter)
    holder = {"it": first_iter, "pf": first_pf}

    def batches_factory(pos: int):
        if holder["it"] is not None and pos == start_step:
            out = (holder["it"], holder["pf"])
            holder["it"] = None
            return out
        it, pf = make_batches(pos)
        holder["pf"] = pf  # keep on_sync's wait accounting on the live one
        return it, pf

    def on_sync(gstep, cur_state, window_losses, window_dt):
        nonlocal last_loss
        last_loss = window_losses[-1]
        tps = tokens_per_step * len(window_losses) / max(window_dt, 1e-9)
        fields = dict(step=gstep, loss=round(last_loss, 4),
                      tokens_per_sec=round(tps, 1),
                      tflops=round(tps * fpt / 1e12, 2))
        if peak:
            fields["mfu"] = round(compute_mfu(tps, config, seq_len, peak), 4)
        if holder["pf"] is not None:
            fields["prefetch_wait_s"] = round(holder["pf"].wait_seconds, 4)
        log.log("info", "train", **fields)

    def on_checkpoint(gstep, kind):
        msg = ("emergency checkpoint saved" if kind == "emergency"
               else "checkpoint saved")
        log.log("info" if kind != "emergency" else "warn", msg, step=gstep)

    guard = (LossAnomalyGuard(factor=args.anomaly_factor)
             if args.anomaly_factor > 0 else None)
    preempt = PreemptionGuard()
    try:
        preempt.install()
    except ValueError:  # not the main thread (embedded run): unguarded
        preempt = None

    report = None
    aborted = None
    try:
        if max_steps:
            if args.profile_dir and not args.dry_run:
                # The compile step is already excluded (AOT above), so the
                # whole loop is steady state — trace all of it. Single-
                # window runs get a trace too.
                jax.profiler.start_trace(args.profile_dir)
                tracing = True
                log.log("info", "profiler tracing", dir=args.profile_dir)
            try:
                state, report = run_resilient(
                    step_fn, state, batches_factory,
                    ckpt=ckpt, emergency_ckpt=em_ckpt or ckpt,
                    target_step=target_step, start_step=start_step,
                    sync_every=sync_every,
                    checkpoint_every=(args.checkpoint_every if ckpt else 0),
                    guard=guard, max_rollbacks=args.max_rollbacks,
                    skip_anomalous_window=args.skip_anomalous_window,
                    start_is_checkpointed=start_is_checkpointed,
                    preemption=preempt,
                    tokens_per_step=tokens_per_step,
                    config_name=config.name,
                    on_sync=on_sync, on_checkpoint=on_checkpoint)
            except AnomalyAbortedError as e:
                aborted = e
                log.log("error", "anomaly guard aborted the run",
                        error=str(e), step=e.anomaly.step,
                        reason=e.anomaly.reason)
            else:
                if report.rollbacks:
                    log.log("warn", "anomaly rollbacks taken",
                            rollbacks=report.rollbacks,
                            restored_steps=report.restored_steps)
                if (report.steps < max_steps and not report.interrupted):
                    log.log("warn", "data exhausted before requested steps",
                            done=start_step + report.steps, want=args.steps)
    finally:
        if holder["it"] is not None and holder["pf"] is not None:
            holder["pf"].close()
        if tracing:
            # try/finally: the trace matters MOST when the run dies (OOM,
            # interrupt) — sync so it holds completed device work, then
            # flush it regardless of how the loop exited. The sync itself
            # re-raises on a failed computation; that must not cost the
            # trace (or mask the original exception).
            try:
                jax.block_until_ready(state.params)
            except Exception:
                pass
            jax.profiler.stop_trace()
            log.log("info", "profiler trace written", dir=args.profile_dir)
        if preempt is not None:
            preempt.uninstall()

    final_loss = round(last_loss, 4) if last_loss is not None else "n/a"
    if aborted is not None:
        # The state tree was donated into the failed window: do not touch
        # it (no final save) — the last good checkpoint is the artifact.
        for mgr in (ckpt, em_ckpt):
            if mgr is not None:
                mgr.close()
        log.log("info", "trainer done", final_loss=final_loss,
                outcome="anomaly-abort")
        return 4
    if report is not None and report.interrupted:
        # Preemption warning honored: the emergency checkpoint (manifest-
        # committed) is on disk; exit with the resume code so the JobSet
        # restart policy relaunches with --resume instead of failing.
        for mgr in (ckpt, em_ckpt):
            if mgr is not None:
                mgr.close()
        log.log("warn", "trainer preempted; exiting for resume",
                step=start_step + report.steps,
                emergency_step=report.emergency_step,
                exit_code=EXIT_RESUME)
        log.log("info", "trainer done", final_loss=final_loss,
                outcome="preempted")
        return EXIT_RESUME
    if ckpt:
        if ckpt.latest_step() != int(state.step):
            ckpt.save(int(state.step), state, wait=True, kind="final")
            log.log("info", "final checkpoint", step=int(state.step))
        ckpt.close()
    if em_ckpt is not None:
        em_ckpt.close()
    log.log("info", "trainer done", final_loss=final_loss)
    return 0


if __name__ == "__main__":
    sys.exit(main())
