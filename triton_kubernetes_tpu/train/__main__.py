"""Trainer entrypoint: ``python -m triton_kubernetes_tpu.train``.

This is the command the provisioned JobSets run (docs/guide/gcp-tpu,
modules/gcp_tpu.py training-job manifests): every worker starts the same
program, ``jax.distributed`` initializes from the env the JobSet injects
(``JAX_COORDINATOR_ADDRESS`` + ``TPU_WORKER_ID``/job completion index), and
the whole slice executes one SPMD program over the requested mesh.

Single-process runs (laptop smoke, one-host slice) skip distributed init
automatically. Under ``jax.distributed`` the trainer is process-aware end
to end: the mesh is the hybrid DCN×ICI placement (data-parallel across
processes, ICI axes within — parallel/multihost.py), each host stages
only its own batch rows, checkpoint save/restore is coordinated
single-writer-per-shard, the preemption stop is a cross-process
agreement, and logs/metrics are rank-tagged. Data comes from the native
sharded token pipeline when
``--data-dir`` is given (falls back to the pure-Python reader), else from
the synthetic Markov generator, so the entrypoint always has something to
train on — the BASELINE "cluster-up then train" gates assume that.

The loop itself is the resilient one (train/resilience.py): SIGTERM (the
GKE preemption warning) force-syncs the window, writes a synchronous
emergency checkpoint, and exits with code 75 so the JobSet restart policy
resumes instead of fails; restores are manifest-verified with automatic
fallback past corrupt steps; and ``--anomaly-factor`` arms a loss guard
that rolls back to the last good checkpoint instead of training through a
NaN.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time
from dataclasses import dataclass
from typing import Mapping, Optional

# The jax.distributed coordinator port every worker dials (worker 0
# listens), plus the config/anomaly exit codes — all single-sourced from
# the dependency-free constants module (the rendering layer imports the
# same values, so manifests and runtime cannot drift; lint rule TK8S104
# re-checks every duplication site cross-file).
from ..constants import COORDINATOR_PORT, EXIT_ANOMALY, EXIT_CONFIG


class DistributedEnvError(ValueError):
    """The JobSet-injected distributed variables are malformed (a
    non-integer worker id, an out-of-range rank, a coordinator address
    with no port). Raised BEFORE ``jax.distributed.initialize`` so the
    operator gets one clean line instead of a distributed-runtime hang
    or traceback."""


@dataclass(frozen=True)
class DistributedEnv:
    """Parsed multi-process identity (topology/jobset.py injects these;
    the local launcher in parallel/multihost.py injects the same)."""

    coordinator: str               # host:port of worker 0
    process_id: int                # this worker's rank
    num_processes: Optional[int]   # None = let jax discover


def parse_distributed_env(
        environ: Optional[Mapping[str, str]] = None,
) -> Optional[DistributedEnv]:
    """Distributed identity from the environment, or None when no
    coordinator is advertised (single-process run, or auto-detect).

    ``JAX_COORDINATOR_ADDRESS`` selects JobSet mode; the worker id comes
    from ``TPU_WORKER_ID`` falling back to ``JOB_COMPLETION_INDEX``
    (the indexed-Job downward-API path) falling back to 0; world size
    from ``NUM_TPU_WORKERS`` (0/unset = let jax discover). Malformed
    values raise :class:`DistributedEnvError` — never a downstream hang.
    """
    env = os.environ if environ is None else environ
    coord = (env.get("JAX_COORDINATOR_ADDRESS") or "").strip()
    if not coord:
        return None
    _, sep, port = coord.rpartition(":")
    if not sep or not port.isdigit():
        raise DistributedEnvError(
            f"JAX_COORDINATOR_ADDRESS={coord!r} must be host:port "
            f"(the JobSet injects e.g. name-0.name.ns.svc:"
            f"{COORDINATOR_PORT})")
    wid_raw = (env.get("TPU_WORKER_ID") or "").strip() or (
        env.get("JOB_COMPLETION_INDEX") or "").strip() or "0"
    try:
        wid = int(wid_raw)
    except ValueError:
        raise DistributedEnvError(
            f"TPU_WORKER_ID/JOB_COMPLETION_INDEX={wid_raw!r} is not an "
            f"integer") from None
    if wid < 0:
        raise DistributedEnvError(f"TPU_WORKER_ID={wid} must be >= 0")
    num_raw = (env.get("NUM_TPU_WORKERS") or "").strip()
    num: Optional[int] = None
    if num_raw and num_raw != "0":
        try:
            num = int(num_raw)
        except ValueError:
            raise DistributedEnvError(
                f"NUM_TPU_WORKERS={num_raw!r} is not an integer") from None
        if num < 1:
            raise DistributedEnvError(
                f"NUM_TPU_WORKERS={num} must be >= 1")
        if wid >= num:
            raise DistributedEnvError(
                f"TPU_WORKER_ID={wid} out of range for "
                f"NUM_TPU_WORKERS={num}")
    return DistributedEnv(coordinator=coord, process_id=wid,
                          num_processes=num)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m triton_kubernetes_tpu.train",
        description="Bundled sharded trainer for the provisioned TPU slice.")
    p.add_argument("--model", default="llama3-bench",
                   help="config name from models.CONFIGS")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=0,
                   help="global batch across all chips "
                        "(0 = 4 per data*fsdp shard, fits any slice)")
    p.add_argument("--seq-len", type=int, default=0,
                   help="0 = the model's max_seq_len")
    p.add_argument("--learning-rate", type=float, default=3e-4)
    p.add_argument("--warmup-steps", type=int, default=100)
    # Mesh axes: -1 absorbs remaining devices (at most one axis).
    p.add_argument("--data", type=int, default=0,
                   help="data-parallel (DCN) axis; 0 = auto: 1 single-"
                        "process, the process count under "
                        "jax.distributed (one DCN shard per host)")
    p.add_argument("--stage", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=-1)
    p.add_argument("--seq", type=int, default=1)
    p.add_argument("--expert", type=int, default=1)
    p.add_argument("--tensor", type=int, default=1)
    p.add_argument("--microbatches", type=int, default=0,
                   help="pipeline microbatches (0 = stage count)")
    p.add_argument("--ring-attention", action="store_true",
                   help="sequence-parallel attention (required when seq>1)")
    p.add_argument("--data-dir", default="",
                   help="dir of *.bin token shards; empty = synthetic data")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="steps between saves (0 = only at the end)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--elastic", action="store_true",
                   help="negotiate the mesh shape from the newest "
                        "checkpoint manifest (schema v2 records the "
                        "saved mesh) instead of requiring the flags to "
                        "match it: a restart on a different device/"
                        "process count keeps the recorded ICI axes and "
                        "re-derives the DCN data axis from the "
                        "surviving fleet, re-placing every leaf under "
                        "the new sharding and replaying the data "
                        "stream from the step index; requires --resume "
                        "to have any effect")
    p.add_argument("--emergency-dir", default="",
                   help="directory for preemption emergency checkpoints "
                        "(default: the checkpoint dir); --resume considers "
                        "both and restores the newest verified step")
    p.add_argument("--anomaly-factor", type=float, default=0.0,
                   help="loss-anomaly guard: roll back to the last good "
                        "checkpoint when a synced loss exceeds this factor "
                        "times the running median (NaN/Inf always trip); "
                        "0 disables the guard")
    p.add_argument("--max-rollbacks", type=int, default=3,
                   help="abort (exit 4) after this many consecutive "
                        "anomaly rollbacks without a clean window")
    p.add_argument("--skip-anomalous-window", action="store_true",
                   help="on anomaly rollback, resume the data stream after "
                        "the offending window instead of replaying it")
    p.add_argument("--model-opt", action="append", default=[],
                   metavar="K=V",
                   help="ModelConfig override, repeatable (e.g. "
                        "--model-opt fused_ce=true --model-opt "
                        "remat_policy=dots); values coerce like YAML "
                        "scalars")
    p.add_argument("--precision", choices=["auto", "f32", "bf16"],
                   default="auto",
                   help="precision policy (train/precision.py): f32 "
                        "master params + optimizer state always; bf16 "
                        "casts compute/activations (softmax and CE "
                        "accumulation stay f32); auto keeps the model "
                        "config's own dtypes")
    p.add_argument("--remat-policy",
                   choices=["none", "dots", "full"], default="",
                   help="rematerialization of the transformer block: "
                        "none saves every activation (fastest step, "
                        "largest memory), dots saves MXU outputs and "
                        "recomputes elementwise ops, full recomputes "
                        "whole blocks (max memory savings); default: "
                        "the model config's policy")
    p.add_argument("--profile-dir", default="",
                   help="capture a jax.profiler trace of steady-state "
                        "steps into this directory (view with "
                        "tensorboard/xprof; SURVEY.md §5 tracing "
                        "obligation)")
    p.add_argument("--sync-every", type=int, default=0,
                   help="steps between device->host metric syncs; also "
                        "the in-flight bound of the pipelined loop "
                        "(0 = --log-every)")
    p.add_argument("--prefetch", type=int, default=2,
                   help="device-prefetch buffer depth (0 disables the "
                        "background producer + jax.device_put staging)")
    p.add_argument("--compile-cache-dir", default="",
                   help="JAX persistent compilation cache directory; "
                        "reused across runs so restarts skip XLA "
                        "recompilation")
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--json-logs", action="store_true")
    p.add_argument("--device-ms-per-row", type=float, default=0.0,
                   help="deterministic per-step device-time floor: this "
                        "many milliseconds per LOCAL batch row, slept "
                        "off (remainder only — real compute overlaps "
                        "it) before each dispatch. The train-loop "
                        "analogue of cloudsim's op_latency knob: models "
                        "the accelerator each CPU process stands in "
                        "for, so scale-out concurrency is measurable "
                        "without a cloud. 0 = off")
    p.add_argument("--report-json", default="",
                   help="write a machine-readable run report (per-step "
                        "losses, steps/s, aggregate tokens/s, process "
                        "count, preemption outcome) to this path; "
                        "process 0 writes, other ranks skip — the "
                        "scale-out harness and CI evidence read it")
    p.add_argument("--trace-jsonl", default="",
                   help="append train.* span events and the goodput "
                        "ledger (chip-time categories partitioning the "
                        "run's wall window) as JSON lines to this path; "
                        "under multi-process runs every rank derives its "
                        "own file (PATH gains .rankN before the "
                        "extension) so one flag serves the whole "
                        "launch_trainers fleet. Merge onto the fleet "
                        "timeline with `tk8s trace merge`")
    p.add_argument("--distributed", choices=["auto", "on", "off"],
                   default="auto")
    p.add_argument("--dcn-sync", choices=["auto", "fused", "xla"],
                   default="auto",
                   help="cross-process gradient exchange: 'fused' builds "
                        "the step as one bucketed all-reduce per step "
                        "(parallel/multihost.make_fused_dcn_step — the "
                        "DCN-friendly DDP layout; needs a pure "
                        "data-parallel mesh), 'xla' lets GSPMD insert "
                        "per-parameter psums (the ICI-friendly layout), "
                        "'auto' picks fused whenever the mesh supports "
                        "it under multi-process runs")
    p.add_argument("--dry-run", action="store_true",
                   help="build everything, run one step, exit")
    return p


def _maybe_init_distributed(mode: str, log) -> None:
    """JobSet workers carry JAX_COORDINATOR_ADDRESS + TPU_WORKER_ID
    (topology/jobset.py:53-70); initialize jax.distributed from them.

    On CPU platforms the gloo collectives implementation is selected
    FIRST — on jax 0.4.x that is a config update (the env var is not
    read), and without it every cross-process CPU program dies at
    compile time. Raises :class:`DistributedEnvError` on malformed env
    and :class:`..parallel.multihost.MultiHostUnavailable` (typed
    reason) when the environment cannot host cross-process collectives;
    ``main`` turns the latter into EXIT_UNSUPPORTED — a loud skip, never
    an abort."""
    import jax

    if mode == "off":
        return
    denv = parse_distributed_env()
    if mode == "auto" and denv is None:
        return
    # The gloo selection must consider the CONFIG as well as the env
    # var (conftest/sitecustomize set the config; a bare CPU box may
    # set neither). Explicit cpu -> gloo is mandatory (typed skip when
    # this jax cannot); unset/auto -> best-effort, so a TPU pod whose
    # jaxlib lacks gloo still initializes instead of skipping.
    platforms = (os.environ.get("JAX_PLATFORMS") or "").strip() or (
        getattr(jax.config, "jax_platforms", None) or "")
    if "cpu" in platforms or not platforms:
        from ..parallel.multihost import (
            MultiHostUnavailable, enable_cpu_collectives)

        try:
            enable_cpu_collectives()
        except MultiHostUnavailable as e:
            if "cpu" in platforms:
                raise
            # Auto-detect platform: a TPU pod does not need gloo, but
            # if the backend resolves to CPU this run will crash in
            # XLA instead of skipping — say so NOW, with the fix.
            log.log("warn", "no CPU collectives in this jax; if the "
                    "backend resolves to CPU this run will fail — set "
                    "JAX_PLATFORMS=cpu for the typed skip",
                    reason=e.reason)
    if denv is None:
        # --distributed on without the JobSet env: let jax auto-detect
        # (it knows the GKE TPU pod metadata).
        log.log("info", "jax.distributed init (auto-detect)")
        jax.distributed.initialize()
        return
    log.log("info", "jax.distributed init",
            coordinator=denv.coordinator, process_id=denv.process_id,
            num_processes=denv.num_processes)
    jax.distributed.initialize(
        coordinator_address=denv.coordinator,
        num_processes=denv.num_processes, process_id=denv.process_id)


def _distributed_shutdown(n_processes: int) -> None:
    """Synchronized teardown on every clean exit path: rank 0 hosts the
    coordination service, so the barrier keeps it alive until every rank
    is done, and the explicit shutdown stops each client's error-poll
    thread — otherwise the first-exiting rank's teardown makes its peers
    abort with a fatal 'leader task died' from inside the coordination
    client, turning a clean rc into a crash."""
    if n_processes <= 1:
        return
    import jax

    from ..parallel.multihost import barrier

    try:
        barrier("tk8s-exit")
    # tk8s-lint: disable=TK8S106(a peer crashed mid-barrier: exiting
    # loudly with our own rc is all that is left to do)
    except Exception:
        pass
    try:
        jax.distributed.shutdown()
    # tk8s-lint: disable=TK8S106(shutdown after a dead coordinator
    # raises; the process is exiting either way and rc is already set)
    except Exception:
        pass


def _batches(args, config, batch_size: int, seq_len: int):
    if args.data_dir:
        from .data import ShardedTokenPipeline

        return ShardedTokenPipeline(
            args.data_dir, batch_size, seq_len).batches()
    from .data import synthetic_batches

    gen = synthetic_batches(config.vocab_size, batch_size, seq_len)
    return gen


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    from ..utils.logging import Logger

    log = Logger(json_mode=args.json_logs)
    crash_rank = os.environ.get("TK8S_TEST_CRASH_RANK")
    if crash_rank is not None and crash_rank == os.environ.get("TPU_WORKER_ID"):
        # Deterministic startup-death injection (tests only): models a
        # worker lost to an import error or port race BEFORE it joins the
        # coordination service, so what its peers experience is the
        # launcher's fail-fast reap, not a burned timeout.
        log.log("error", "TK8S_TEST_CRASH_RANK: injected startup crash",
                rank=crash_rank)
        return 3
    # Mid-run death injection (chaos workload arms): the named rank
    # hard-exits at the first sync window >= start_step + N — rank 0
    # models coordinator loss, any other rank a plain worker death.
    # os._exit on purpose: a real crash runs no finally blocks.
    crash_step_env = os.environ.get("TK8S_TEST_CRASH_STEP")
    crash_step = int(crash_step_env) if crash_step_env else None
    crash_step_rank = os.environ.get("TK8S_TEST_CRASH_STEP_RANK", "0")
    try:
        _maybe_init_distributed(args.distributed, log)
    except DistributedEnvError as e:
        log.log("error", "malformed distributed environment", error=str(e))
        return EXIT_CONFIG
    except Exception as e:
        from ..parallel.multihost import EXIT_UNSUPPORTED, MultiHostUnavailable

        if not isinstance(e, MultiHostUnavailable):
            raise
        # Loud, typed skip — the harness contract: an environment that
        # cannot host cross-process collectives must say so and step
        # aside, never abort or masquerade as a training failure.
        log.log("error", "multi-process harness unavailable; skipping",
                reason=e.reason, error=str(e))
        return EXIT_UNSUPPORTED

    import jax

    from ..models import get_config
    from ..ops.ring_attention import make_ring_attention
    from ..parallel import MeshConfig, create_mesh
    from ..parallel.mesh import describe_mesh
    from .trainer import (
        aot_compile_step, enable_compile_cache, init_state, make_optimizer,
        make_train_step)
    from .mfu import flops_per_token, mfu as compute_mfu

    from ..config.config import parse_scalar

    overrides = {}
    for item in args.model_opt:
        key, sep, value = item.partition("=")
        if not sep:
            raise SystemExit(f"--model-opt expects K=V, got {item!r}")
        overrides[key] = parse_scalar(value)
    if args.compile_cache_dir:
        cache = enable_compile_cache(args.compile_cache_dir)
        log.log("info", "persistent compile cache",
                dir=cache or "(unsupported by this jax)")
    if args.remat_policy:
        # The dedicated flag wins outright: it both selects the policy
        # and arms/disarms remat itself, so a stale --model-opt
        # remat=false cannot silently turn "dots"/"full" into a no-op.
        overrides["remat_policy"] = args.remat_policy
        overrides["remat"] = args.remat_policy != "none"
    config = get_config(args.model, **overrides)
    from .precision import apply_policy, policy_of, remat_policy_of

    config = apply_policy(config, args.precision)
    log.log("info", "precision policy", policy=policy_of(config),
            compute_dtype=config.dtype, param_dtype=config.param_dtype,
            remat=remat_policy_of(config))
    seq_len = args.seq_len or config.max_seq_len
    n_processes = jax.process_count()
    mesh_cfg = MeshConfig(
        # 0 = auto: one DCN shard per process multi-process (filled in
        # by default_mesh_config), a plain data=1 mesh single-process.
        data=args.data or (0 if n_processes > 1 else 1),
        stage=args.stage, fsdp=args.fsdp, seq=args.seq,
        expert=args.expert, tensor=args.tensor)
    # --elastic: the mesh shape is negotiated from the newest manifest's
    # recorded mesh section (schema v2), not taken from the flags — the
    # fleet that survived a slice loss decides the restore shape. The
    # peek is pure file I/O on the shared checkpoint dirs, so every rank
    # derives the same answer without a collective. A format-1 manifest
    # (no recorded shape) falls back to the flags with a warning; a
    # fleet the saved shapes cannot divide is a typed ReshapeError and
    # the same loud rc-2 as every config error.
    elastic_reshard = None
    elastic_batch = 0
    if args.elastic and args.resume and (
            args.checkpoint_dir or args.emergency_dir):
        from .checkpoint import ReshapeError, peek_newest_manifest
        from .resilience import negotiate_mesh_config

        peeked = peek_newest_manifest(
            args.checkpoint_dir or None, args.emergency_dir or None)
        saved_mesh = peeked[1].get("mesh") if peeked else None
        if saved_mesh is None:
            log.log("warn", "--elastic: no recorded mesh to negotiate "
                    "from (no checkpoint yet, or a format-1 manifest); "
                    "using the flag-derived mesh")
        else:
            try:
                mesh_cfg = negotiate_mesh_config(
                    saved_mesh, n_processes=n_processes,
                    n_devices=jax.device_count())
            except ReshapeError as e:
                log.log("error", "elastic shape negotiation failed",
                        error=str(e))
                _distributed_shutdown(n_processes)
                return EXIT_CONFIG
            elastic_batch = int(saved_mesh.get("global_batch") or 0)
            saved_shape = dict(saved_mesh.get("axes") or {})
            reshaped = (
                int(saved_mesh.get("n_devices") or 0) != jax.device_count()
                or int(saved_mesh.get("n_processes") or 0) != n_processes)
            if reshaped:
                elastic_reshard = {
                    "step": int(peeked[0]),
                    "from_axes": saved_shape,
                    "from_devices": int(saved_mesh.get("n_devices") or 0),
                    "from_processes": int(
                        saved_mesh.get("n_processes") or 0),
                    "to_devices": jax.device_count(),
                    "to_processes": n_processes,
                }
            log.log("info", "elastic mesh negotiated",
                    saved_axes=saved_shape,
                    negotiated=repr(mesh_cfg), reshaped=reshaped,
                    step=int(peeked[0]))
    if n_processes > 1:
        # Hybrid DCN×ICI placement: the data axis spans processes (one
        # DCN shard per host by default), ICI axes stay within each
        # host's devices. Rank-tag every log line and tk8s_train_*
        # metric series so N workers' telemetry stays attributable.
        from ..parallel import multihost
        from ..utils import metrics as _metrics_mod

        log.bind(process=jax.process_index())
        _metrics_mod.set_default_labels(
            process_id=str(jax.process_index()))
        mesh_cfg = multihost.default_mesh_config(mesh_cfg)
        try:
            mesh = multihost.create_hybrid_mesh(mesh_cfg)
        except multihost.MeshPlacementError as e:
            # The same contract as every sibling config error: one
            # clean line, rc 2, synchronized teardown — never a raw
            # traceback that skips the exit barrier.
            log.log("error", "hybrid mesh placement rejected",
                    error=str(e))
            _distributed_shutdown(n_processes)
            return EXIT_CONFIG
    else:
        mesh = create_mesh(mesh_cfg)
    n_devices = mesh.size
    batch_shards = max(mesh.shape["data"] * mesh.shape["fsdp"], 1)
    # The recorded global batch wins over the shard-derived default under
    # --elastic: replay skips `step` whole batches, so the stream only
    # lines up when the global batch survives the reshape unchanged.
    batch_size = args.batch_size or elastic_batch or 4 * batch_shards
    log.log("info", "trainer starting", model=config.name,
            mesh=describe_mesh(mesh), devices=n_devices,
            processes=n_processes, batch=batch_size,
            seq_len=seq_len, steps=args.steps)

    # Training flight recorder: every rank writes its own clock-anchored
    # trace file (launch_trainers passes identical args to all ranks, so
    # the per-rank name is derived HERE from the process index) and
    # attributes its wall time into the closed train goodput vocabulary.
    # flush_each: train segments are window-scale, and a rank killed
    # mid-run (chaos arms) must leave its booked ledger on disk.
    tracer = None
    goodput = None
    if args.trace_jsonl:
        from ..utils.trace import GoodputRecorder, TraceWriter

        rank = jax.process_index()
        trace_path = args.trace_jsonl
        if n_processes > 1:
            root, ext = os.path.splitext(trace_path)
            trace_path = f"{root}.rank{rank}{ext or '.jsonl'}"
        tracer = TraceWriter(trace_path, f"trainer:rank{rank}",
                             clock=time.perf_counter)
        goodput = GoodputRecorder("train", clock=time.perf_counter,
                                  writer=tracer, flush_each=True)
        log.log("info", "trace jsonl", path=trace_path)

    if batch_size % batch_shards:
        log.log("error", "global batch must divide the data*fsdp axes",
                batch=batch_size, shards=batch_shards)
        _distributed_shutdown(n_processes)
        return EXIT_CONFIG
    stages = mesh.shape["stage"]
    if stages > 1:
        # The per-stage kernel shard_maps split each microbatch over
        # (data, fsdp): validate here so misconfigurations are a friendly
        # error, not a shard_map traceback from deep inside tracing.
        m = args.microbatches or stages
        if batch_size % m or (batch_size // m) % batch_shards:
            log.log("error",
                    "batch/microbatches must divide the data*fsdp axes "
                    "under pipeline stages",
                    batch=batch_size, microbatches=m, shards=batch_shards)
            _distributed_shutdown(n_processes)
            return EXIT_CONFIG

    attention_fn = None
    if args.ring_attention and mesh.shape["seq"] == 1:
        # seq > 1 meshes get ring automatically (trainer._resolve_attention,
        # incl. the nested-under-pipeline form); this flag covers the
        # unusual request for ring on an unsharded sequence.
        ring = make_ring_attention(mesh)
        attention_fn = lambda q, k, v, positions: ring(q, k, v)

    opt = make_optimizer(
        learning_rate=args.learning_rate, warmup_steps=args.warmup_steps,
        decay_steps=max(args.steps, args.warmup_steps + 1))
    state = init_state(config, mesh, opt)
    # Gradient-exchange layout: under multi-process runs a pure
    # data-parallel mesh takes the fused DCN sync — local grads, ONE
    # bucketed all-reduce per step — instead of GSPMD's per-parameter
    # psums, whose per-collective DCN latency serializes the step
    # (parallel/multihost.make_fused_dcn_step). Sharded-param meshes and
    # single-process runs keep the XLA-partitioned step.
    dcn_sync = "xla"
    if args.dcn_sync != "xla" and n_processes > 1:
        from ..parallel.multihost import (
            make_fused_dcn_step, supports_fused_dcn)

        # Everything the fused step cannot honor blocks it — silently
        # dropping a requested feature (ring attention, gradient
        # accumulation) would change the run's memory/compute profile
        # with nothing in the logs; an explicit --dcn-sync fused that
        # meets a blocker is a loud rc-2, same as every config error.
        blockers = []
        if attention_fn is not None:
            blockers.append("--ring-attention (the fused step computes "
                            "dense attention)")
        if args.microbatches > 1:
            blockers.append("--microbatches > 1 (the fused step takes "
                            "one backward per step)")
        if not supports_fused_dcn(mesh):
            blockers.append("a non-pure-data-parallel mesh (every "
                            "non-data axis must be 1)")
        if not blockers:
            dcn_sync = "fused"
        elif args.dcn_sync == "fused":
            log.log("error",
                    "fused DCN sync unavailable: " + "; ".join(blockers),
                    mesh=describe_mesh(mesh))
            _distributed_shutdown(n_processes)
            return EXIT_CONFIG
    if dcn_sync == "fused":
        step_fn = make_fused_dcn_step(config, mesh, opt)
    else:
        step_fn = make_train_step(
            config, mesh, opt, attention_fn=attention_fn,
            microbatches=args.microbatches)
    if n_processes > 1:
        log.log("info", "dcn gradient sync", mode=dcn_sync)

    from .checkpoint import CheckpointManager, mesh_spec_of
    from .resilience import (
        EXIT_RESUME, AnomalyAbortedError, LossAnomalyGuard, PreemptionGuard,
        run_resilient)

    # Every save from here on records the live shape in the manifest
    # (schema v2): the NEXT restart — elastic or not — knows what mesh
    # the bytes were placed under without trusting its own flags.
    live_spec = mesh_spec_of(mesh, n_processes=n_processes,
                             global_batch=batch_size)
    ckpt = None
    em_ckpt = None
    if args.checkpoint_dir:
        ckpt = CheckpointManager(args.checkpoint_dir,
                                 single_controller=n_processes > 1,
                                 mesh_spec=live_spec)
    if args.emergency_dir and (
            ckpt is None
            or os.path.abspath(args.emergency_dir) != ckpt.directory):
        # Path-normalized: two orbax managers on one directory would race
        # each other's GC/finalize and double-list every resume candidate.
        em_ckpt = CheckpointManager(args.emergency_dir,
                                    single_controller=n_processes > 1,
                                    mesh_spec=live_spec)
    if n_processes > 1:
        # Single-writer-per-shard coordination: process 0 writes (the DCN
        # axis carries only replicated state, so rank 0 holds every
        # byte), every rank barriers on the commit, restores re-place
        # leaves from process-local data (parallel/multihost.py).
        from ..parallel.multihost import CoordinatedCheckpoint

        if ckpt is not None:
            ckpt = CoordinatedCheckpoint(ckpt)
        if em_ckpt is not None:
            em_ckpt = CoordinatedCheckpoint(em_ckpt)
    start_is_checkpointed = False
    if args.resume and (ckpt is not None or em_ckpt is not None):
        # The newest *verified* step wins, scheduled or emergency — a torn
        # emergency save is quarantined and resume falls back to the last
        # scheduled checkpoint automatically. All-corrupt is a typed,
        # loud CheckpointIntegrityError, not a silent retrain.
        from .checkpoint import restore_newest_verified

        # A resume restore is recovery work re-establishing state a
        # fault interrupted — the ledger books it rollback_replay, so
        # the kill->resume storyline never shows recovery as `step`.
        # When --elastic changed the shape, the window is a reshard
        # instead: re-placing every leaf under the new sharding is
        # neutral capacity-adaptation work (like migrate_*), not waste,
        # and the goodput report must show it honestly.
        restore_cat = ("reshard" if elastic_reshard is not None
                       else "rollback_replay")
        reshard_t0 = time.perf_counter()
        if goodput is not None:
            goodput.transition(restore_cat)
        try:
            state, best, best_step = restore_newest_verified(
                state, ckpt, em_ckpt)
        except FileNotFoundError:
            pass  # nothing saved yet: a fresh start under --resume is fine
        else:
            # The restored step was just verified end-to-end; when it
            # lives in the scheduled dir, the guard's baseline check can
            # skip re-hashing it.
            start_is_checkpointed = best is ckpt
            if elastic_reshard is not None:
                elastic_reshard["seconds"] = round(
                    time.perf_counter() - reshard_t0, 6)
                if tracer is not None:
                    tracer.event("train.reshard", goodput.clock(),
                                 step=int(state.step), **{
                                     k: v for k, v in
                                     elastic_reshard.items()
                                     if k not in ("from_axes", "step")})
                log.log("info", "elastic reshard restore",
                        step=int(state.step), **{
                            k: v for k, v in elastic_reshard.items()
                            if k not in ("from_axes", "step")})
            if tracer is not None:
                tracer.event("train.restore", goodput.clock(),
                             step=int(state.step), rollback=False)
            log.log("info", "resumed", step=int(state.step),
                    source=best.directory,
                    emergency=best is em_ckpt)
        if goodput is not None:
            goodput.transition("idle")

    fpt = flops_per_token(config, seq_len)
    from ..topology.slices import peak_bf16_tflops_for_kind

    # 0 off-TPU: the mfu field is then omitted rather than wrong.
    peak = peak_bf16_tflops_for_kind(
        jax.devices()[0].device_kind) * n_devices

    start_step = int(state.step)
    tokens_per_step = batch_size * seq_len
    # --device-ms-per-row: the floor scales with the rows THIS process
    # owns, so halving the per-host shard halves the modeled device
    # time — exactly how a real accelerator behaves under data-parallel
    # scale-out. Ownership comes from the batch sharding (NOT
    # batch/n_processes: on a stage-spanning DCN mesh every host
    # computes the full batch and the floor must not shrink).
    if n_processes > 1:
        from ..parallel import multihost
        from .trainer import batch_spec

        local_rows = multihost.local_batch_rows(
            mesh, batch_spec(), batch_size)
    else:
        local_rows = batch_size
    step_floor = args.device_ms_per_row * local_rows / 1e3
    # The tokens COUNTER ticks by this rank's shard, so summing the
    # rank-tagged series over process_id is the true fleet rate (every
    # rank counting the global batch would multiply it by N). The
    # report/log rates below stay global-batch-derived — they are the
    # run's aggregate, not this rank's share.
    local_tokens_per_step = local_rows * seq_len
    last_loss = None  # None until the first sync: never log a fake NaN
    tracing = False
    max_steps = max(args.steps - start_step, 0)
    if args.dry_run:
        max_steps = min(max_steps, 1)
    target_step = start_step + max_steps
    sync_every = 1 if args.dry_run else \
        max(args.sync_every or args.log_every, 1)

    # Step-pipelined hot path (train/pipeline.py): steps dispatch back to
    # back with the next batch's host->device transfer already in flight
    # (DevicePrefetch) and ONE host sync per window — never one per step.
    # The resilient driver (train/resilience.py) rebuilds this stream at a
    # rolled-back step by deterministic replay: same source, same seed,
    # skip to the step index.
    from .data import DevicePrefetch
    from .trainer import batch_spec
    from jax.sharding import NamedSharding

    # Per-process input sharding: every rank runs the same deterministic
    # host stream (same seed / same shard files), but only this rank's
    # row block is ever staged to devices — the global jax.Array is
    # assembled from process-local data, so no host transfers rows it
    # does not own. Single-process keeps the plain sharded device_put.
    place = None
    if n_processes > 1:
        from ..parallel import multihost

        place = multihost.make_batch_placer(mesh, batch_spec())

    def make_batches(start: int):
        gen = _batches(args, config, batch_size, seq_len)
        if start:
            log.log("info", "skipping consumed batches", count=start)
            for _ in range(start):
                next(gen)
        host = ({"tokens": b["tokens"]} for b in gen)
        if args.prefetch > 0:
            pf = DevicePrefetch(
                host,
                sharding=(None if place is not None
                          else NamedSharding(mesh, batch_spec())),
                place=place, buffer_size=args.prefetch)
            return pf, pf
        if place is not None:
            return (place(b) for b in host), None
        return host, None

    first_iter, first_pf = (None, None)
    if max_steps:
        # AOT compile against the exact first batch: the compile cost is
        # measured and attributed (lower vs XLA) instead of silently
        # diluting the first window, and the loop cannot retrace.
        first_iter, first_pf = make_batches(start_step)
        first = next(first_iter, None)
        if first is None:
            max_steps = 0
            target_step = start_step
        else:
            if goodput is not None:
                goodput.transition("compile")
            step_fn, timings = aot_compile_step(
                step_fn, state, first, config_name=config.name)
            if goodput is not None:
                t1 = goodput.clock()
                if tracer is not None:
                    tracer.event(
                        "train.compile", goodput.state_since,
                        t1 - goodput.state_since,
                        lower_s=round(timings.lower_seconds, 6),
                        compile_s=round(timings.compile_seconds, 6))
                goodput.transition("idle", t1)
            from .trainer import memory_stats

            mem = memory_stats(step_fn)
            mem_fields = {}
            if mem is not None:
                mem_fields = dict(
                    temp_mib=round(mem.temp_bytes / 2**20, 1),
                    peak_mib=round(mem.peak_bytes / 2**20, 1))
            log.log("info", "train step compiled",
                    lower_s=round(timings.lower_seconds, 3),
                    compile_s=round(timings.compile_seconds, 3),
                    cache_dir=timings.cache_dir or "", **mem_fields)
            first_iter = itertools.chain([first], first_iter)
    holder = {"it": first_iter, "pf": first_pf}

    def batches_factory(pos: int):
        if holder["it"] is not None and pos == start_step:
            out = (holder["it"], holder["pf"])
            holder["it"] = None
            return out
        it, pf = make_batches(pos)
        holder["pf"] = pf  # keep on_sync's wait accounting on the live one
        return it, pf

    # Per-window (steps, seconds) pairs: the report's steady-state rate
    # is computed over every window but the first, which carries the
    # jit compile and first-batch staging — whole-run wall answers "how
    # long did this take", steady answers "how fast does it train".
    sync_windows: list = []

    def on_sync(gstep, cur_state, window_losses, window_dt):
        nonlocal last_loss
        if crash_step is not None \
                and gstep >= start_step + crash_step \
                and str(jax.process_index()) == crash_step_rank:
            log.log("error",
                    "TK8S_TEST_CRASH_STEP: injected mid-run death",
                    step=gstep, rank=crash_step_rank)
            os._exit(3)
        sync_windows.append((len(window_losses), window_dt))
        last_loss = window_losses[-1]
        tps = tokens_per_step * len(window_losses) / max(window_dt, 1e-9)
        fields = dict(step=gstep, loss=round(last_loss, 4),
                      tokens_per_sec=round(tps, 1),
                      tflops=round(tps * fpt / 1e12, 2))
        if peak:
            fields["mfu"] = round(compute_mfu(tps, config, seq_len, peak), 4)
        if holder["pf"] is not None:
            fields["prefetch_wait_s"] = round(holder["pf"].wait_seconds, 4)
        log.log("info", "train", **fields)

    def on_checkpoint(gstep, kind):
        msg = ("emergency checkpoint saved" if kind == "emergency"
               else "checkpoint saved")
        log.log("info" if kind != "emergency" else "warn", msg, step=gstep)

    guard = (LossAnomalyGuard(factor=args.anomaly_factor)
             if args.anomaly_factor > 0 else None)
    if n_processes > 1:
        # The stop decision must be a cross-process AGREEMENT: signal
        # delivery skews across workers, and a rank that stops
        # dispatching while its peers enter the next step's collective
        # deadlocks the slice. One tiny all-reduce per sync window keeps
        # every rank stopping on the same step (parallel/multihost.py).
        from ..parallel.multihost import SyncedPreemptionGuard

        preempt = SyncedPreemptionGuard(check_every=sync_every)
    else:
        preempt = PreemptionGuard()
    try:
        preempt.install()
    except ValueError:  # not the main thread (embedded run): unguarded
        preempt = None

    report = None
    aborted = None

    def write_report(outcome: str) -> None:
        """--report-json: the machine-readable record the scale-out
        harness, goodput runner, and CI evidence read. Rank 0 only."""
        if not args.report_json or jax.process_index() != 0:
            return
        wall = max(time.perf_counter() - run_t0, 1e-9)
        steps_done = report.steps if report is not None else 0
        data = {
            "schema": 1,
            "model": config.name,
            "mesh": describe_mesh(mesh),
            "n_processes": n_processes,
            "dcn_sync": dcn_sync,
            "process_id": jax.process_index(),
            "devices": n_devices,
            "global_batch": batch_size,
            "seq_len": seq_len,
            "device_ms_per_row": args.device_ms_per_row,
            "start_step": start_step,
            "target_step": target_step,
            "steps": steps_done,
            "losses": list(report.losses) if report is not None else [],
            "sync_points": report.sync_points if report is not None else 0,
            "rollbacks": report.rollbacks if report is not None else 0,
            "interrupted": bool(report is not None and report.interrupted),
            "emergency_step": (report.emergency_step
                               if report is not None else None),
            "wall_seconds": round(wall, 3),
            "steps_per_sec": round(steps_done / wall, 4),
            "tokens_per_sec": round(
                steps_done * tokens_per_step / wall, 1),
            "outcome": outcome,
            "elastic": bool(args.elastic),
            "reshard": elastic_reshard,
        }
        steady = sync_windows[1:]
        if steady:
            s_steps = sum(n for n, _ in steady)
            s_secs = max(sum(dt for _, dt in steady), 1e-9)
            data["steady_steps_per_sec"] = round(s_steps / s_secs, 4)
            data["steady_tokens_per_sec"] = round(
                s_steps * tokens_per_step / s_secs, 1)
        if goodput is not None:
            data["goodput"] = goodput.snapshot()
        parent = os.path.dirname(os.path.abspath(args.report_json))
        os.makedirs(parent, exist_ok=True)
        tmp = args.report_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1)
            f.write("\n")
        os.replace(tmp, args.report_json)
        log.log("info", "run report written", path=args.report_json)

    run_t0 = time.perf_counter()
    try:
        if max_steps:
            if args.profile_dir and not args.dry_run:
                # The compile step is already excluded (AOT above), so the
                # whole loop is steady state — trace all of it. Single-
                # window runs get a trace too.
                jax.profiler.start_trace(args.profile_dir)
                tracing = True
                log.log("info", "profiler tracing", dir=args.profile_dir)
            try:
                state, report = run_resilient(
                    step_fn, state, batches_factory,
                    ckpt=ckpt, emergency_ckpt=em_ckpt or ckpt,
                    target_step=target_step, start_step=start_step,
                    sync_every=sync_every,
                    checkpoint_every=(args.checkpoint_every if ckpt else 0),
                    guard=guard, max_rollbacks=args.max_rollbacks,
                    skip_anomalous_window=args.skip_anomalous_window,
                    start_is_checkpointed=start_is_checkpointed,
                    preemption=preempt,
                    tokens_per_step=local_tokens_per_step,
                    config_name=config.name,
                    on_sync=on_sync, on_checkpoint=on_checkpoint,
                    step_floor_seconds=step_floor, goodput=goodput)
            except AnomalyAbortedError as e:
                aborted = e
                log.log("error", "anomaly guard aborted the run",
                        error=str(e), step=e.anomaly.step,
                        reason=e.anomaly.reason)
            else:
                if report.rollbacks:
                    log.log("warn", "anomaly rollbacks taken",
                            rollbacks=report.rollbacks,
                            restored_steps=report.restored_steps)
                if (report.steps < max_steps and not report.interrupted):
                    log.log("warn", "data exhausted before requested steps",
                            done=start_step + report.steps, want=args.steps)
    finally:
        if holder["it"] is not None and holder["pf"] is not None:
            holder["pf"].close()
        if tracing:
            # try/finally: the trace matters MOST when the run dies (OOM,
            # interrupt) — sync so it holds completed device work, then
            # flush it regardless of how the loop exited. The sync itself
            # re-raises on a failed computation; that must not cost the
            # trace (or mask the original exception).
            try:
                jax.block_until_ready(state.params)
            # tk8s-lint: disable=TK8S106(the sync re-raises a failed
            # computation; that must not cost the trace or mask the
            # original exception unwinding through this finally)
            except Exception:
                pass
            jax.profiler.stop_trace()
            log.log("info", "profiler trace written", dir=args.profile_dir)
        if preempt is not None:
            preempt.uninstall()
        if goodput is not None:
            # Close the ledger in the finally for the same reason as the
            # profiler trace: the chip-second attribution matters MOST on
            # the runs that die, and close() is what makes the categories
            # tile the recorded window exactly (partition oracle).
            goodput.close()
        if tracer is not None:
            tracer.close()

    final_loss = round(last_loss, 4) if last_loss is not None else "n/a"
    if aborted is not None:
        # The state tree was donated into the failed window: do not touch
        # it (no final save) — the last good checkpoint is the artifact.
        for mgr in (ckpt, em_ckpt):
            if mgr is not None:
                mgr.close()
        write_report("anomaly-abort")
        log.log("info", "trainer done", final_loss=final_loss,
                outcome="anomaly-abort")
        _distributed_shutdown(n_processes)
        return EXIT_ANOMALY
    if report is not None and report.interrupted:
        # Preemption warning honored: the emergency checkpoint (manifest-
        # committed) is on disk; exit with the resume code so the JobSet
        # restart policy relaunches with --resume instead of failing.
        for mgr in (ckpt, em_ckpt):
            if mgr is not None:
                mgr.close()
        log.log("warn", "trainer preempted; exiting for resume",
                step=start_step + report.steps,
                emergency_step=report.emergency_step,
                exit_code=EXIT_RESUME)
        write_report("preempted")
        log.log("info", "trainer done", final_loss=final_loss,
                outcome="preempted")
        _distributed_shutdown(n_processes)
        return EXIT_RESUME
    if ckpt:
        if ckpt.latest_step() != int(state.step):
            ckpt.save(int(state.step), state, wait=True, kind="final")
            log.log("info", "final checkpoint", step=int(state.step))
        ckpt.close()
    if em_ckpt is not None:
        em_ckpt.close()
    write_report("ok")
    log.log("info", "trainer done", final_loss=final_loss)
    _distributed_shutdown(n_processes)
    return 0


if __name__ == "__main__":
    sys.exit(main())
