"""Trainer entrypoint: ``python -m triton_kubernetes_tpu.train``.

This is the command the provisioned JobSets run (docs/guide/gcp-tpu,
modules/gcp_tpu.py training-job manifests): every worker starts the same
program, ``jax.distributed`` initializes from the env the JobSet injects
(``JAX_COORDINATOR_ADDRESS`` + ``TPU_WORKER_ID``/job completion index), and
the whole slice executes one SPMD program over the requested mesh.

Single-process runs (laptop smoke, one-host slice) skip distributed init
automatically. Data comes from the native sharded token pipeline when
``--data-dir`` is given (falls back to the pure-Python reader), else from
the synthetic Markov generator, so the entrypoint always has something to
train on — the BASELINE "cluster-up then train" gates assume that.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m triton_kubernetes_tpu.train",
        description="Bundled sharded trainer for the provisioned TPU slice.")
    p.add_argument("--model", default="llama3-bench",
                   help="config name from models.CONFIGS")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=0,
                   help="global batch across all chips "
                        "(0 = 4 per data*fsdp shard, fits any slice)")
    p.add_argument("--seq-len", type=int, default=0,
                   help="0 = the model's max_seq_len")
    p.add_argument("--learning-rate", type=float, default=3e-4)
    p.add_argument("--warmup-steps", type=int, default=100)
    # Mesh axes: -1 absorbs remaining devices (at most one axis).
    p.add_argument("--data", type=int, default=1)
    p.add_argument("--stage", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=-1)
    p.add_argument("--seq", type=int, default=1)
    p.add_argument("--expert", type=int, default=1)
    p.add_argument("--tensor", type=int, default=1)
    p.add_argument("--microbatches", type=int, default=0,
                   help="pipeline microbatches (0 = stage count)")
    p.add_argument("--ring-attention", action="store_true",
                   help="sequence-parallel attention (required when seq>1)")
    p.add_argument("--data-dir", default="",
                   help="dir of *.bin token shards; empty = synthetic data")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="steps between saves (0 = only at the end)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--model-opt", action="append", default=[],
                   metavar="K=V",
                   help="ModelConfig override, repeatable (e.g. "
                        "--model-opt fused_ce=true --model-opt "
                        "remat_policy=dots); values coerce like YAML "
                        "scalars")
    p.add_argument("--profile-dir", default="",
                   help="capture a jax.profiler trace of steady-state "
                        "steps into this directory (view with "
                        "tensorboard/xprof; SURVEY.md §5 tracing "
                        "obligation)")
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--json-logs", action="store_true")
    p.add_argument("--distributed", choices=["auto", "on", "off"],
                   default="auto")
    p.add_argument("--dry-run", action="store_true",
                   help="build everything, run one step, exit")
    return p


def _maybe_init_distributed(mode: str, log) -> None:
    """JobSet workers carry JAX_COORDINATOR_ADDRESS + TPU_WORKER_ID
    (topology/jobset.py:53-70); initialize jax.distributed from them."""
    import jax

    coord = os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    if mode == "off" or (mode == "auto" and not coord):
        return
    if not coord:
        # --distributed on without the JobSet env: let jax auto-detect
        # (it knows the GKE TPU pod metadata).
        log.log("info", "jax.distributed init (auto-detect)")
        jax.distributed.initialize()
        return
    worker = int(os.environ.get(
        "TPU_WORKER_ID", os.environ.get("JOB_COMPLETION_INDEX", "0")))
    num = int(os.environ.get("NUM_TPU_WORKERS", "0")) or None
    log.log("info", "jax.distributed init",
            coordinator=coord, process_id=worker, num_processes=num)
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=num, process_id=worker)


def _batches(args, config, batch_size: int, seq_len: int):
    if args.data_dir:
        from .data import ShardedTokenPipeline

        return ShardedTokenPipeline(
            args.data_dir, batch_size, seq_len).batches()
    from .data import synthetic_batches

    gen = synthetic_batches(config.vocab_size, batch_size, seq_len)
    return gen


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    from ..utils.logging import Logger

    log = Logger(json_mode=args.json_logs)
    _maybe_init_distributed(args.distributed, log)

    import jax

    from ..models import get_config
    from ..ops.ring_attention import make_ring_attention
    from ..parallel import MeshConfig, create_mesh
    from ..parallel.mesh import describe_mesh
    from .trainer import init_state, make_optimizer, make_train_step
    from .mfu import flops_per_token, mfu as compute_mfu

    from ..config.config import parse_scalar

    overrides = {}
    for item in args.model_opt:
        key, sep, value = item.partition("=")
        if not sep:
            raise SystemExit(f"--model-opt expects K=V, got {item!r}")
        overrides[key] = parse_scalar(value)
    config = get_config(args.model, **overrides)
    seq_len = args.seq_len or config.max_seq_len
    mesh_cfg = MeshConfig(
        data=args.data, stage=args.stage, fsdp=args.fsdp, seq=args.seq,
        expert=args.expert, tensor=args.tensor)
    mesh = create_mesh(mesh_cfg)
    n_devices = mesh.size
    batch_shards = max(mesh.shape["data"] * mesh.shape["fsdp"], 1)
    batch_size = args.batch_size or 4 * batch_shards
    log.log("info", "trainer starting", model=config.name,
            mesh=describe_mesh(mesh), devices=n_devices,
            process=jax.process_index(), batch=batch_size,
            seq_len=seq_len, steps=args.steps)

    if batch_size % batch_shards:
        log.log("error", "global batch must divide the data*fsdp axes",
                batch=batch_size, shards=batch_shards)
        return 2
    stages = mesh.shape["stage"]
    if stages > 1:
        # The per-stage kernel shard_maps split each microbatch over
        # (data, fsdp): validate here so misconfigurations are a friendly
        # error, not a shard_map traceback from deep inside tracing.
        m = args.microbatches or stages
        if batch_size % m or (batch_size // m) % batch_shards:
            log.log("error",
                    "batch/microbatches must divide the data*fsdp axes "
                    "under pipeline stages",
                    batch=batch_size, microbatches=m, shards=batch_shards)
            return 2

    attention_fn = None
    if args.ring_attention and mesh.shape["seq"] == 1:
        # seq > 1 meshes get ring automatically (trainer._resolve_attention,
        # incl. the nested-under-pipeline form); this flag covers the
        # unusual request for ring on an unsharded sequence.
        ring = make_ring_attention(mesh)
        attention_fn = lambda q, k, v, positions: ring(q, k, v)

    opt = make_optimizer(
        learning_rate=args.learning_rate, warmup_steps=args.warmup_steps,
        decay_steps=max(args.steps, args.warmup_steps + 1))
    state = init_state(config, mesh, opt)
    step_fn = make_train_step(
        config, mesh, opt, attention_fn=attention_fn,
        microbatches=args.microbatches)

    ckpt = None
    if args.checkpoint_dir:
        from .checkpoint import CheckpointManager

        ckpt = CheckpointManager(args.checkpoint_dir)
        if args.resume and ckpt.latest_step() is not None:
            state = ckpt.restore(state)
            log.log("info", "resumed", step=int(state.step))

    gen = _batches(args, config, batch_size, seq_len)
    fpt = flops_per_token(config, seq_len)
    from ..topology.slices import peak_bf16_tflops_for_kind

    # 0 off-TPU: the mfu field is then omitted rather than wrong.
    peak = peak_bf16_tflops_for_kind(
        jax.devices()[0].device_kind) * n_devices

    start_step = int(state.step)
    if start_step:
        # Resume: advance the data stream past what the checkpointed run
        # consumed so no batch is trained twice.
        log.log("info", "skipping consumed batches", count=start_step)
        for _ in range(start_step):
            next(gen)
    t0 = time.perf_counter()
    timed_from = start_step
    tokens_per_step = batch_size * seq_len
    last_loss = float("nan")
    tracing = False
    try:
        for i in range(start_step, args.steps):
            # Both sources yield int32 numpy [B, S+1]; jit places it on the
            # mesh directly, no eager host->device staging.
            state, metrics = step_fn(state, {"tokens": next(gen)["tokens"]})
            if i == start_step:
                # Restart the throughput window after the compile step so the
                # reported tokens/sec is steady-state, not compile-diluted.
                float(metrics["loss"])
                t0 = time.perf_counter()
                timed_from = i + 1
                if args.profile_dir and not args.dry_run \
                        and args.steps > start_step + 1:
                    # Steady-state steps only: the compile step would dwarf
                    # everything else in the trace.
                    jax.profiler.start_trace(args.profile_dir)
                    tracing = True
                    log.log("info", "profiler tracing", dir=args.profile_dir)
            if args.dry_run or (i + 1) % args.log_every == 0 \
                    or i + 1 == args.steps:
                last_loss = float(metrics["loss"])  # device sync
                dt = time.perf_counter() - t0
                done = i + 1 - timed_from
                tps = tokens_per_step * done / max(dt, 1e-9) if done else 0.0
                fields = dict(step=i + 1, loss=round(last_loss, 4),
                              tokens_per_sec=round(tps, 1),
                              tflops=round(tps * fpt / 1e12, 2))
                if peak:
                    fields["mfu"] = round(compute_mfu(
                        tps, config, seq_len, peak), 4)
                log.log("info", "train", **fields)
            if ckpt and args.checkpoint_every \
                    and (i + 1) % args.checkpoint_every == 0:
                ckpt.save(i + 1, state)
                log.log("info", "checkpoint saved", step=i + 1)
            if args.dry_run:
                break
    finally:
        if tracing:
            # try/finally: the trace matters MOST when the run dies (OOM,
            # interrupt) — sync so it holds completed device work, then
            # flush it regardless of how the loop exited. The sync itself
            # re-raises on a failed computation; that must not cost the
            # trace (or mask the original exception).
            try:
                jax.block_until_ready(state.params)
            except Exception:
                pass
            jax.profiler.stop_trace()
            log.log("info", "profiler trace written", dir=args.profile_dir)
    if ckpt:
        if ckpt.latest_step() != int(state.step):
            ckpt.save(int(state.step), state, wait=True)
            log.log("info", "final checkpoint", step=int(state.step))
        ckpt.close()
    log.log("info", "trainer done", final_loss=round(last_loss, 4))
    return 0


if __name__ == "__main__":
    sys.exit(main())
