"""Orbax checkpointing for train state.

The reference's only checkpoint/backup story is Heptio Ark over the whole
cluster (SURVEY.md §5) and the provisioning doc itself; workload-level
checkpoint/resume is new here. Orbax writes sharded arrays directly from
device memory (each host saves its shards — no gather), which is the only
viable path at 70B-class sizes, and restores into an abstract target tree
carrying the desired shardings.

Restore is **elastic**: the target tree's shardings, not the writer's,
decide the landed layout, so a job resumes onto a different mesh shape or
device count (slice shrunk by a dead host, or grown after repair) — the
trainer's ``--resume`` builds its target on whatever mesh it starts with.
Proven in tests/test_train.py::test_checkpoint_elastic_reshard_across_meshes:
save on 4 devices fsdp=4, resume on fsdp=2×tensor=2 and on 8-device
fsdp=8; training continues numerically identically (post-restore loss
matches the uninterrupted run to 1e-5 — cross-layout reduction orders
preclude bitwise claims).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """``state_like``: concrete or abstract (jax.eval_shape output whose
        leaves carry shardings) tree matching what was saved."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
