"""Orbax checkpointing for train state, hardened for preemption.

The reference's only checkpoint/backup story is Heptio Ark over the whole
cluster (SURVEY.md §5) and the provisioning doc itself; workload-level
checkpoint/resume is new here. Orbax writes sharded arrays directly from
device memory (each host saves its shards — no gather), which is the only
viable path at 70B-class sizes, and restores into an abstract target tree
carrying the desired shardings.

Restore is **elastic**: the target tree's shardings, not the writer's,
decide the landed layout, so a job resumes onto a different mesh shape or
device count (slice shrunk by a dead host, or grown after repair) — the
trainer's ``--resume`` builds its target on whatever mesh it starts with.
Proven in tests/test_train.py::test_checkpoint_elastic_reshard_across_meshes:
save on 4 devices fsdp=4, resume on fsdp=2×tensor=2 and on 8-device
fsdp=8; training continues numerically identically (post-restore loss
matches the uninterrupted run to 1e-5 — cross-layout reduction orders
preclude bitwise claims).

Restore is also **integrity-verified**: every committed save carries a
sidecar ``manifest.json`` inside its step directory — per-leaf tree
structure (path, shape, dtype), per-file sizes and SHA-256 content
checksums, and a whole-manifest digest. The manifest is written *after*
orbax finishes the step (atomic tmp+rename, fsync'd), so its presence is
the commit marker: a step without one is a save the process died inside.
``restore`` verifies the newest candidate first and, on a torn,
truncated, or bit-rotted step, **quarantines** it (rename into
``quarantine/``, never delete — it is postmortem evidence) and falls back
to the newest earlier step that verifies, automatically. Verification
failures and fallbacks are counted in the ``tk8s_train_checkpoint_*``
metric families (utils/metrics.py CATALOG).

Manifest **format 2** additionally versions the *mesh* into the
checkpoint: a ``mesh`` section recording the axis sizes the writer
trained under, its process/device counts, and the global batch — so a
restart can *negotiate* its shape from what survived instead of trusting
CLI flags (train/resilience.py ``negotiate_mesh_config``, the trainer's
``--elastic``). Format-1 manifests (no ``mesh`` section) stay fully
readable: verification and restore are format-agnostic, and
:func:`peek_newest_manifest` simply reports no recorded shape, which the
elastic path treats as "fall back to the flags" (documented in
docs/guide/fault-tolerance.md §Elastic reshaping).
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ..utils import metrics as _metrics

MANIFEST_NAME = "manifest.json"
QUARANTINE_DIR = "quarantine"
#: Current manifest schema; format 2 added the ``mesh`` section.
MANIFEST_FORMAT = 2
#: Formats this reader accepts (restore/verify are format-agnostic; the
#: only format-2 addition is *extra* data older readers ignore).
MANIFEST_FORMATS = (1, 2)


class CheckpointError(RuntimeError):
    """Base type for checkpoint-subsystem failures."""


class CheckpointIntegrityError(CheckpointError):
    """A step failed manifest verification (uncommitted save, torn
    manifest, truncated or bit-flipped file). ``reason`` is the bounded
    machine-readable label fed to
    ``tk8s_train_checkpoint_verify_failures_total``."""

    def __init__(self, message: str, reason: str = "corrupt"):
        super().__init__(message)
        self.reason = reason


class ReshapeError(CheckpointError):
    """Elastic shape negotiation failed: the surviving fleet cannot hold
    the recorded mesh (axes don't divide the device count, the ICI block
    no longer fits one process, or the manifest predates format 2 and
    carries no shape at all when one is required). The message names the
    recorded shape and the surviving fleet — the operator's actionable
    alternative to a blind mesh-mismatch crash deep inside restore."""


class MeshMismatchError(ReshapeError):
    """The restore-target mesh cannot hold the saved arrays: some mesh
    axis product does not divide a sharded dimension. Raised *before*
    touching orbax so the operator gets an actionable message instead of
    a raw Orbax/XLA partitioning traceback. A :class:`ReshapeError`
    subtype: with ``--elastic`` this is what negotiation exists to
    avoid; without it, it must still fire (the non-elastic path never
    silently adopts a wrong shape)."""


def _leaf_meta(tree: Any) -> List[Dict[str, Any]]:
    """Per-leaf (path, shape, dtype) — the manifest's structure section."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [{
        "path": jax.tree_util.keystr(path),
        "shape": [int(d) for d in getattr(leaf, "shape", ())],
        "dtype": str(getattr(leaf, "dtype", type(leaf).__name__)),
    } for path, leaf in leaves]


def _scan_files(step_dir: str) -> Dict[str, Tuple[int, str]]:
    """{relpath: (bytes, sha256)} over every file of a step directory,
    the manifest itself excluded."""
    out: Dict[str, Tuple[int, str]] = {}
    for root, _, files in os.walk(step_dir):
        for name in files:
            full = os.path.join(root, name)
            rel = os.path.relpath(full, step_dir)
            if rel in (MANIFEST_NAME, MANIFEST_NAME + ".tmp"):
                continue
            h = hashlib.sha256()
            with open(full, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            out[rel] = (os.path.getsize(full), h.hexdigest())
    return out


def _to_abstract(leaf: Any) -> Any:
    """Shape-dtype-struct view of a leaf. Already-abstract leaves pass
    through unchanged — ``ocp.utils.to_shape_dtype_struct`` assumes an
    orbax metadata sharding on ShapeDtypeStruct inputs and trips over a
    plain jax one (or None, for host-only trees)."""
    if isinstance(leaf, jax.ShapeDtypeStruct):
        return leaf
    return ocp.utils.to_shape_dtype_struct(leaf)


def _restore_args(state_like: Any, abstract: Any) -> Any:
    """Orbax restore args for a template tree. An all-numpy template
    pins ``restore_type=np.ndarray`` explicitly: a sharding-less
    abstract leaf otherwise falls back to the sharding recorded at SAVE
    time, whose devices other ranks don't have when the writer ran at a
    different world size (the elastic regrow: a 1-process save restored
    by a 2-process fleet's host-read path)."""
    import numpy as _np

    leaves = jax.tree_util.tree_leaves(state_like)
    if leaves and all(isinstance(l, _np.ndarray) for l in leaves):
        # Pass the numpy leaves through verbatim: orbax maps np.ndarray
        # template leaves to restore_type=np.ndarray, while an erased
        # (sharding-less) abstract leaf would fall back to save-time
        # sharding and explode on ranks without those devices.
        return ocp.args.StandardRestore(state_like)
    return ocp.args.StandardRestore(abstract)


def _manifest_digest(manifest: Dict[str, Any]) -> str:
    """Whole-checkpoint digest over the manifest body (everything but the
    digest field itself) — the last thing written, i.e. the commit bit."""
    body = {k: v for k, v in manifest.items() if k != "digest"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()).hexdigest()


def mesh_spec_of(mesh: Any, n_processes: int = 1,
                 global_batch: int = 0) -> Dict[str, Any]:
    """The manifest-v2 ``mesh`` section for a live jax mesh: axis sizes
    (every axis, unit or not — the negotiator must see the full layout),
    fleet size, and the global batch the data stream was cut for (kept
    constant across resizes so the loss trajectory is fleet-shape-
    independent)."""
    return {
        "axes": {str(name): int(size) for name, size in mesh.shape.items()},
        "n_processes": int(n_processes),
        "n_devices": int(mesh.devices.size),
        "global_batch": int(global_batch),
    }


def peek_newest_manifest(*directories: Optional[str],
                         ) -> Optional[Tuple[int, Dict[str, Any]]]:
    """``(step, manifest)`` of the newest digest-intact manifest across
    checkpoint directories — pure file I/O, no orbax, no mesh. This is
    what elastic startup reads BEFORE building any mesh: the recorded
    shape decides the mesh the restore target is built on. A torn or
    digest-broken manifest is skipped (restore proper will quarantine
    it); deterministic, so every rank peeking the same shared filesystem
    negotiates the same shape with no collective needed."""
    candidates: List[Tuple[int, str]] = []
    for directory in directories:
        if not directory or not os.path.isdir(directory):
            continue
        for name in os.listdir(directory):
            if name.isdigit():
                candidates.append((int(name),
                                   os.path.join(directory, name)))
    for step, sdir in sorted(candidates, reverse=True):
        mpath = os.path.join(sdir, MANIFEST_NAME)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            continue
        if manifest.get("digest") != _manifest_digest(manifest):
            continue
        return step, manifest
    return None


class CheckpointManager:
    """Orbax manager + manifest commit/verify/quarantine/fallback layer.

    Save kinds (the ``kind`` metric label): ``scheduled`` (cadenced saves
    from the training loop), ``emergency`` (preemption-warning synchronous
    save), ``final`` (end-of-run). Async saves are *pending* until their
    manifest commits — ``close()`` (idempotent, also registered via
    ``atexit``) guarantees every scheduled save is either finalized or
    quarantined, so a crash between async save and process exit can never
    leave a half-written step masquerading as ``latest_step()``.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 single_controller: bool = False,
                 mesh_spec: Optional[Dict[str, Any]] = None):
        self.directory = os.path.abspath(directory)
        # The manifest-v2 mesh section (mesh_spec_of); assignable after
        # construction too — the trainer sets it once the mesh exists.
        # None keeps a format-2 manifest with "mesh": null, which the
        # elastic path treats exactly like a format-1 manifest.
        self.mesh_spec = mesh_spec
        options_kwargs: Dict[str, Any] = {}
        if single_controller:
            # Multi-process runs coordinate checkpoints OUTSIDE orbax
            # (parallel/multihost.CoordinatedCheckpoint: process 0 writes
            # host-assembled trees, explicit barriers around the commit).
            # Orbax must therefore never run its own cross-process
            # barriers — a rank-0-only save would deadlock inside them —
            # so each rank's orbax instance is scoped to exactly its own
            # process.
            try:
                from orbax.checkpoint import options as ocp_options

                rank = jax.process_index()
                options_kwargs["multiprocessing_options"] = (
                    ocp_options.MultiprocessingOptions(
                        primary_host=rank, active_processes={rank},
                        barrier_sync_key_prefix=f"tk8s-r{rank}"))
            except (ImportError, TypeError) as e:
                raise CheckpointError(
                    f"this orbax cannot scope its process set "
                    f"(multiprocessing_options unavailable: {e}); "
                    f"single-controller checkpointing needs orbax >= 0.5"
                ) from e
            # Orbax refuses create=True alongside active_processes; the
            # root directory is ours to make.
            os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=not single_controller,
            **options_kwargs)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)
        self._closed = False
        # step -> {"t0": dispatch clock, "kind": ..., "tree": leaf meta};
        # entries live from save() until the manifest commits.
        self._pending: Dict[int, Dict[str, Any]] = {}
        # Steps whose manifest THIS instance committed: only these may be
        # silently skipped on re-save — a same-numbered step from an
        # earlier run is a different state and must never be adopted.
        self._committed: set = set()
        self.last_restored_step: Optional[int] = None
        atexit.register(self._atexit_guard)

    # ------------------------------------------------------------------ save
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def _known_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps() or [])

    def save(self, step: int, state: Any, wait: bool = False,
             kind: str = "scheduled") -> None:
        """Schedule (or, with ``wait``/``kind="emergency"``, complete) a
        save. Emergency saves are always synchronous — an emergency
        checkpoint that outlives the process is no checkpoint at all.
        Re-saving a step this instance already committed is a no-op (an
        emergency save landing exactly on a scheduled checkpoint
        boundary); a same-numbered step left by an *earlier run* is a
        different state and is quarantined first, never adopted."""
        if self._closed:
            raise CheckpointError(
                f"CheckpointManager for {self.directory} is closed")
        if kind == "emergency":
            _metrics.counter(
                "tk8s_train_checkpoint_emergency_saves_total").inc()
            wait = True
        already = step in self._pending or step in self._committed
        if not already:
            if step in self._known_steps():
                self.quarantine(step, "superseded-by-resave")
            self._pending[step] = {"t0": time.perf_counter(), "kind": kind,
                                   "tree": _leaf_meta(state),
                                   "mesh": self.mesh_spec}
            self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._finalize()

    def _finalize(self) -> None:
        """Wait out scheduled async saves and commit their manifests; a
        failed wait quarantines whatever the dead save left behind."""
        if not self._pending:
            return
        pending, self._pending = self._pending, {}
        try:
            self._mgr.wait_until_finished()
        except Exception:
            for step in sorted(pending):
                if os.path.isdir(self._step_dir(step)):
                    self.quarantine(step, "async-save-failed")
            raise
        for step, info in sorted(pending.items()):
            sdir = self._step_dir(step)
            if not os.path.isdir(sdir):  # gc'd by max_to_keep already
                continue
            if os.path.exists(os.path.join(sdir, MANIFEST_NAME)):
                self._committed.add(step)
                continue
            files = _scan_files(sdir)
            manifest: Dict[str, Any] = {
                "format": MANIFEST_FORMAT,
                "step": step,
                "kind": info["kind"],
                "tree": info["tree"],
                "mesh": info.get("mesh"),
                "files": {rel: {"bytes": size, "sha256": digest}
                          for rel, (size, digest) in sorted(files.items())},
            }
            manifest["digest"] = _manifest_digest(manifest)
            tmp = os.path.join(sdir, MANIFEST_NAME + ".tmp")
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(sdir, MANIFEST_NAME))
            self._committed.add(step)
            _metrics.histogram(
                "tk8s_train_checkpoint_save_duration_seconds").observe(
                time.perf_counter() - info["t0"], kind=info["kind"])
            _metrics.counter("tk8s_train_checkpoint_bytes_total").inc(
                sum(size for size, _ in files.values()), kind=info["kind"])

    # ---------------------------------------------------------------- verify
    def verify_step(self, step: int) -> None:
        """Raise :class:`CheckpointIntegrityError` unless ``step`` is a
        committed, byte-intact checkpoint. Every failure is counted."""

        def fail(message: str, reason: str) -> None:
            _metrics.counter(
                "tk8s_train_checkpoint_verify_failures_total").inc(
                reason=reason)
            raise CheckpointIntegrityError(
                f"step {step} in {self.directory}: {message}", reason=reason)

        sdir = self._step_dir(step)
        if not os.path.isdir(sdir):
            fail("no step directory", "missing-step")
        mpath = os.path.join(sdir, MANIFEST_NAME)
        if not os.path.exists(mpath):
            fail("no manifest — the save never committed", "missing-manifest")
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except ValueError as e:
            fail(f"torn manifest ({e})", "torn-manifest")
        if manifest.get("digest") != _manifest_digest(manifest):
            fail("manifest digest mismatch", "digest-mismatch")
        actual = _scan_files(sdir)
        for rel, meta in manifest.get("files", {}).items():
            got = actual.get(rel)
            if got is None:
                fail(f"file {rel} missing", "missing-file")
            elif got[0] != int(meta["bytes"]):
                fail(f"file {rel} is {got[0]} bytes, manifest says "
                     f"{meta['bytes']} (truncated or torn)", "truncated")
            elif got[1] != meta["sha256"]:
                fail(f"file {rel} content checksum mismatch (bit rot or "
                     f"partial overwrite)", "checksum-mismatch")

    def quarantine(self, step: int, reason: str = "corrupt") -> str:
        """Move a bad step aside (rename, never delete — it is postmortem
        evidence) and drop it from orbax's step index."""
        src = self._step_dir(step)
        qdir = os.path.join(self.directory, QUARANTINE_DIR)
        os.makedirs(qdir, exist_ok=True)
        slug = "".join(c if c.isalnum() or c in "._-" else "-"
                       for c in reason)[:64] or "corrupt"
        dst = os.path.join(qdir, f"{step}-{slug}")
        n = 1
        while os.path.exists(dst):
            dst = os.path.join(qdir, f"{step}-{slug}.{n}")
            n += 1
        os.rename(src, dst)
        self._pending.pop(step, None)
        self._committed.discard(step)
        self._mgr.reload()  # latest_step() must not see the quarantined dir
        return dst

    # --------------------------------------------------------------- restore
    def reload(self) -> None:
        """Re-scan the directory for steps other writers committed (the
        coordinated multi-process wrapper calls this on non-writer ranks:
        their orbax index only tracks their OWN saves, which is none)."""
        self._mgr.reload()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return self._known_steps()

    def latest_verified_step(self) -> Optional[int]:
        """Newest step that passes manifest verification (read-only: bad
        steps are reported by counter but not quarantined here)."""
        for step in sorted(self._known_steps(), reverse=True):
            try:
                self.verify_step(step)
                return step
            except CheckpointIntegrityError:
                continue
        return None

    def manifest(self, step: int) -> Optional[Dict[str, Any]]:
        """The committed manifest of ``step`` (None when the step or its
        manifest is missing/torn — callers wanting a typed failure use
        :meth:`verify_step`)."""
        mpath = os.path.join(self._step_dir(step), MANIFEST_NAME)
        try:
            with open(mpath) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def saved_mesh_spec(self, step: int) -> Optional[Dict[str, Any]]:
        """The ``mesh`` section ``step`` was saved under, or None for a
        format-1 manifest (pre-elastic writer) / missing step."""
        manifest = self.manifest(step)
        return manifest.get("mesh") if manifest else None

    @staticmethod
    def _check_mesh_fits(abstract: Any) -> None:
        """Typed, actionable error when the target mesh cannot partition
        the tree — instead of the raw Orbax/XLA ValueError."""
        for path, leaf in jax.tree_util.tree_flatten_with_path(abstract)[0]:
            sharding = getattr(leaf, "sharding", None)
            spec = getattr(sharding, "spec", None)
            mesh = getattr(sharding, "mesh", None)
            if spec is None or mesh is None:
                continue
            shape = tuple(getattr(leaf, "shape", ()))
            mesh_shape = dict(mesh.shape)
            for dim, entry in enumerate(spec):
                if entry is None or dim >= len(shape):
                    continue
                axes = entry if isinstance(entry, tuple) else (entry,)
                ways = 1
                for ax in axes:
                    ways *= mesh_shape.get(ax, 1)
                if ways > 1 and shape[dim] % ways:
                    raise MeshMismatchError(
                        f"cannot restore onto this mesh: leaf "
                        f"'{jax.tree_util.keystr(path)}' dimension {dim} "
                        f"(size {shape[dim]}) would be split {ways} ways "
                        f"by mesh axes {tuple(axes)} of mesh {mesh_shape}; "
                        f"the restore mesh must divide every sharded "
                        f"dimension — resume on a device count whose axes "
                        f"divide the saved shapes (e.g. the original mesh) "
                        f"or reshard offline")

    def restore(self, state_like: Any, step: Optional[int] = None,
                verify: bool = True) -> Any:
        """``state_like``: concrete or abstract (shape-dtype structs whose
        leaves carry shardings) tree matching what was saved.

        Verifies the newest candidate's manifest first; a step that fails
        is quarantined and the next older step is tried — the restore
        self-heals past torn or bit-rotted checkpoints. ``step`` bounds
        the search (newest verified step <= ``step``); the actually
        restored step lands in ``last_restored_step``."""
        self._finalize()  # a restore must see every scheduled save committed
        abstract = jax.tree.map(_to_abstract, state_like)
        self._check_mesh_fits(abstract)
        steps = self._known_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        candidates = [s for s in steps if step is None or s <= step]
        if not candidates:
            raise FileNotFoundError(
                f"no checkpoint at or before step {step} in "
                f"{self.directory} (have {steps})")
        failures: List[str] = []
        for s in sorted(candidates, reverse=True):
            if verify:
                try:
                    self.verify_step(s)
                except CheckpointIntegrityError as e:
                    where = self.quarantine(s, e.reason)
                    failures.append(f"{e} -> quarantined to {where}")
                    continue
            restored = self._mgr.restore(
                s, args=_restore_args(state_like, abstract))
            if failures:
                _metrics.counter(
                    "tk8s_train_checkpoint_fallback_restores_total").inc()
            self.last_restored_step = s
            return restored
        raise CheckpointIntegrityError(
            f"no checkpoint in {self.directory} passed verification: "
            + "; ".join(failures), reason="all-quarantined")

    # ----------------------------------------------------------------- close
    def close(self) -> None:
        """Idempotent: commit (or quarantine) every scheduled async save,
        then release orbax resources. Also runs at interpreter exit via
        ``atexit``, so a trainer that forgets close() still never leaves a
        committed-looking half-step behind."""
        if self._closed:
            return
        self._closed = True
        try:
            atexit.unregister(self._atexit_guard)
        # tk8s-lint: disable=TK8S106(unregister during interpreter
        # teardown is cosmetic; failing it must not block close())
        except Exception:  # pragma: no cover - interpreter teardown
            pass
        try:
            self._finalize()
        finally:
            self._mgr.close()

    def _atexit_guard(self) -> None:
        try:
            self.close()
        # tk8s-lint: disable=TK8S106(atexit last resort: close() already
        # quarantines torn saves, and there is no caller left to notify)
        except Exception:  # pragma: no cover - best effort at exit
            pass


def restore_newest_verified(state_like: Any, *managers: Any,
                            ) -> Tuple[Any, Any, int]:
    """Cross-manager resume: restore the newest verified step across
    several checkpoint directories (the scheduled dir and the emergency
    dir). Candidate steps from every manager are tried globally
    newest-first; one that fails verification is quarantined by its
    owning manager and the next-newest step — wherever it lives — is
    tried, so a torn emergency save falls back to the last scheduled
    checkpoint (never to an older step in its own directory while a
    newer verified one exists elsewhere). Returns ``(restored_state,
    manager, step)``. Raises ``FileNotFoundError`` when no manager holds
    any checkpoint, and :class:`CheckpointIntegrityError` when
    checkpoints exist but none verifies anywhere."""
    mgrs = [m for m in managers if m is not None]
    candidates = [(step, mgr) for mgr in mgrs for step in mgr.all_steps()]
    # Newest step first; ties keep the caller's manager order (scheduled
    # before emergency when both committed the same step).
    candidates.sort(key=lambda c: (-c[0], mgrs.index(c[1])))
    if not candidates:
        raise FileNotFoundError(
            "no checkpoints in any of: "
            + ", ".join(m.directory for m in mgrs))
    failures: List[str] = []
    for step, mgr in candidates:
        # Verify HERE, not via restore's own fallback: a manager must not
        # fall back within its own directory past steps another manager
        # holds verified copies newer than.
        try:
            mgr.verify_step(step)
        except CheckpointIntegrityError as e:
            where = mgr.quarantine(step, e.reason)
            failures.append(f"{e} -> quarantined to {where}")
            continue
        # verify=False: this exact step was hashed end-to-end two lines
        # up — re-verifying inside restore would read a multi-GB
        # checkpoint twice on every resume.
        restored = mgr.restore(state_like, step=step, verify=False)
        if failures:
            _metrics.counter(
                "tk8s_train_checkpoint_fallback_restores_total").inc()
        return restored, mgr, step
    raise CheckpointIntegrityError(
        "no checkpoint passed verification in any directory: "
        + "; ".join(failures), reason="all-quarantined")
