"""GPipe-style SPMD pipeline parallelism over the ``stage`` mesh axis.

TPU-first formulation (no per-stage programs, no send/recv): the stacked
layer tensors [L, ...] are reshaped to [S, L/S, ...] with the leading dim
sharded over ``stage``; a ``vmap`` over that dim makes each device group
compute only its own stage, and the microbatch hand-off is a shifted
``concatenate`` on the stage-sharded dim — GSPMD lowers exactly that shift
to a ``collective-permute`` between neighboring stages (the ICI/DCN
transfer), so the whole schedule stays one jitted SPMD program.

Schedule: plain GPipe with M microbatches over S stages, T = M + S - 1
ticks. At tick t, stage s processes microbatch t - s; ticks where t - s
falls outside [0, M) are bubbles computing on zero activations (RMS-norm is
eps-guarded, so bubbles are finite and their outputs are never collected).
Efficiency is M / (M + S - 1); pick microbatches >= 4 * stages to amortize.

No reference analog: the reference provisions clusters and has no ML
runtime (SURVEY.md §2.5); this implements the pipeline-parallel axis the
TPU build adds on top (BASELINE.json north star).

Kernels inside the pipeline: on a mesh the per-tick stage computation runs
under a *partial-manual* ``shard_map`` over the ``stage`` axis (every other
axis stays under GSPMD). Because manual axes are disjoint, the flash
attention kernel's own shard_map (over data/fsdp/tensor) and ring
attention's (over data/fsdp/seq/tensor) nest inside it — pp×tp keeps the
Pallas kernel and pp×sp keeps the ring exchange, instead of falling back
to the dense einsum.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama
from ..models.config import ModelConfig
from ..ops.rotary import rotary_tables
from ..parallel.mesh import (
    AXIS_DATA, AXIS_FSDP, AXIS_SEQ, AXIS_STAGE, mesh_axis_size)


def _stage_params(layers, num_stages: int):
    """[L, ...] stacked leaves -> [S, L/S, ...]."""
    def split(leaf):
        l = leaf.shape[0]
        if l % num_stages:
            raise ValueError(
                f"num_layers ({l}) must divide evenly into "
                f"{num_stages} pipeline stages")
        return leaf.reshape(num_stages, l // num_stages, *leaf.shape[1:])

    return jax.tree.map(split, layers)


def pipeline_forward(
    params,
    tokens: jnp.ndarray,  # [B, S_len] int32
    config: ModelConfig,
    num_stages: int,
    microbatches: int,
    attention_fn=None,
    positions: Optional[jnp.ndarray] = None,
    mesh: Optional[Mesh] = None,
):
    """Pipelined equivalent of ``models.llama.forward``.

    Returns (logits [B, S_len, V] f32, moe aux loss scalar). For dense
    configs this is numerically identical to the sequential forward (same
    params, same layer order) up to reduction-order noise. For MoE configs
    it is NOT: capacity-based routing runs per microbatch, so which tokens
    are dropped (and the aux load-balancing loss) genuinely differ from a
    full-batch forward — pipelined MoE training uses microbatch-local
    routing/capacity by design.
    """
    # No config-forced kernel resolution here: a raw (un-shard_mapped)
    # Pallas call under the stage map's GSPMD-managed axes would silently
    # all-gather and replicate. Kernel selection for the pipeline lives
    # in trainer._resolve_attention, which builds the nested shard_map.
    attention_fn = attention_fn or llama._dense_attention
    b, s = tokens.shape
    if b % microbatches:
        raise ValueError(
            f"batch ({b}) must divide into {microbatches} microbatches")
    if microbatches % num_stages:
        raise ValueError(
            f"microbatches ({microbatches}) must be a multiple of "
            f"stages ({num_stages})")
    mb = b // microbatches
    ad = config.activation_dtype
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    cos, sin = rotary_tables(
        config.head_dim, config.max_seq_len, config.rope_theta)

    stage_layers = _stage_params(params["layers"], num_stages)
    pos_mb = positions.reshape(microbatches, mb, s)

    def stage_apply(layers_s, x, pos):
        """One stage: scan its L/S layers over the microbatch activation."""
        def body(carry, layer):
            out, aux = llama._block(
                carry, layer, config, cos, sin, pos, attention_fn)
            return out, aux

        body = llama.remat_block(body, config)
        x, auxs = lax.scan(body, x, layers_s)
        return x, auxs.sum()

    # Microbatch embeddings up front: [M, mb, s, d] (same total bytes as the
    # unpipelined activation), padded with S-1 zero ticks for the drain.
    x = params["embed"].astype(ad)[tokens]
    x = x.reshape(microbatches, mb, s, -1)
    ticks = microbatches + num_stages - 1
    pad = jnp.zeros((num_stages - 1,) + x.shape[1:], x.dtype)
    injects = jnp.concatenate([x, pad], axis=0)          # [T, mb, s, d]
    pos_pad = jnp.concatenate(
        [pos_mb, jnp.zeros((num_stages - 1, mb, s), pos_mb.dtype)], axis=0)

    if mesh is not None:
        # The activation's sequence dim rides the seq axis too, so ring
        # attention under the pipeline starts from seq-sharded operands.
        buf_sharding = NamedSharding(
            mesh, P(AXIS_STAGE, (AXIS_DATA, AXIS_FSDP), AXIS_SEQ))
        constrain = lambda a: lax.with_sharding_constraint(a, buf_sharding)
    else:
        constrain = lambda a: a  # shape-only run (tests, no mesh in scope)
    stage_idx = jnp.arange(num_stages)

    if mesh is not None and mesh_axis_size(mesh, AXIS_STAGE) > 1:
        if mesh_axis_size(mesh, AXIS_STAGE) != num_stages:
            raise ValueError(
                f"num_stages ({num_stages}) must equal the mesh stage axis "
                f"({mesh_axis_size(mesh, AXIS_STAGE)})")

        # Partial-manual over the stage axis only: each device group applies
        # its single local stage; data/fsdp/seq/tensor stay under GSPMD, so
        # kernel shard_maps (flash, ring) nest inside the body.
        def _one_stage(layers_s, x, pos):
            out, aux = stage_apply(
                jax.tree.map(lambda l: l[0], layers_s), x[0], pos[0])
            return out[None], aux[None]

        from ..utils.jaxcompat import shard_map as _shard_map

        stage_specs = jax.tree.map(lambda _: P(AXIS_STAGE), stage_layers)
        stage_map = _shard_map(
            _one_stage, mesh=mesh,
            in_specs=(stage_specs, P(AXIS_STAGE), P(AXIS_STAGE)),
            out_specs=(P(AXIS_STAGE), P(AXIS_STAGE)),
            axis_names={AXIS_STAGE}, check_vma=False)
    else:
        stage_map = jax.vmap(stage_apply)

    def tick(carry, xs):
        buf, pos_buf, outputs, aux_total = carry
        inject, pos_t, t = xs
        # Shift the stage buffer: stage 0 takes the new microbatch, stage
        # s takes stage s-1's previous output (collective-permute on ICI).
        # Positions ride along so each stage sees its own microbatch's.
        buf = constrain(jnp.concatenate([inject[None], buf[:-1]], axis=0))
        pos_buf = jnp.concatenate([pos_t[None], pos_buf[:-1]], axis=0)
        out, aux = stage_map(stage_layers, buf, pos_buf)
        out = constrain(out)
        # Only stages holding a real microbatch (0 <= t - s < M) count.
        valid = ((t - stage_idx >= 0)
                 & (t - stage_idx < microbatches)).astype(aux.dtype)
        aux_total = aux_total + (aux * valid).sum()
        # Collect the last stage's finished microbatch (index t - (S-1);
        # clamped writes before the fill tick are overwritten at t = S-1).
        outputs = lax.dynamic_update_index_in_dim(
            outputs, out[-1], jnp.clip(t - (num_stages - 1), 0, None), 0)
        return (out, pos_buf, outputs, aux_total), None

    buf0 = jnp.zeros((num_stages, mb, s, x.shape[-1]), x.dtype)
    pos0 = jnp.zeros((num_stages, mb, s), pos_mb.dtype)
    out0 = jnp.zeros_like(x)
    (_, _, outputs, aux_total), _ = lax.scan(
        tick, (buf0, pos0, out0, jnp.zeros((), jnp.float32)),
        (injects, pos_pad, jnp.arange(ticks)))

    h = outputs.reshape(b, s, -1)
    logits = llama.unembed(h, params, config)
    # Each microbatch's aux is a mean-over-its-tokens estimate of the same
    # batch-level balance loss; average them to match the sequential scale.
    return logits, aux_total / microbatches


def pipeline_degree(mesh: Mesh) -> int:
    return mesh_axis_size(mesh, AXIS_STAGE)


# ==========================================================================
# Host-side step pipelining: the producer/consumer training loop.
#
# The GPipe schedule above pipelines *within* one step; this section
# pipelines *across* steps. JAX dispatch is asynchronous, so the fast loop
# is simply the one that never forces a device->host sync: steps are
# dispatched back to back (the device queue keeps up to ``sync_every``
# steps in flight), per-step metrics stay resident as device scalars, and
# the host touches the device exactly once per sync window — one
# ``device_get`` of the window's metric scalars, which also drains the
# in-flight queue and thereby bounds it. Input never gates dispatch when
# the batches iterator is a ``train.data.DevicePrefetch``.
#
# Every quantity the old per-step loop printed is still available — just
# amortized: per-step losses come out bitwise identical (same step_fn,
# same batch order; the sync cadence does not touch the math), and the
# overlap itself is measurable through the ``tk8s_train_*`` families
# (utils/metrics.py CATALOG) instead of being vibes.
# ==========================================================================


@dataclass
class LoopReport:
    """What one ``run_pipelined`` call did, fully host-resident."""

    steps: int = 0
    losses: List[float] = field(default_factory=list)  # per step, in order
    sync_points: int = 0
    interrupted: bool = False  # should_stop tripped; partial window synced
    wall_seconds: float = 0.0
    steps_per_sec: float = 0.0
    tokens_per_sec: float = 0.0
    prefetch_wait_seconds: float = 0.0
    last_metrics: Dict[str, float] = field(default_factory=dict)


def run_pipelined(
    step_fn: Callable[[Any, Any], Tuple[Any, Dict[str, jnp.ndarray]]],
    state: Any,
    batches: Iterable[Any],
    *,
    sync_every: int = 8,
    max_steps: Optional[int] = None,
    tokens_per_step: int = 0,
    config_name: str = "",
    on_sync: Optional[Callable[[int, Any, List[float], float], None]] = None,
    force_sync: Optional[Callable[[int], bool]] = None,
    should_stop: Optional[Callable[[], bool]] = None,
    prefetch: Any = None,
    clock: Callable[[], float] = time.perf_counter,
    step_floor_seconds: float = 0.0,
    goodput: Any = None,
    goodput_step_category: Optional[Callable[[int], str]] = None,
) -> Tuple[Any, LoopReport]:
    """Bounded-async training loop: dispatch every step, sync every K.

    ``batches`` is any iterable of step inputs (a list/tuple is cycled —
    pass ``max_steps`` then); a finite iterator ends the loop early
    (short epoch), which is reported, not an error. ``sync_every`` is both
    the host-sync cadence and the in-flight bound: the window fetch waits
    on the newest dispatched step, so at most ``sync_every`` steps are
    ever outstanding. ``on_sync(step, state, window_losses,
    window_seconds)`` runs at each sync point — the only place logging
    and checkpointing belong (anything per-step would reintroduce the
    sync this loop exists to remove). ``force_sync(steps_done)`` may
    close a window early at caller-meaningful boundaries (checkpoint
    multiples) without shrinking ``sync_every`` for every other window.
    ``should_stop()`` is polled before each dispatch (a host flag read —
    free); when it turns true the loop syncs the partial window and
    returns with ``report.interrupted`` set — the preemption-warning
    path (train/resilience.py): the sync point is where an emergency
    checkpoint is safe to take. ``prefetch`` names the :class:`..train.data.DevicePrefetch` feeding
    ``batches`` when the iterable wraps it (e.g. in an
    ``itertools.chain``), so input-wait accounting still reaches the
    gauge.

    ``step_floor_seconds`` is a deterministic per-step device-time
    floor — the train-loop analogue of cloudsim's ``op_latency`` knob:
    it models the accelerator each CPU process stands in for, so
    scale-out concurrency is measurable without a cloud. The host
    sleeps only the *remainder* of the floor after each dispatch, so
    real (async) compute overlaps it and per-step wall converges to
    ``max(floor, compute)``; losses are untouched and every real
    overhead (staging, collectives, host syncs) still lands on top.
    0 (the default) disables it.

    ``goodput`` is an optional
    :class:`..utils.trace.GoodputRecorder` (``train`` vocabulary,
    sharing this loop's ``clock``): the loop attributes its own wall
    time — ``data_wait`` while pulling the next batch, ``step`` across
    the floor sleep and dispatch, ``host_sync`` across the window
    drain, ``preempted_lost`` from the moment ``should_stop`` trips —
    with segments closing exactly when the next opens, so the ledger
    partitions the loop's wall window. ``goodput_step_category(n)``
    (``n`` = 1-based step index within this call) lets a resilient
    caller book replayed steps as ``rollback_replay`` instead of
    ``step``.

    Returns ``(final_state, LoopReport)``; ``report.losses`` is bitwise
    identical to what a per-step-synced loop over the same step_fn and
    batch order would fetch.
    """
    from ..utils import metrics as _metrics

    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    if isinstance(batches, (list, tuple)):
        if max_steps is None:
            raise ValueError(
                "a list of batches is cycled forever; pass max_steps")
        batches_it: Iterable[Any] = itertools.cycle(batches)
    else:
        batches_it = batches
    if max_steps is not None:
        batches_it = itertools.islice(batches_it, max_steps)

    hist = _metrics.histogram("tk8s_train_step_duration_seconds")
    tokens_total = _metrics.counter("tk8s_train_tokens_total")
    syncs_total = _metrics.counter("tk8s_train_host_syncs_total")
    wait_gauge = _metrics.gauge("tk8s_train_prefetch_wait_seconds")
    inflight_gauge = _metrics.gauge("tk8s_train_steps_in_flight")

    report = LoopReport()
    window: List[Dict[str, jnp.ndarray]] = []
    t_start = clock()
    t_window = t_start

    def sync() -> None:
        nonlocal t_window
        if not window:
            return
        if goodput is not None and not report.interrupted:
            # An interrupted partial window drains under the category
            # should_stop opened (preempted_lost): that drain is
            # recovery work, not a routine host sync.
            goodput.transition("host_sync")
        inflight_gauge.set(len(window))
        # THE host sync: one transfer of the window's metric scalars
        # (losses + the newest step's full metrics dict, combined so the
        # host_syncs count equals real transfer points). Fetching the
        # newest step transitively drains every step dispatched before
        # it, so this both reports and bounds.
        fetched, last_vals = jax.device_get(
            ([m["loss"] for m in window], window[-1]))
        dt = clock() - t_window
        window_losses = [float(x) for x in fetched]
        report.last_metrics = {k: float(v) for k, v in last_vals.items()}
        report.losses.extend(window_losses)
        report.sync_points += 1
        per_step = dt / len(window)
        for _ in window:
            hist.observe(per_step, config=config_name)
        if tokens_per_step:
            tokens_total.inc(tokens_per_step * len(window),
                             config=config_name)
        syncs_total.inc(config=config_name)
        wait = getattr(prefetch if prefetch is not None else batches,
                       "wait_seconds", None)
        if wait is not None:
            report.prefetch_wait_seconds = float(wait)
            wait_gauge.set(float(wait))
        inflight_gauge.set(0)
        n_window = len(window)
        window.clear()
        if goodput is not None and goodput.writer is not None:
            goodput.writer.event("train.window", t_window, dt,
                                 steps=n_window,
                                 loss=window_losses[-1])
        if on_sync is not None:
            on_sync(report.steps, state, window_losses, dt)
        t_window = clock()

    t_dispatch = clock()
    it = iter(batches_it)
    _end = object()
    while True:
        if goodput is not None:
            goodput.transition("data_wait")
        batch = next(it, _end)
        if batch is _end:
            break
        if should_stop is not None and should_stop():
            if goodput is not None:
                goodput.transition("preempted_lost")
            report.interrupted = True
            break
        if goodput is not None:
            goodput.transition(
                goodput_step_category(report.steps + 1)
                if goodput_step_category is not None else "step")
        if step_floor_seconds > 0.0:
            # Device-time model: pace dispatch to the floor. Sleeping
            # (not spinning) frees the core for the async steps already
            # in flight, so compute overlaps the modeled device time.
            remain = step_floor_seconds - (clock() - t_dispatch)
            if remain > 0:
                time.sleep(remain)
            t_dispatch = clock()
        state, metrics = step_fn(state, batch)
        window.append(metrics)
        report.steps += 1
        if len(window) >= sync_every or (
                force_sync is not None and force_sync(report.steps)):
            sync()
    sync()
    if goodput is not None and not report.interrupted:
        goodput.transition("idle")
    report.wall_seconds = max(clock() - t_start, 1e-9)
    report.steps_per_sec = report.steps / report.wall_seconds
    report.tokens_per_sec = (
        report.steps * tokens_per_step / report.wall_seconds)
    return state, report
