"""Survive-the-step: preemption-aware saves, loss-anomaly rollback.

"Exploring the limits of Concurrency in ML Training on Google TPUs"
(PAPERS.md) makes preemption-tolerant checkpoint/resume the defining
constraint of large pod-slice jobs: recovery is a first-class hot path,
not an error path. This module is that hot path for the bundled trainer,
wrapping the step-pipelined loop (train/pipeline.py ``run_pipelined``)
with three protections:

1. **Preemption-aware emergency save** — GKE delivers SIGTERM ~30s
   before reclaiming a TPU slice (the JobSet's terminationGracePeriod).
   :class:`PreemptionGuard` turns that signal into a flag the pipelined
   loop checks before dispatching each step; on trip the current window
   is force-synced, a *synchronous* emergency checkpoint is written
   (``kind="emergency"``, manifest-committed), and the trainer exits
   with :data:`EXIT_RESUME` so the JobSet restart policy resumes the job
   instead of failing it.

2. **Loss-anomaly guard with rollback** — at each sync window the
   already-host-synced losses are screened for NaN/Inf and for a
   configurable spike factor over a running median
   (:class:`LossAnomalyGuard`). On trip the loop rolls back to the last
   *verified* checkpoint, rebuilds the data stream at the rolled-back
   step (step-indexed replay keeps the resumed batch sequence
   reproducible), optionally skips the offending window's batches, and
   aborts with :class:`AnomalyAbortedError` after ``max_rollbacks``
   consecutive trips instead of looping forever.

3. **Verified restore under everything** — rollbacks and resumes go
   through ``CheckpointManager.restore``, which quarantines torn or
   bit-rotted steps and falls back to the newest verifiable earlier one
   (train/checkpoint.py).

The non-tripping path adds exactly one host-side screen per sync window
(pure Python over already-fetched floats), so per-step losses stay
bitwise identical to the bare pipelined loop — pinned in
tests/test_resilience.py.
"""

from __future__ import annotations

import math
import signal
import statistics
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from .pipeline import run_pipelined

# EX_TEMPFAIL: the distinguishable "resume me" exit code. The JobSet
# restart policy (topology/jobset.py podFailurePolicy) treats it as
# retryable — a preempted trainer restarts with --resume, a genuinely
# failed one (any other nonzero code) does not loop forever.
# Single-sourced from constants.py (lint rule TK8S104).
from ..constants import EXIT_RESUME


class AnomalyAbortedError(RuntimeError):
    """The guarded loop gave up: ``max_rollbacks`` consecutive anomaly
    trips without a clean window in between. Carries the last anomaly."""

    def __init__(self, message: str, anomaly: "Anomaly"):
        super().__init__(message)
        self.anomaly = anomaly


#: Mesh axes whose product must fit within one process's local devices
#: (ICI), vs the DCN axes (data, stage) that span processes — mirrors
#: parallel/mesh.create_hybrid_mesh's split.
_ICI_AXES = ("fsdp", "seq", "expert", "tensor")


def negotiate_mesh_config(saved: Optional[Dict[str, Any]], *,
                          n_processes: int, n_devices: int):
    """Elastic shape negotiation: the mesh a restart should build, from
    the manifest-v2 ``mesh`` section of the newest surviving checkpoint
    and the fleet that actually came up.

    The recorded ICI block (fsdp × seq × expert × tensor) and the stage
    axis are kept — they partition *model* dimensions, so changing them
    would re-split saved leaves — and the data axis absorbs the fleet
    delta: ``data = n_devices / (ici × stage)``. An 8-device
    ``data=2×fsdp=4`` checkpoint restarting on 4 devices negotiates
    ``data=1×fsdp=4``; back on 8, ``data=2×fsdp=4`` again. Raises
    :class:`~.checkpoint.ReshapeError` (typed, actionable — never a raw
    partitioning traceback) when no such mesh exists on the survivors.
    """
    from ..parallel.mesh import MeshConfig
    from .checkpoint import ReshapeError

    fleet = f"{n_devices} devices / {n_processes} processes"
    if not saved or not saved.get("axes"):
        raise ReshapeError(
            f"cannot negotiate a mesh for {fleet}: the checkpoint "
            f"manifest records no mesh (format-1 manifest from a "
            f"pre-elastic writer) — pass the mesh flags explicitly")
    axes = {str(k): int(v) for k, v in saved["axes"].items()}
    stage = axes.get("stage", 1)
    ici = 1
    for name in _ICI_AXES:
        ici *= axes.get(name, 1)
    saved_shape = "x".join(f"{k}={v}" for k, v in sorted(axes.items())
                           if v != 1) or "single-device"
    if ici * stage <= 0 or n_devices % (ici * stage):
        raise ReshapeError(
            f"cannot negotiate a mesh for {fleet}: the recorded ICI "
            f"block (ici={ici}, stage={stage}) of saved mesh "
            f"[{saved_shape}] does not divide {n_devices} devices — "
            f"resume on a multiple of {ici * stage} devices or reshard "
            f"offline")
    data = n_devices // (ici * stage)
    if (data * stage) % max(n_processes, 1):
        raise ReshapeError(
            f"cannot negotiate a mesh for {fleet}: DCN axes "
            f"(data={data}, stage={stage}) cannot span {n_processes} "
            f"processes evenly (saved mesh [{saved_shape}])")
    if n_processes > 1:
        local = n_devices // n_processes
        if local <= 0 or local % ici:
            raise ReshapeError(
                f"cannot negotiate a mesh for {fleet}: the recorded "
                f"ICI block (ici={ici}) no longer fits one process's "
                f"{local} local devices (saved mesh [{saved_shape}])")
    return MeshConfig(data=data, stage=stage,
                      fsdp=axes.get("fsdp", 1), seq=axes.get("seq", 1),
                      expert=axes.get("expert", 1),
                      tensor=axes.get("tensor", 1))


class PreemptionGuard:
    """SIGTERM/SIGINT -> a flag the training loop polls.

    Signal handlers must be installed from the main thread; ``install``
    raises ``ValueError`` elsewhere (callers may then run unguarded).
    ``trip()`` sets the flag programmatically — tests and in-process
    orchestrators use it; the signal path and it are equivalent.
    """

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self.signals = signals
        self.signum: Optional[int] = None
        self._event = threading.Event()
        self._prev: Dict[int, Any] = {}

    def install(self) -> "PreemptionGuard":
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self) -> None:
        while self._prev:
            sig, prev = self._prev.popitem()
            signal.signal(sig, prev)

    def _handle(self, signum, frame) -> None:
        self.signum = signum
        self._event.set()

    def trip(self) -> None:
        self._event.set()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


@dataclass(frozen=True)
class Anomaly:
    """One tripped loss: which step, what value, why."""

    step: int          # absolute (global) step of the offending loss
    loss: float
    reason: str        # "non-finite" | "spike"
    median: float      # running median at screening time (nan for n-f)


class LossAnomalyGuard:
    """Screens per-window losses for NaN/Inf and median-relative spikes.

    Only healthy losses enter the running-median history, so a slow ramp
    cannot drag the baseline up to meet the spike it should have caught.
    ``factor`` <= 0 disables the spike rule (non-finite always trips);
    ``min_history`` healthy losses are required before spikes arm, so
    the noisy first steps of a fresh run cannot false-positive.
    """

    def __init__(self, factor: float = 10.0, min_history: int = 4,
                 history: int = 256):
        self.factor = factor
        self.min_history = min_history
        self._hist: deque = deque(maxlen=history)

    def screen(self, losses: List[float], start_step: int) -> Optional[Anomaly]:
        """First anomalous loss of a window (absolute steps start at
        ``start_step`` for ``losses[0]``), or None; healthy prefix values
        are absorbed into the history either way."""
        for i, loss in enumerate(losses):
            if not math.isfinite(loss):
                return Anomaly(start_step + i, loss, "non-finite",
                               float("nan"))
            if self.factor > 0 and len(self._hist) >= self.min_history:
                med = statistics.median(self._hist)
                if loss > med * self.factor:
                    return Anomaly(start_step + i, loss, "spike", med)
            self._hist.append(loss)
        return None

    def reset_history(self, losses: List[float]) -> None:
        """Replace the running-median history (rollback support: replayed
        windows must not enter the history twice and skew the median)."""
        self._hist.clear()
        self._hist.extend(losses[-(self._hist.maxlen or len(losses)):])


@dataclass
class ResilienceReport:
    """What one ``run_resilient`` call did, host-resident."""

    steps: int = 0                      # accepted steps past start_step
    losses: List[float] = field(default_factory=list)  # accepted, in order
    rollbacks: int = 0
    anomalies: List[Anomaly] = field(default_factory=list)
    interrupted: bool = False           # preemption flag tripped
    emergency_step: Optional[int] = None
    restored_steps: List[int] = field(default_factory=list)  # rollback targets
    sync_points: int = 0


class _AnomalyTrip(Exception):
    """Internal unwind from the sync callback to the segment driver."""


def _abstract_like(state: Any) -> Any:
    """Shape/dtype/sharding template for rollback restores — built before
    the first (donating) step invalidates the concrete buffers."""
    return jax.tree.map(
        lambda leaf: jax.ShapeDtypeStruct(
            getattr(leaf, "shape", ()), getattr(leaf, "dtype", None),
            sharding=getattr(leaf, "sharding", None)),
        state)


def run_resilient(
    step_fn: Callable[[Any, Any], Tuple[Any, Dict[str, Any]]],
    state: Any,
    make_batches: Callable[[int], Any],
    *,
    ckpt: Any = None,                   # train.checkpoint.CheckpointManager
    emergency_ckpt: Any = None,         # defaults to ckpt
    target_step: int,
    start_step: int = 0,
    sync_every: int = 8,
    checkpoint_every: int = 0,
    guard: Optional[LossAnomalyGuard] = None,
    max_rollbacks: int = 3,
    skip_anomalous_window: bool = False,
    start_is_checkpointed: bool = False,
    preemption: Optional[PreemptionGuard] = None,
    tokens_per_step: int = 0,
    config_name: str = "",
    on_sync: Optional[Callable[[int, Any, List[float], float], None]] = None,
    on_checkpoint: Optional[Callable[[int, str], None]] = None,
    step_floor_seconds: float = 0.0,
    goodput: Any = None,
) -> Tuple[Any, ResilienceReport]:
    """Drive ``run_pipelined`` to ``target_step`` under the guards.

    ``make_batches(step)`` returns a fresh batch iterable positioned so
    its first batch is the one step ``step + 1`` consumes — the trainer's
    deterministic stream replay; it may return ``(iterable, prefetch)``
    to keep ``DevicePrefetch`` wait accounting flowing. Each segment's
    iterable is closed (if closeable) when the segment ends.

    Checkpoints are cadenced at absolute ``checkpoint_every`` multiples
    (windows force-split there, exactly like the bare trainer loop) and
    are what rollback restores; with a ``guard`` active and no verified
    checkpoint at/below ``start_step``, a baseline save is taken first so
    the very first window is already protected. ``on_sync(gstep, state,
    window_losses, dt)`` fires per clean window with *absolute* steps;
    ``on_checkpoint(gstep, kind)`` after each save.

    ``goodput`` is an optional
    :class:`..utils.trace.GoodputRecorder` (``train`` vocabulary): the
    inner loop books ``step``/``data_wait``/``host_sync``; this driver
    adds the recovery categories — ``checkpoint`` across every save
    (scheduled, baseline, emergency), ``rollback_replay`` from an
    anomaly trip through the restore AND across the replayed steps up
    to the tripped window (so redone work never masquerades as fresh
    ``step`` time), ``preempted_lost`` from the preemption flag to
    exit. Segments close exactly when the next opens: the ledger
    partitions wall time whatever the fault schedule does.
    """
    from ..utils import metrics as _metrics

    if emergency_ckpt is None:
        emergency_ckpt = ckpt
    template = _abstract_like(state)
    report = ResilienceReport()
    done = start_step        # accepted model step
    data_pos = start_step    # data-stream position (diverges under skip)
    # Model step -> data-stream position when that step's checkpoint was
    # taken. Skips shift data_pos ahead of done, so a rollback must land
    # the STREAM where it was at the restored step, not at the raw step
    # index (which would replay — or re-skip into — the wrong batches).
    data_at: Dict[int, int] = {start_step: start_step}
    consecutive = 0
    trip_high = start_step  # furthest window a trip has reached
    rollback_counter = _metrics.counter("tk8s_train_anomaly_rollbacks_total")

    if (guard is not None and ckpt is not None
            and not start_is_checkpointed):
        # Rollback needs a landing spot AT start_step in the scheduled
        # dir — resume-from-emergency leaves the scheduled dir's newest
        # step behind the resume point, and rolling back past start_step
        # would discard durable progress and corrupt the report's
        # step/loss alignment. ``start_is_checkpointed`` (the caller just
        # restored this exact step from ``ckpt``) skips the re-hash.
        if ckpt.latest_verified_step() != start_step:
            if goodput is not None:
                goodput.transition("checkpoint")
            ckpt.save(start_step, state, wait=True)
            if goodput is not None:
                goodput.transition("idle")
            if on_checkpoint is not None:
                on_checkpoint(start_step, "scheduled")

    while done < target_step:
        if preemption is not None and preemption.requested:
            report.interrupted = True
            break
        made = make_batches(data_pos)
        batches, prefetch = made if isinstance(made, tuple) else (made, None)
        seg_base = done
        seg_data = data_pos  # step s in this segment reads data index
        #                      seg_data + (s - seg_base)
        last_mark = seg_base // checkpoint_every if checkpoint_every else 0
        trip: Dict[str, Any] = {}

        def _on_sync(seg_done: int, cur_state: Any,
                     window_losses: List[float], dt: float) -> None:
            nonlocal consecutive, last_mark
            gstep = seg_base + seg_done
            if guard is not None:
                anomaly = guard.screen(
                    window_losses, gstep - len(window_losses) + 1)
                if anomaly is not None:
                    trip["anomaly"] = anomaly
                    trip["window_end"] = gstep
                    raise _AnomalyTrip()
            if gstep > trip_high:
                # Only progress PAST the furthest trip resets the abort
                # budget — replayed clean windows *behind* a recurring
                # anomaly must not refill it, or a deterministic NaN more
                # than one window past the checkpoint would roll back
                # forever instead of aborting.
                consecutive = 0
            report.losses.extend(window_losses)
            report.sync_points += 1
            if ckpt is not None and checkpoint_every:
                mark = gstep // checkpoint_every
                if mark > last_mark:
                    last_mark = mark
                    prev = None
                    if goodput is not None:
                        t0 = goodput.clock()
                        prev = goodput.state
                        goodput.transition("checkpoint", t0)
                    ckpt.save(gstep, cur_state)
                    data_at[gstep] = seg_data + (gstep - seg_base)
                    if goodput is not None:
                        t1 = goodput.clock()
                        if goodput.writer is not None:
                            goodput.writer.event(
                                "train.checkpoint", t0, t1 - t0,
                                step=gstep, kind="scheduled")
                        if prev is not None:
                            goodput.transition(prev, t1)
                    if on_checkpoint is not None:
                        on_checkpoint(gstep, "scheduled")
            if on_sync is not None:
                on_sync(gstep, cur_state, window_losses, dt)

        force_sync = None
        if checkpoint_every:
            force_sync = (
                lambda n, base=seg_base: (base + n) % checkpoint_every == 0)
        should_stop = (
            (lambda: preemption.requested) if preemption is not None else None)
        # After a rollback, steps at or below the tripped window are a
        # re-execution of work a fault already paid for: the ledger
        # books them rollback_replay, never step.
        step_category = None
        if goodput is not None and trip_high > seg_base:
            step_category = (
                lambda n, base=seg_base, high=trip_high:
                "rollback_replay" if base + n <= high else "step")
        try:
            state, seg = run_pipelined(
                step_fn, state, batches,
                sync_every=sync_every, max_steps=target_step - seg_base,
                tokens_per_step=tokens_per_step, config_name=config_name,
                on_sync=_on_sync, force_sync=force_sync,
                should_stop=should_stop, prefetch=prefetch,
                step_floor_seconds=step_floor_seconds,
                goodput=goodput, goodput_step_category=step_category)
        except _AnomalyTrip:
            anomaly: Anomaly = trip["anomaly"]
            report.anomalies.append(anomaly)
            trip_high = max(trip_high, trip["window_end"])
            consecutive += 1
            if consecutive > max_rollbacks:
                _metrics.counter("tk8s_train_anomaly_aborts_total").inc()
                raise AnomalyAbortedError(
                    f"aborting after {max_rollbacks} consecutive "
                    f"loss-anomaly rollbacks without a clean window "
                    f"(last: {anomaly.reason} loss={anomaly.loss} at step "
                    f"{anomaly.step})", anomaly)
            if ckpt is None:
                _metrics.counter("tk8s_train_anomaly_aborts_total").inc()
                raise AnomalyAbortedError(
                    f"loss anomaly at step {anomaly.step} "
                    f"({anomaly.reason}, loss={anomaly.loss}) with no "
                    f"checkpoint manager to roll back to", anomaly)
            report.rollbacks += 1
            rollback_counter.inc(reason=anomaly.reason)
            # Newest checkpoint THIS RUN anchored at/below the tripped
            # window (saves only happen at clean sync points, so every
            # anchor predates the anomaly). Bounding by the run's own
            # anchors — not just the step number — keeps a rollback from
            # landing on a same-numbered stranger from an earlier run or
            # below start_step; restore still falls back further if the
            # anchor itself fails verification.
            target = max(s for s in data_at if s <= trip["window_end"])
            if goodput is not None:
                t0 = goodput.clock()
                goodput.transition("rollback_replay", t0)
                if goodput.writer is not None:
                    goodput.writer.event(
                        "train.rollback", t0,
                        window_end=trip["window_end"], target=target)
            state = ckpt.restore(template, step=target)
            good = ckpt.last_restored_step
            if goodput is not None and goodput.writer is not None:
                goodput.writer.event("train.restore", goodput.clock(),
                                     step=good, rollback=True)
            report.restored_steps.append(good)
            del report.losses[max(good - start_step, 0):]
            guard.reset_history(report.losses)  # replays must not re-enter
            done = good
            # Both branches work in DATA space, honoring earlier skips:
            # resume the stream where the restored step left it, or just
            # past the offending window's last consumed batch.
            data_pos = (seg_data + (trip["window_end"] - seg_base)
                        if skip_anomalous_window
                        else data_at.get(good, good))
            continue
        finally:
            close = getattr(batches, "close", None) or getattr(
                prefetch, "close", None)
            if close is not None:
                close()
        done = seg_base + seg.steps
        data_pos += seg.steps
        if seg.interrupted:
            report.interrupted = True
            break
        if seg.steps < target_step - seg_base:
            break  # data exhausted: a short epoch, reported not raised

    report.steps = done - start_step
    if report.interrupted:
        if goodput is not None:
            # No-op when the inner loop already opened it; covers the
            # flag tripping between segments (loop top break).
            goodput.transition("preempted_lost")
            if goodput.writer is not None:
                goodput.writer.event("train.preempt", goodput.clock(),
                                     step=done)
        # Nothing new trained (warning landed before the first step, or
        # right after a resume) => the state at ``done`` is already
        # durable (or a deterministic re-init): saving again would only
        # quarantine-and-rewrite a good on-disk step inside the kill
        # window. Skip; exit-for-resume is still correct.
        if emergency_ckpt is not None and done > start_step:
            t0 = goodput.clock() if goodput is not None else 0.0
            if goodput is not None:
                goodput.transition("checkpoint", t0)
            emergency_ckpt.save(done, state, kind="emergency")
            if goodput is not None:
                t1 = goodput.clock()
                if goodput.writer is not None:
                    goodput.writer.event("train.checkpoint", t0, t1 - t0,
                                         step=done, kind="emergency")
                goodput.transition("preempted_lost", t1)
            report.emergency_step = done
            if on_checkpoint is not None:
                on_checkpoint(done, "emergency")
    return state, report
