"""Llama-3-family decoder: pure-JAX pytree model, TPU-first.

Design choices (vs. a torch-style nn.Module translation):
- Params are a plain dict pytree; every leaf has a logical-axis tuple
  (``logical_axes``) the parallel layer maps to mesh shardings. One model
  definition serves dp/fsdp/tp/sp/ep — parallelism is data layout, not code.
- The layer stack is a single stacked tensor per weight ([L, ...]) consumed
  by ``lax.scan``: O(1) trace/compile time in depth, which is what keeps
  70B-class compiles tractable.
- ``jax.checkpoint`` on the block body (config.remat) rematerializes
  activations in backward — the standard HBM-for-FLOPs trade on TPU.
- Master weights live in f32; compute casts to bf16 at use so matmuls hit
  the MXU at full rate; softmax/norm reductions stay f32.
- MoE (num_experts > 0) swaps the dense SwiGLU for the GShard-style
  expert layer in ``ops/moe.py`` (Mixtral family).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import auto_attention, causal_attention
from ..ops.moe import moe_layer
from ..ops.norms import rms_norm
from ..ops.quantization import quantized_einsum, resolve_matmul_dtype
from ..ops.rotary import apply_rotary, rotary_tables
from .config import ModelConfig

Params = Dict[str, Any]
# attention_fn(q, k, v, positions) -> out; positions is [B, S] int32 global.
AttentionFn = Callable[
    [jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


def _dense_attention(q, k, v, positions):
    return causal_attention(q, k, v, positions, positions)


def resolve_attention(config: ModelConfig,
                      platform: Optional[str] = None,
                      ) -> Optional[AttentionFn]:
    """``config.attention`` -> attention fn, or None for the dense einsum.

    "flash" forces the Pallas blockwise kernel; off-TPU it runs in Pallas
    interpret mode so the SAME code path is testable (and parity-pinned)
    on CPU. "auto" returns the platform's best full-sequence kernel
    (flash on TPU, dense elsewhere) — mesh-aware upgrades (ring attention,
    shard_map wrapping) stay in ``train.trainer._resolve_attention``,
    which builds on this. Forced kernels assume standard positions
    (0..S-1); ``forward_hidden`` falls back to the dense einsum when a
    caller passes explicit positions (ragged prefill, packed sequences).
    """
    mode = config.attention
    if mode == "dense":
        return None
    if mode in ("flash", "flash-interpret"):
        from ..ops.flash_attention import flash_attention

        platform = platform or jax.default_backend()
        interpret = mode == "flash-interpret" or platform != "tpu"
        return lambda q, k, v, positions: flash_attention(
            q, k, v, interpret=interpret)
    return auto_attention(platform) if platform is not None else None


def resolve_weight(w: Any, ad: jnp.dtype) -> jnp.ndarray:
    """The matmul operand for a (possibly quantized) weight leaf.

    Plain arrays cast to the activation dtype as ever; an int8 leaf
    (the ``{"q", "scale"}`` pair :func:`quantize_weights` produces)
    dequantizes per-channel at its point of use — XLA fuses the scale
    multiply into the consuming matmul, and on TPU the HBM read is the
    int8 tensor, which is the whole win. Keying off the leaf structure
    (not a config flag) means a params tree can never be half-honored:
    whatever tree arrives is computed correctly.
    """
    if isinstance(w, dict):
        return (w["q"].astype(jnp.float32) * w["scale"]).astype(ad)
    return w.astype(ad)


def weight_einsum(spec: str, x: jnp.ndarray, leaf: Any,
                  config: ModelConfig,
                  out_dtype: Optional[jnp.dtype] = None) -> jnp.ndarray:
    """One weight matmul, honoring ``config.matmul_dtype``.

    The single chokepoint for every big serving/training einsum: on the
    resolved ``"f32"`` path this IS the historical call —
    ``einsum(spec, x, resolve_weight(leaf))`` — bitwise unchanged. On
    the ``"int8"``/``"fp8"`` paths a quantized leaf contracts through
    :func:`ops.quantization.quantized_einsum` instead: the stored
    low-precision tensor is the dot operand (int8 dot, int32
    accumulate; scales folded into the epilogue), and no dequantized
    full-precision weight is materialized. Unquantized leaves always
    take the f32 path — ``matmul_dtype`` selects arithmetic for
    quantized storage, it does not quantize anything itself.
    """
    ad = config.activation_dtype
    if isinstance(leaf, dict):
        mode = resolve_matmul_dtype(config.matmul_dtype,
                                    config.weight_quant)
        if mode != "f32":
            return quantized_einsum(
                spec, x, leaf["q"], leaf["scale"],
                out_dtype=out_dtype if out_dtype is not None else ad)
    w = resolve_weight(leaf, ad)
    if out_dtype is not None:
        return jnp.einsum(spec, x, w, preferred_element_type=out_dtype)
    return jnp.einsum(spec, x, w)


# Weight leaf -> axes its matmul contracts over (per-channel int8 scales
# reduce exactly these, keeping one scale per OUTPUT channel per layer).
# Stacked layer weights carry a leading L axis, hence the +1 offsets.
_QUANT_AXES_LAYERS: Dict[str, Tuple[int, ...]] = {
    "wq": (1,), "wk": (1,), "wv": (1,),   # [L, d, h, k]: contract d
    "wo": (1, 2),                          # [L, h, k, d]: contract h, k
    "w1": (1,), "w3": (1,),                # [L, d, f]: contract d
    "w2": (1,),                            # [L, f, d]: contract f
    "moe_w1": (2,), "moe_w3": (2,),        # [L, e, d, f]: contract d
    "moe_w2": (2,),                        # [L, e, f, d]: contract f
}


def quantize_weights(params: Params, config: ModelConfig,
                     dtype: str = "int8") -> Tuple[Params, ModelConfig]:
    """Per-channel symmetric quantization for the big decode matmuls.

    Returns a NEW ``(params, config)`` pair: every weight named in
    :data:`_QUANT_AXES_LAYERS` plus ``lm_head`` becomes a
    ``{"q": int8|float8_e4m3fn, "scale": f32}`` leaf, and the config
    records ``weight_quant=dtype`` — the two rewrites travel together
    (the apply-policy shape from train/precision.py), so a half-applied
    state cannot exist. The caller's f32 master tree is untouched
    (pure function); ``embed`` (a gather, not a matmul), the MoE
    router (tiny, routing-sensitive), and the norms stay full
    precision. Idempotent: quantizing twice at the same dtype is the
    identity; re-quantizing an already-quantized tree at a DIFFERENT
    dtype raises (quantization losses must not compound silently).
    ``dtype="fp8"`` raises ``Fp8UnavailableError`` where this jax build
    lacks ``float8_e4m3fn`` — a loud typed failure, never a fallback.
    """
    from dataclasses import replace

    from ..ops.quantization import fp8_dtype, quantize_channelwise

    if dtype not in ("int8", "fp8"):
        raise ValueError(
            f"quantize_weights dtype must be 'int8' or 'fp8', got "
            f"{dtype!r}")
    if config.weight_quant == dtype:
        return params, config
    if config.weight_quant != "none":
        raise ValueError(
            f"params are already weight_quant={config.weight_quant!r}; "
            f"re-quantizing to {dtype!r} would compound rounding losses "
            f"— quantize from the full-precision tree instead")
    qdtype = jnp.int8 if dtype == "int8" else fp8_dtype()

    def qleaf(w, axes):
        q, scale = quantize_channelwise(w, axes, qdtype)
        return {"q": q, "scale": scale}

    layers = dict(params["layers"])
    for name, axes in _QUANT_AXES_LAYERS.items():
        if name in layers:
            layers[name] = qleaf(layers[name], axes)
    new = dict(params)
    new["layers"] = layers
    new["lm_head"] = qleaf(params["lm_head"], (0,))  # [d, v]: contract d
    return new, replace(config, weight_quant=dtype)


def remat_block(body: Callable, config: ModelConfig) -> Callable:
    """Apply the configured rematerialization policy to a block body —
    the single source of the remat knob for the sequential stack and the
    pipeline stages (train/pipeline.py). "none" (or remat=False) saves
    everything; "full" recomputes the whole block in backward; "dots"
    saves MXU outputs and recomputes only elementwise ops."""
    if not config.remat or config.remat_policy == "none":
        return body
    policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
              if config.remat_policy == "dots" else None)
    return jax.checkpoint(body, policy=policy)


def init_params(config: ModelConfig, key: jax.Array) -> Params:
    """Normal(0.02) init; residual-out projections scaled by 1/sqrt(2L)."""
    wd = config.weight_dtype
    d, dh = config.embed_dim, config.head_dim
    h, hkv = config.num_heads, config.num_kv_heads
    ll, f, v = config.num_layers, config.mlp_dim, config.vocab_size
    std, out_std = 0.02, 0.02 / (2 * ll) ** 0.5
    keys = iter(jax.random.split(key, 16))

    def norm(shape):
        return jnp.ones(shape, dtype=wd)

    def rnd(shape, s=std):
        return (jax.random.normal(next(keys), shape, dtype=jnp.float32) * s
                ).astype(wd)

    layers: Params = {
        "attn_norm": norm((ll, d)),
        "wq": rnd((ll, d, h, dh)),
        "wk": rnd((ll, d, hkv, dh)),
        "wv": rnd((ll, d, hkv, dh)),
        "wo": rnd((ll, h, dh, d), out_std),
        "mlp_norm": norm((ll, d)),
    }
    if config.is_moe:
        e = config.num_experts
        layers.update({
            "router": rnd((ll, d, e)),
            "moe_w1": rnd((ll, e, d, f)),
            "moe_w3": rnd((ll, e, d, f)),
            "moe_w2": rnd((ll, e, f, d), out_std),
        })
    else:
        layers.update({
            "w1": rnd((ll, d, f)),
            "w3": rnd((ll, d, f)),
            "w2": rnd((ll, f, d), out_std),
        })
    return {
        "embed": rnd((v, d)),
        "layers": layers,
        "final_norm": norm((d,)),
        "lm_head": rnd((d, v)),
    }


def logical_axes(config: ModelConfig) -> Params:
    """Same structure as init_params, leaves = logical-axis tuples."""
    layers: Params = {
        "attn_norm": ("layers", "norm"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "mlp_norm": ("layers", "norm"),
    }
    if config.is_moe:
        layers.update({
            "router": ("layers", "embed", None),
            "moe_w1": ("layers", "expert", "embed", "mlp"),
            "moe_w3": ("layers", "expert", "embed", "mlp"),
            "moe_w2": ("layers", "expert", "mlp", "embed"),
        })
    else:
        layers.update({
            "w1": ("layers", "embed", "mlp"),
            "w3": ("layers", "embed", "mlp"),
            "w2": ("layers", "mlp", "embed"),
        })
    return {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def _qkv(x: jnp.ndarray, layer: Params, config: ModelConfig,
         cos: jnp.ndarray, sin: jnp.ndarray, positions: jnp.ndarray):
    """Projected + rotary-encoded q/k/v for a block input ([B, S, ...])."""
    h = rms_norm(x, layer["attn_norm"], config.norm_eps)
    q = weight_einsum("bsd,dhk->bshk", h, layer["wq"], config)
    k = weight_einsum("bsd,dhk->bshk", h, layer["wk"], config)
    v = weight_einsum("bsd,dhk->bshk", h, layer["wv"], config)
    q = apply_rotary(q, cos, sin, positions)
    k = apply_rotary(k, cos, sin, positions)
    return q, k, v


def _mlp(x: jnp.ndarray, layer: Params, config: ModelConfig,
         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Post-attention half of the block: norm + SwiGLU (dense or MoE).
    Returns (residual delta, aux loss)."""
    ad = config.activation_dtype

    def w(name):
        return resolve_weight(layer[name], ad)

    h = rms_norm(x, layer["mlp_norm"], config.norm_eps)
    if config.is_moe:
        moe_params = {
            "router": layer["router"],
            "w1": w("moe_w1"), "w3": w("moe_w3"), "w2": w("moe_w2"),
        }
        return moe_layer(
            h, moe_params, config.num_selected, config.capacity_factor,
            dispatch_mode=config.moe_dispatch)
    gate = jax.nn.silu(
        weight_einsum("bsd,df->bsf", h, layer["w3"], config)
        .astype(jnp.float32)
    ).astype(ad)
    up = weight_einsum("bsd,df->bsf", h, layer["w1"], config)
    y = weight_einsum("bsf,fd->bsd", gate * up, layer["w2"], config)
    return y, jnp.zeros((), dtype=jnp.float32)


def _block(
    x: jnp.ndarray,  # [B, S, D] activation dtype
    layer: Params,  # one layer's weights (no leading L dim)
    config: ModelConfig,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: jnp.ndarray,
    attention_fn: AttentionFn,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    q, k, v = _qkv(x, layer, config, cos, sin, positions)
    attn = attention_fn(q, k, v, positions)
    x = project_out(x, attn, layer, config)
    y, aux = _mlp(x, layer, config)
    return x + y, aux


def forward(
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32
    config: ModelConfig,
    attention_fn: Optional[AttentionFn] = None,
    positions: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (logits [B, S, V] f32, moe aux loss scalar)."""
    x, aux_total = forward_hidden(params, tokens, config, attention_fn,
                                  positions)
    return unembed(x, params, config), aux_total


def forward_hidden(
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32
    config: ModelConfig,
    attention_fn: Optional[AttentionFn] = None,
    positions: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The decoder stack without the vocab projection: returns (hidden
    states [B, S, D] before the final norm, moe aux loss scalar). The
    fused-CE path (ops/fused_ce.py) consumes this so [B, S, V] logits are
    never materialized."""
    b, s = tokens.shape
    if attention_fn is None:
        # Config-forced kernels only apply at standard positions: a forced
        # flash kernel ignores its positions operand, so callers with
        # explicit positions (ragged prefill) keep the dense einsum.
        if positions is None:
            attention_fn = resolve_attention(config)
        attention_fn = attention_fn or _dense_attention
    ad = config.activation_dtype
    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(s, dtype=jnp.int32), (b, s))
    cos, sin = rotary_tables(
        config.head_dim, config.max_seq_len, config.rope_theta)

    x = params["embed"].astype(ad)[tokens]

    def body(carry, layer):
        out, aux = _block(
            carry, layer, config, cos, sin, positions, attention_fn)
        return out, aux

    body = remat_block(body, config)
    if config.scan_layers:
        x, auxs = lax.scan(body, x, params["layers"])
        aux_total = auxs.sum()
    else:
        aux_total = jnp.zeros((), dtype=jnp.float32)
        for i in range(config.num_layers):
            layer_i = jax.tree.map(lambda p: p[i], params["layers"])
            x, aux = body(x, layer_i)
            aux_total = aux_total + aux

    return x, aux_total


def final_norm_hidden(x: jnp.ndarray, params: Params,
                      config: ModelConfig) -> jnp.ndarray:
    """The hidden states the vocab head consumes (final rms_norm applied).
    Single source of truth for both heads: ``unembed`` (full logits) and
    the fused-CE path (ops/fused_ce.py) — any head change lands in both."""
    return rms_norm(x, params["final_norm"], config.norm_eps)


def head_weights(params: Params, config: ModelConfig) -> jnp.ndarray:
    """The lm head matrix in activation dtype — the exact operand
    ``unembed`` contracts with."""
    return resolve_weight(params["lm_head"], config.activation_dtype)


def unembed(x: jnp.ndarray, params: Params, config: ModelConfig):
    """Final norm + lm_head: [B, S, D] -> f32 logits [B, S, V]."""
    x = final_norm_hidden(x, params, config)
    return weight_einsum("bsd,dv->bsv", x, params["lm_head"], config,
                         out_dtype=jnp.float32)


def project_out(x: jnp.ndarray, attn: jnp.ndarray, layer: Params,
                config: ModelConfig) -> jnp.ndarray:
    """Attention output projection + residual add."""
    return x + weight_einsum("bshk,hkd->bsd", attn, layer["wo"], config)
