"""Paged-KV-cache prefill and decode for the serving engine.

The training-side twin of this file is ``models/generate.py``: same weight
pytree, same ``llama._qkv`` / ``llama._mlp`` block math, same explicit-
position attention masking — so greedy decode through pages reproduces the
contiguous ``decode_step`` loop token for token (pinned in
tests/test_paged_attention.py). What changes is the cache layout:

* ``generate.KVCache`` is one contiguous ``[L, B, max_len, ...]`` strip —
  perfect for a fixed batch decoding in lockstep, hopeless for a serving
  batch where sequences arrive, finish, and differ in length by 100x
  (every sequence pays ``max_len``, and batch membership is baked into
  the array).
* :class:`PagedKVCache` is a static pool of fixed-size pages
  (``[L, num_blocks, block_size, Hkv, Dh]``) plus per-sequence block
  tables owned by the scheduler (``serve/``). Admitting, growing, or
  evicting a sequence mutates *table entries*, never array shapes, so
  the batched decode step compiles exactly once.

Shape discipline (what "never retraces" means concretely): every jitted
entrypoint here has operand shapes fixed by engine configuration —
``(max_batch, blocks_per_seq, block_size, padded_prompt_len)`` — and
takes real lengths as *data* (int32 operands), never as Python ints.

Page 0 is the shared trash page (``ops.paged_attention.TRASH_PAGE``):
padded table entries and inactive batch slots scatter/gather there, and
position masking keeps its garbage out of every real sequence's support.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp
from jax import lax

from ..ops.paged_attention import ragged_paged_attention, scatter_token
from ..ops.rotary import rotary_tables
from .config import ModelConfig
from . import llama
from .generate import init_cache, prefill


class PagedKVCache(NamedTuple):
    """The static page pool. Per-sequence block tables live with the
    scheduler, not here — the pool is just memory."""

    k: jnp.ndarray  # [L, num_blocks, block_size, Hkv, Dh]
    v: jnp.ndarray  # [L, num_blocks, block_size, Hkv, Dh]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]


def init_paged_cache(config: ModelConfig, num_blocks: int,
                     block_size: int) -> PagedKVCache:
    if num_blocks < 2:
        raise ValueError(
            f"num_blocks must be >= 2 (page 0 is the reserved trash page), "
            f"got {num_blocks}")
    shape = (config.num_layers, num_blocks, block_size,
             config.num_kv_heads, config.head_dim)
    # Two distinct buffers, never one aliased zeros array: the engine
    # donates k and v to its jitted steps, and XLA rejects donating the
    # same buffer twice.
    return PagedKVCache(k=jnp.zeros(shape, config.activation_dtype),
                        v=jnp.zeros(shape, config.activation_dtype))


def paged_prefill(
    params,
    tokens: jnp.ndarray,  # [1, P] int32, right-padded to the trace width
    length: jnp.ndarray,  # [] int32 — real prompt tokens (<= P)
    config: ModelConfig,
    cache: PagedKVCache,
    block_table: jnp.ndarray,  # [P // block_size] int32 physical pages
) -> Tuple[jnp.ndarray, PagedKVCache]:
    """Run one right-padded prompt and land its K/V in pages.

    Returns (logits [V] f32 at the last *real* token, updated pool).

    Right-padding is the load-bearing choice: with causal masking, pad
    tokens sit at positions > length-1 and cannot perturb any real
    position's logits, so the padded batch-of-one forward equals the
    exact-length forward at ``length - 1`` — the parity contract pinned
    in tests/test_generate.py (and exactly what left-padding breaks).
    The prompt's K/V pages then hold real tokens in slots < length and
    pad garbage above, which every later paged-attention call masks out.
    """
    _, p = tokens.shape
    bs = cache.block_size
    if p % bs != 0:
        raise ValueError(
            f"padded prompt length {p} must be a multiple of the "
            f"block size {bs} (pad the trace width, not the pages)")
    t = p // bs
    if block_table.shape != (t,):
        raise ValueError(
            f"block_table must cover the padded prompt: expected shape "
            f"({t},), got {block_table.shape}")
    contiguous = init_cache(config, 1, p)
    # Unembed only the last real position: the full padded-width logits
    # would be the admission's largest buffer (generate.prefill docstring).
    logits, contiguous = prefill(params, tokens, config, contiguous,
                                 last_position=(length - 1)[None])
    last = logits[0, 0]  # [V]
    # [L, 1, P, Hkv, Dh] -> [L, T, bs, Hkv, Dh], scattered to this
    # sequence's pages. Padded table entries (trash) take pad garbage;
    # partially-filled last pages carry pad garbage above `length` until
    # decode overwrites those slots one token at a time.
    ll = config.num_layers
    k = contiguous.k.reshape(ll, t, bs, *contiguous.k.shape[3:])
    v = contiguous.v.reshape(ll, t, bs, *contiguous.v.shape[3:])
    return last, PagedKVCache(k=cache.k.at[:, block_table].set(k),
                              v=cache.v.at[:, block_table].set(v))


def paged_decode_step(
    params,
    token: jnp.ndarray,  # [B] int32 — each sequence's latest token
    config: ModelConfig,
    cache: PagedKVCache,
    block_tables: jnp.ndarray,  # [B, T] int32
    lengths: jnp.ndarray,  # [B] int32 — tokens already written per seq
) -> Tuple[jnp.ndarray, PagedKVCache]:
    """One ragged decode step: returns (logits [B, V] f32, updated pool).

    Sequence ``b``'s token lands at position ``lengths[b]`` in its own
    pages; attention then covers positions ``0..lengths[b]``. Inactive
    batch slots ride along with an all-trash table and length 0 — their
    logits are garbage the scheduler discards, their writes hit only the
    trash page, and their cost is what static shapes buy us.
    """
    b = token.shape[0]
    ad = config.activation_dtype
    positions = lengths[:, None].astype(jnp.int32)  # [B, 1] — ragged!
    cos, sin = rotary_tables(
        config.head_dim, config.max_seq_len, config.rope_theta)
    x = params["embed"].astype(ad)[token[:, None]]  # [B, 1, D]

    def body(carry, layer_and_pages):
        x = carry
        layer, kp, vp = layer_and_pages
        q, k, v = llama._qkv(x, layer, config, cos, sin, positions)
        kp, vp = scatter_token(kp, vp, k, v, block_tables, lengths)
        attn = ragged_paged_attention(
            q, kp, vp, block_tables, lengths + 1)
        x = llama.project_out(x, attn, layer, config)
        y, _ = llama._mlp(x, layer, config)
        return x + y, (kp, vp)

    x, (kp, vp) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    logits = llama.unembed(x, params, config)[:, 0, :]
    return logits, PagedKVCache(k=kp, v=vp)
