"""Paged-KV-cache prefill and decode for the serving engine.

The training-side twin of this file is ``models/generate.py``: same weight
pytree, same ``llama._qkv`` / ``llama._mlp`` block math, same explicit-
position attention masking — so greedy decode through pages reproduces the
contiguous ``decode_step`` loop token for token (pinned in
tests/test_paged_attention.py). What changes is the cache layout:

* ``generate.KVCache`` is one contiguous ``[L, B, max_len, ...]`` strip —
  perfect for a fixed batch decoding in lockstep, hopeless for a serving
  batch where sequences arrive, finish, and differ in length by 100x
  (every sequence pays ``max_len``, and batch membership is baked into
  the array).
* :class:`PagedKVCache` is a static pool of fixed-size pages
  (``[L, num_blocks, Hkv, block_size, Dh]`` — head-major, the TPU
  kernel's tiling-friendly page plane) plus per-sequence block
  tables owned by the scheduler (``serve/``). Admitting, growing, or
  evicting a sequence mutates *table entries*, never array shapes, so
  the batched decode step compiles exactly once.

Shape discipline (what "never retraces" means concretely): every jitted
entrypoint here has operand shapes fixed by engine configuration —
``(max_batch, blocks_per_seq, block_size, padded_prompt_len)`` — and
takes real lengths as *data* (int32 operands), never as Python ints.

Page 0 is the shared trash page (``ops.paged_attention.TRASH_PAGE``):
padded table entries and inactive batch slots scatter/gather there, and
position masking keeps its garbage out of every real sequence's support.

Quantized pools (``kv_dtype="int8"``): pages hold int8 K/V and the cache
carries per-page-per-head f32 scales (``[L, num_blocks, Hkv]``) —
roughly ``block_size * Dh / 1`` data bytes per 4 scale bytes, so pool
memory drops by ~4x vs f32 pages (~2x vs bf16), which is that many more
concurrent sequences per chip. Writes quantize (anchored scales,
``ops/quantization.py`` — the quantizer is write-order invariant, so
preemption's re-prefill adds no quantization-order divergence on top of
the forward-path numerics); reads dequantize fused into the attention
compute. The full-precision pool never exists.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple, Union

import jax.numpy as jnp
from jax import lax

# The serve/CLI-facing page-storage knob (``tk8s serve --kv-dtype``):
# "auto" stores pages in the model's activation dtype (the pre-quant
# behavior), "bf16" forces bfloat16 pages, "int8" turns on quantized
# pages + scales. Pinned in constants.py (the CLI registers the choices
# on jax-less machines; this module validates them at runtime).
from ..constants import KV_DTYPES
from ..ops.paged_attention import (
    TRASH_PAGE,
    paged_prefill_attention,
    ragged_paged_attention,
    ragged_verify_attention,
    resolve_paged_impl,
    scatter_chunk,
    scatter_span,
    scatter_token,
    table_slots,
)
from ..ops.quantization import fp8_dtype, kv_quant_error, quantize_kv_pages
from ..ops.rotary import rotary_tables
from .config import ModelConfig
from . import llama
from .generate import init_cache, prefill


class PagedKVCache(NamedTuple):
    """The static page pool. Per-sequence block tables live with the
    scheduler, not here — the pool is just memory. ``k_scale``/
    ``v_scale`` are present exactly when the pool is int8 (per-page-
    per-head anchored scales)."""

    k: jnp.ndarray  # [L, num_blocks, Hkv, block_size, Dh]
    v: jnp.ndarray  # [L, num_blocks, Hkv, block_size, Dh]
    k_scale: Optional[jnp.ndarray] = None  # [L, num_blocks, Hkv] f32
    v_scale: Optional[jnp.ndarray] = None  # [L, num_blocks, Hkv] f32

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        """Whether pages carry quantized values + scales (int8 or fp8).
        Keyed off the scale tensors, not the page dtype, so adding a
        scaled dtype can never leave a path half-aware of it."""
        return self.k_scale is not None

    @property
    def pool_bytes(self) -> int:
        """Device bytes of the K/V page arrays (scales excluded)."""
        return self.k.nbytes + self.v.nbytes

    @property
    def scale_bytes(self) -> int:
        if self.k_scale is None:
            return 0
        return self.k_scale.nbytes + self.v_scale.nbytes


def init_paged_cache(config: ModelConfig, num_blocks: int,
                     block_size: int,
                     kv_dtype: str = "auto") -> PagedKVCache:
    if num_blocks < 2:
        raise ValueError(
            f"num_blocks must be >= 2 (page 0 is the reserved trash page), "
            f"got {num_blocks}")
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype must be one of {KV_DTYPES}, got {kv_dtype!r}")
    shape = (config.num_layers, num_blocks, config.num_kv_heads,
             block_size, config.head_dim)
    if kv_dtype == "int8":
        dtype: jnp.dtype = jnp.dtype(jnp.int8)
    elif kv_dtype == "fp8":
        # Raises Fp8UnavailableError where this jax build lacks the
        # dtype — the loud typed path, never a silent int8/bf16 swap.
        dtype = fp8_dtype()
    elif kv_dtype == "bf16":
        dtype = jnp.dtype(jnp.bfloat16)
    else:
        dtype = config.activation_dtype
    # Distinct buffers, never one aliased zeros array: the engine
    # donates every pool array to its jitted steps, and XLA rejects
    # donating the same buffer twice.
    if kv_dtype in ("int8", "fp8"):
        sshape = (config.num_layers, num_blocks, config.num_kv_heads)
        return PagedKVCache(
            k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
            k_scale=jnp.zeros(sshape, jnp.float32),
            v_scale=jnp.zeros(sshape, jnp.float32))
    return PagedKVCache(k=jnp.zeros(shape, dtype),
                        v=jnp.zeros(shape, dtype))


def paged_prefill(
    params,
    tokens: jnp.ndarray,  # [1, P] int32, right-padded to the trace width
    length: jnp.ndarray,  # [] int32 — real prompt tokens (<= P)
    config: ModelConfig,
    cache: PagedKVCache,
    block_table: jnp.ndarray,  # [P // block_size] int32 physical pages
    with_quant_error: bool = False,
) -> Union[Tuple[jnp.ndarray, PagedKVCache],
           Tuple[jnp.ndarray, PagedKVCache, Tuple[jnp.ndarray,
                                                  jnp.ndarray]]]:
    """Run one right-padded prompt and land its K/V in pages.

    Returns (logits [V] f32 at the last *real* token, updated pool) —
    plus a ``(k_err, v_err)`` pair of device scalars (mean relative
    dequantization error of the scattered pages, the
    ``tk8s_serve_quant_error`` gauge's source) when ``with_quant_error``
    is set on a quantized pool.

    Right-padding is the load-bearing choice: with causal masking, pad
    tokens sit at positions > length-1 and cannot perturb any real
    position's logits, so the padded batch-of-one forward equals the
    exact-length forward at ``length - 1`` — the parity contract pinned
    in tests/test_generate.py (and exactly what left-padding breaks).
    The prompt's K/V pages then hold real tokens in slots < length and
    pad garbage above, which every later paged-attention call masks out.
    """
    _, p = tokens.shape
    bs = cache.block_size
    if p % bs != 0:
        raise ValueError(
            f"padded prompt length {p} must be a multiple of the "
            f"block size {bs} (pad the trace width, not the pages)")
    t = p // bs
    if block_table.shape != (t,):
        raise ValueError(
            f"block_table must cover the padded prompt: expected shape "
            f"({t},), got {block_table.shape}")
    if with_quant_error and not cache.quantized:
        raise ValueError("with_quant_error only applies to int8 pools")
    contiguous = init_cache(config, 1, p)
    # Unembed only the last real position: the full padded-width logits
    # would be the admission's largest buffer (generate.prefill docstring).
    logits, contiguous = prefill(params, tokens, config, contiguous,
                                 last_position=(length - 1)[None])
    last = logits[0, 0]  # [V]
    # [L, 1, P, Hkv, Dh] -> [L, T, Hkv, bs, Dh] (the head-major page
    # plane: split tokens into pages, then swap heads ahead of slots),
    # scattered to this sequence's pages. Padded table entries (trash)
    # take pad garbage; partially-filled last pages carry pad garbage
    # above `length` until decode overwrites those slots one at a time.
    ll = config.num_layers
    k = jnp.transpose(
        contiguous.k.reshape(ll, t, bs, *contiguous.k.shape[3:]),
        (0, 1, 3, 2, 4))
    v = jnp.transpose(
        contiguous.v.reshape(ll, t, bs, *contiguous.v.shape[3:]),
        (0, 1, 3, 2, 4))
    if not cache.quantized:
        # Explicit cast: kv_dtype="bf16" pools under an f32 activation
        # config downcast on write, exactly as the decode scatter does.
        return last, cache._replace(
            k=cache.k.at[:, block_table].set(k.astype(cache.k.dtype)),
            v=cache.v.at[:, block_table].set(v.astype(cache.v.dtype)))
    # Anchored whole-page quantization: identical, slot for slot, to
    # what token-at-a-time decode writes produce for the same token
    # values — the quantizer's contribution to the recompute-on-readmit
    # (preemption) parity contract (ops/quantization.py docstring).
    qk, sk = quantize_kv_pages(k, cache.k.dtype)
    qv, sv = quantize_kv_pages(v, cache.v.dtype)
    new = PagedKVCache(
        k=cache.k.at[:, block_table].set(qk),
        v=cache.v.at[:, block_table].set(qv),
        k_scale=cache.k_scale.at[:, block_table].set(sk),
        v_scale=cache.v_scale.at[:, block_table].set(sv))
    if not with_quant_error:
        return last, new
    # Error over REAL slots only: pad garbage above `length` and
    # trash-table pages would otherwise dominate the gauge.
    slot = (jnp.arange(t, dtype=jnp.int32)[:, None] * bs
            + jnp.arange(bs, dtype=jnp.int32)[None, :])  # [T, bs]
    mask = (slot < length)[None, :, None, :, None]
    err = (kv_quant_error(qk, sk[:, :, :, None, None], k, mask),
           kv_quant_error(qv, sv[:, :, :, None, None], v, mask))
    return last, new, err


def paged_prefill_chunk(
    params,
    tokens: jnp.ndarray,  # [1, C] int32 — one window, right-padded
    offset: jnp.ndarray,  # [] int32 — tokens already in pages (C-aligned)
    chunk_len: jnp.ndarray,  # [] int32 — real tokens this window (1..C)
    config: ModelConfig,
    cache: PagedKVCache,
    block_table: jnp.ndarray,  # [T] int32 — the sequence's FULL table
    with_quant_error: bool = False,
    attention_impl: Optional[str] = None,
) -> Union[Tuple[jnp.ndarray, PagedKVCache],
           Tuple[jnp.ndarray, PagedKVCache, Tuple[jnp.ndarray,
                                                  jnp.ndarray]]]:
    """One chunk of an incremental prefill: run ``C`` prompt tokens at
    positions ``offset .. offset+C-1``, land their K/V in this window's
    pages, and attend to everything written so far (earlier chunks,
    prefix-cache pages, and this chunk itself, causally).

    This is the trace chunked prefill and prefix-cache reuse both ride
    (docs/guide/serving.md): the engine walks a prompt window by window
    — reused windows are *skipped outright* (their pages already hold
    this exact prefix's K/V), computed windows all share this ONE
    ``[1, C]`` trace, so a 32k-token prompt costs many small steps the
    scheduler interleaves with decode instead of one batch-freezing
    monolith. Returns (logits [V] f32 at row ``chunk_len - 1``, updated
    pool) — the logits only matter on the final window, where that row
    is the prompt's last real token. Plus the ``(k_err, v_err)`` device
    scalars over this window's real slots when ``with_quant_error`` is
    set on a quantized pool.

    Contract with the engine (all static-shape or host-enforced):
    ``C % block_size == 0``; ``offset`` is a multiple of ``C`` (windows
    are *absolute* — window ``j`` always covers tokens
    ``[j*C, (j+1)*C)`` whatever was reused, which is what makes outputs
    with prefix sharing ON bitwise equal to OFF: every computed window
    presents the identical trace and identical page contents either
    way); ``T * block_size % C == 0`` so every window's pages sit inside
    the table.

    Numerics: per-token math is the same ``llama._qkv`` /
    ``causal_attention`` / ``llama._mlp`` chain as ``paged_prefill``'s
    dense forward; attention keys are gathered at the table's fixed
    ``T * block_size`` width with explicit positions, so masked slots
    (future tokens, pad garbage, trash pages) contribute exactly zero.
    ``attention_impl`` picks the chunk-attention implementation (the
    ``paged_decode_step`` contract): ``None`` resolves
    ``config.attention`` for the current backend — the fused Pallas
    chunk kernel on TPU, the dense gather+attention reference elsewhere.
    """
    _, c = tokens.shape
    bs = cache.block_size
    t = block_table.shape[0]
    if c % bs != 0:
        raise ValueError(
            f"chunk width {c} must be a multiple of the block size {bs}")
    if (t * bs) % c != 0:
        raise ValueError(
            f"table width {t * bs} tokens must be a multiple of the "
            f"chunk width {c} (pad the table, not the chunk)")
    if with_quant_error and not cache.quantized:
        raise ValueError("with_quant_error only applies to int8 pools")
    w = c // bs
    ad = config.activation_dtype
    quantized = cache.quantized
    if attention_impl is None:
        attention_impl = resolve_paged_impl(config.attention)
    positions = (offset + jnp.arange(c, dtype=jnp.int32))[None]  # [1, C]
    cos, sin = rotary_tables(
        config.head_dim, config.max_seq_len, config.rope_theta)
    x = params["embed"].astype(ad)[tokens]  # [1, C, D]
    window = lax.dynamic_slice(block_table, (offset // bs,), (w,))

    def body(carry, layer_and_pages):
        x = carry
        if quantized:
            layer, kp, vp, ks, vs = layer_and_pages
        else:
            layer, kp, vp = layer_and_pages
            ks = vs = None
        q, k, v = llama._qkv(x, layer, config, cos, sin, positions)
        written = scatter_chunk(kp, vp, k, v, window, ks, vs)
        if quantized:
            kp, vp, ks, vs = written
        else:
            kp, vp = written
        attn = paged_prefill_attention(
            q, kp, vp, block_table, offset, ks, vs, impl=attention_impl)
        x = llama.project_out(x, attn, layer, config)
        y, _ = llama._mlp(x, layer, config)
        ys = (kp, vp, ks, vs) if quantized else (kp, vp)
        if with_quant_error:
            # Exact window K/V ride out as ys so the error is computed
            # once over all layers (ratio of sums, not mean of ratios).
            ys = ys + (k, v)
        return x + y, ys

    xs = ((params["layers"], cache.k, cache.v, cache.k_scale,
           cache.v_scale) if quantized
          else (params["layers"], cache.k, cache.v))
    x, out = lax.scan(body, x, xs)
    if quantized:
        kp, vp, ks, vs = out[:4]
        new = PagedKVCache(k=kp, v=vp, k_scale=ks, v_scale=vs)
    else:
        kp, vp = out[:2]
        new = PagedKVCache(k=kp, v=vp)
    # Unembed only the last real row of the window (the admission-logit
    # parsimony rule generate.prefill's last_position established).
    idx = jnp.reshape(chunk_len - 1, (1, 1, 1)).astype(jnp.int32)
    h = jnp.take_along_axis(x, idx, axis=1)  # [1, 1, D]
    logits = llama.unembed(h, params, config)[0, 0]  # [V]
    if not with_quant_error:
        return logits, new
    exact_k, exact_v = out[-2], out[-1]  # [L, 1, C, Hkv, Dh]
    ll = config.num_layers
    hkv, dh = config.num_kv_heads, config.head_dim
    # Window page plane per layer, same transform scatter_chunk applied.
    pk = jnp.transpose(exact_k.reshape(ll, w, bs, hkv, dh),
                       (0, 1, 3, 2, 4))
    pv = jnp.transpose(exact_v.reshape(ll, w, bs, hkv, dh),
                       (0, 1, 3, 2, 4))
    qk = kp[:, window]
    qv = vp[:, window]
    sk = ks[:, window][:, :, :, None, None]
    sv = vs[:, window][:, :, :, None, None]
    slot = (jnp.arange(w, dtype=jnp.int32)[:, None] * bs
            + jnp.arange(bs, dtype=jnp.int32)[None, :])  # [w, bs]
    mask = (slot < chunk_len)[None, :, None, :, None]
    err = (kv_quant_error(qk, sk, pk, mask),
           kv_quant_error(qv, sv, pv, mask))
    return logits, new, err


def paged_decode_step(
    params,
    token: jnp.ndarray,  # [B] int32 — each sequence's latest token
    config: ModelConfig,
    cache: PagedKVCache,
    block_tables: jnp.ndarray,  # [B, T] int32
    lengths: jnp.ndarray,  # [B] int32 — tokens already written per seq
    attention_impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, PagedKVCache]:
    """One ragged decode step: returns (logits [B, V] f32, updated pool).

    Sequence ``b``'s token lands at position ``lengths[b]`` in its own
    pages; attention then covers positions ``0..lengths[b]``. Inactive
    batch slots ride along with an all-trash table and length 0 — their
    logits are garbage the scheduler discards, their writes hit only the
    trash page, and their cost is what static shapes buy us.

    ``attention_impl`` picks the ragged-attention implementation
    ("dense" reference einsum, "pallas" fused kernel,
    "pallas-interpret"); None resolves it from ``config.attention`` and
    the current backend (``ops.paged_attention.resolve_paged_impl``) —
    the paged-decode site of the ``attention=auto`` contract.
    """
    if attention_impl is None:
        attention_impl = resolve_paged_impl(config.attention)
    b = token.shape[0]
    ad = config.activation_dtype
    positions = lengths[:, None].astype(jnp.int32)  # [B, 1] — ragged!
    cos, sin = rotary_tables(
        config.head_dim, config.max_seq_len, config.rope_theta)
    x = params["embed"].astype(ad)[token[:, None]]  # [B, 1, D]
    quantized = cache.quantized

    def body(carry, layer_and_pages):
        x = carry
        if quantized:
            layer, kp, vp, ks, vs = layer_and_pages
        else:
            layer, kp, vp = layer_and_pages
            ks = vs = None
        q, k, v = llama._qkv(x, layer, config, cos, sin, positions)
        written = scatter_token(kp, vp, k, v, block_tables, lengths,
                                ks, vs)
        if quantized:
            kp, vp, ks, vs = written
        else:
            kp, vp = written
        attn = ragged_paged_attention(
            q, kp, vp, block_tables, lengths + 1, ks, vs,
            impl=attention_impl)
        x = llama.project_out(x, attn, layer, config)
        y, _ = llama._mlp(x, layer, config)
        if quantized:
            return x + y, (kp, vp, ks, vs)
        return x + y, (kp, vp)

    if quantized:
        x, (kp, vp, ks, vs) = lax.scan(
            body, x, (params["layers"], cache.k, cache.v,
                      cache.k_scale, cache.v_scale))
        new_cache = PagedKVCache(k=kp, v=vp, k_scale=ks, v_scale=vs)
    else:
        x, (kp, vp) = lax.scan(
            body, x, (params["layers"], cache.k, cache.v))
        new_cache = PagedKVCache(k=kp, v=vp)
    logits = llama.unembed(x, params, config)[:, 0, :]
    return logits, new_cache


class VerifyUndo(NamedTuple):
    """Pre-write bytes of every pool slot a verify step is about to
    touch — what :func:`paged_rewind` scatters back for rejected draft
    positions, so a speculated-then-rejected tail leaves the pool
    byte-identical to an engine that never speculated (the
    poisoned-page pin in tests/test_speculation.py). ``k_scale``/
    ``v_scale`` are the touched pages' PRE-verify scales (an anchored
    scale only moves when a slot-0 write lands, so restoring it undoes
    exactly the slot-0 rejections)."""

    k: jnp.ndarray  # [L, B, S, Hkv, Dh] page dtype
    v: jnp.ndarray  # [L, B, S, Hkv, Dh]
    k_scale: Optional[jnp.ndarray] = None  # [L, B, S, Hkv] f32
    v_scale: Optional[jnp.ndarray] = None  # [L, B, S, Hkv] f32


def _verify_slots(block_tables: jnp.ndarray, lengths: jnp.ndarray,
                  s: int, block_size: int,
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(page [B, S], offset [B, S]) of the slots verify input ``j``
    lands in: position ``lengths[b] + j`` through the SAME
    ``table_slots`` mapping every write path uses — undo capture and
    rewind must target exactly the slots ``scatter_span``'s writes
    hit, so there is deliberately no second copy of the rule."""
    pos = (lengths[:, None]
           + jnp.arange(s, dtype=jnp.int32)[None, :])  # [B, S]
    return table_slots(block_tables, pos, block_size)


def paged_verify_step(
    params,
    tokens: jnp.ndarray,  # [B, S] int32 — last sampled + spec_k drafts
    config: ModelConfig,
    cache: PagedKVCache,
    block_tables: jnp.ndarray,  # [B, T] int32
    lengths: jnp.ndarray,  # [B] int32 — tokens already written per seq
    attention_impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, PagedKVCache, VerifyUndo]:
    """One speculative verify step: ``S = spec_k + 1`` tokens per
    sequence through the stack in a single widened pass. Returns
    (logits [B, S, V] f32, updated pool, :class:`VerifyUndo`).

    Input ``j`` of sequence ``b`` is written at position
    ``lengths[b] + j`` (input 0 is the sequence's latest *real* sampled
    token — exactly what plain decode would write — inputs 1.. are the
    self-drafted proposals) and its logits row is the model's
    next-token distribution given the draft prefix before it. Row-for-
    row, the math is plain :func:`paged_decode_step` — same
    ``llama._qkv``/ragged-attention/``llama._mlp`` chain, same
    token-at-a-time pool writes (``scatter_span``), the queries merely
    batched along a second axis the ops are element-independent over —
    which is what makes an ACCEPTED row's logits bitwise equal to the
    decode step the non-speculative engine would have run (pinned in
    tests/test_speculation.py). The whole weight pass is paid ONCE for
    all S positions: the bandwidth exchange speculation exists for.

    Inactive batch slots ride an all-trash table exactly as in decode;
    sequences with fewer than ``spec_k`` drafted tokens carry pad
    inputs whose writes the engine rewinds along with rejections
    (:func:`paged_rewind`), so the pool never keeps a byte plain decode
    would not have produced.
    """
    if attention_impl is None:
        attention_impl = resolve_paged_impl(config.attention)
    b, s = tokens.shape
    ad = config.activation_dtype
    positions = (lengths[:, None]
                 + jnp.arange(s, dtype=jnp.int32)[None, :])  # [B, S]
    cos, sin = rotary_tables(
        config.head_dim, config.max_seq_len, config.rope_theta)
    x = params["embed"].astype(ad)[tokens]  # [B, S, D]
    quantized = cache.quantized
    # Pre-write bytes of every slot this step will touch, captured for
    # ALL layers in one gather before any write: each (layer, slot) is
    # written at most once below, so "before the scan" == "before its
    # write". Advanced-indexing note: the [B, S] index pair is
    # separated by slice axes, so the indexed dims land in FRONT —
    # [B, S, L, ...] — and are transposed to layer-major here once.
    page, offset = _verify_slots(block_tables, lengths, s,
                                 cache.block_size)
    undo = VerifyUndo(
        k=jnp.transpose(cache.k[:, page, :, offset], (2, 0, 1, 3, 4)),
        v=jnp.transpose(cache.v[:, page, :, offset], (2, 0, 1, 3, 4)),
        k_scale=(cache.k_scale[:, page] if quantized else None),
        v_scale=(cache.v_scale[:, page] if quantized else None))

    def body(carry, layer_and_pages):
        x = carry
        if quantized:
            layer, kp, vp, ks, vs = layer_and_pages
        else:
            layer, kp, vp = layer_and_pages
            ks = vs = None
        q, k, v = llama._qkv(x, layer, config, cos, sin, positions)
        written = scatter_span(kp, vp, k, v, block_tables, lengths,
                               ks, vs)
        if quantized:
            kp, vp, ks, vs = written
        else:
            kp, vp = written
        attn = ragged_verify_attention(
            q, kp, vp, block_tables, lengths + 1, ks, vs,
            impl=attention_impl)
        x = llama.project_out(x, attn, layer, config)
        y, _ = llama._mlp(x, layer, config)
        if quantized:
            return x + y, (kp, vp, ks, vs)
        return x + y, (kp, vp)

    if quantized:
        x, (kp, vp, ks, vs) = lax.scan(
            body, x, (params["layers"], cache.k, cache.v,
                      cache.k_scale, cache.v_scale))
        new_cache = PagedKVCache(k=kp, v=vp, k_scale=ks, v_scale=vs)
    else:
        x, (kp, vp) = lax.scan(
            body, x, (params["layers"], cache.k, cache.v))
        new_cache = PagedKVCache(k=kp, v=vp)
    logits = llama.unembed(x, params, config)  # [B, S, V]
    return logits, new_cache, undo


def paged_rewind(
    cache: PagedKVCache,
    undo: VerifyUndo,
    block_tables: jnp.ndarray,  # [B, T] int32
    lengths: jnp.ndarray,  # [B] int32 — same operand the verify took
    keep: jnp.ndarray,  # [B] int32 — verify inputs to KEEP (accepted+1)
) -> PagedKVCache:
    """Roll rejected speculative writes back: restore the pre-verify
    bytes of every slot whose input index ``j >= keep[b]``, pages and
    (for quantized pools) anchored scales alike.

    Kept slots and inactive batch rows must not be touched, and a
    conditional scatter needs somewhere to PUT its masked lanes — so
    masked writes are steered to the trash page, the same don't-care
    sink every inactive decode write already uses. Rewound slots only
    ever live in pages the sequence exclusively owns: generated tokens
    never land in prefix-cache pages (serve/engine.py admission
    guarantees writes begin past the shared full-prompt pages), which
    is why rolling them back cannot disturb a neighbor sequence —
    refcounted sharing is untouched by design, not by luck.

    A page's scale is restored only where the rejected slot was the
    page's slot 0 (the only write that moves an anchored scale), so a
    page that keeps an accepted anchor keeps its new scale.
    """
    s = undo.k.shape[2]
    page, offset = _verify_slots(block_tables, lengths, s,
                                 cache.block_size)
    rej = (jnp.arange(s, dtype=jnp.int32)[None, :]
           >= keep[:, None])  # [B, S]
    page_w = jnp.where(rej, page, TRASH_PAGE)
    # Indexed result is [B, S, L, Hkv, D] (the paged_verify_step
    # advanced-indexing note) — permute the layer-major undo to match.
    k = cache.k.at[:, page_w, :, offset].set(
        jnp.transpose(undo.k, (1, 2, 0, 3, 4)))
    v = cache.v.at[:, page_w, :, offset].set(
        jnp.transpose(undo.v, (1, 2, 0, 3, 4)))
    if not cache.quantized:
        return PagedKVCache(k=k, v=v)
    spage = jnp.where(rej & (offset == 0), page, TRASH_PAGE)
    # Single (non-separated) advanced index: dims stay in place, so the
    # layer-major undo scales already match the indexed [L, B, S, Hkv].
    k_scale = cache.k_scale.at[:, spage].set(undo.k_scale)
    v_scale = cache.v_scale.at[:, spage].set(undo.v_scale)
    return PagedKVCache(k=k, v=v, k_scale=k_scale, v_scale=v_scale)
