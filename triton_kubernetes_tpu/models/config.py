"""Model configuration registry.

Real-family shapes (llama3-8b/70b, mixtral-8x7b) match the published
architectures — they are the BASELINE.md gate workloads. The ``*-test``
configs are mesh-divisible miniatures for the 8-device CPU test mesh, and
``llama3-bench`` is sized to train comfortably in one v5e chip's 16 GB HBM
for ``bench.py``.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict

import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    embed_dim: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    mlp_dim: int
    max_seq_len: int
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    # MoE (num_experts == 0 → dense SwiGLU MLP)
    num_experts: int = 0
    num_selected: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    # "auto": sort-based dispatch once the dense [T,E,C] one-hots get big;
    # "dense" / "sort" force a path (ops/moe.py).
    moe_dispatch: str = "auto"
    # Numerics / compile shape
    dtype: str = "bfloat16"  # activation/compute dtype
    param_dtype: str = "float32"  # master weights
    remat: bool = True  # checkpoint each block: trade FLOPs for HBM
    # "full": recompute the whole block in backward (max HBM savings);
    # "dots": save MXU outputs, recompute only elementwise (norms, rotary,
    # silu) — near-zero recompute FLOPs, still drops fused temporaries;
    # "none": save everything (fastest step, largest live activations) —
    # equivalent to remat=False, selectable so the A/B is one knob.
    remat_policy: str = "full"
    # Attention kernel selection (models.llama.resolve_attention):
    # "auto"  — model default is the dense einsum; the trainer upgrades to
    #           the best kernel for its mesh (flash on TPU, ring when the
    #           seq axis is sharded);
    # "dense" — force the einsum everywhere, any mesh (A/B baseline);
    # "flash" — force the Pallas blockwise kernel: the benched HLO carries
    #           the Mosaic custom-call on TPU, and off-TPU the same kernel
    #           runs in Pallas interpret mode (CPU parity tests). A
    #           sharded seq axis still resolves to ring attention — the
    #           same blockwise online-softmax recurrence, distributed;
    # "flash-interpret" — interpret mode on every backend (tests only).
    attention: str = "auto"
    # Decode-time weight storage (models.llama.quantize_weights): "none"
    # keeps param_dtype weights; "int8"/"fp8" mean the big-matmul
    # leaves are {"q": int8|float8_e4m3fn, "scale": f32} pairs
    # (per-channel symmetric). Set ONLY by quantize_weights together
    # with the params rewrite — the pair travels as one, mirroring
    # train/precision.py's apply-policy shape, so a config/params
    # half-applied state cannot exist.
    weight_quant: str = "none"
    # Arithmetic dtype for the big matmuls (models.llama.weight_einsum):
    # "f32"  — dequantize quantized leaves, contract in full precision
    #          (the pinned reference path, bitwise-stable across PRs);
    # "int8" — contract the stored int8 weights directly (int8 dot,
    #          int32 accumulate, per-channel scales folded into the
    #          epilogue; requires weight_quant == "int8");
    # "fp8"  — analogous fp8 dot with f32 accumulate (requires
    #          weight_quant == "fp8" and a runtime jax with the dtype);
    # "auto" — quantized arithmetic on TPU when the weights are
    #          quantized, the f32 reference elsewhere — so CPU runs stay
    #          bitwise-identical to matmul_dtype="f32".
    matmul_dtype: str = "auto"

    def __post_init__(self):
        if self.remat_policy not in ("none", "full", "dots"):
            raise ValueError(
                f"remat_policy must be 'none', 'full', or 'dots', got "
                f"{self.remat_policy!r}")
        if self.attention not in ("auto", "dense", "flash",
                                  "flash-interpret"):
            raise ValueError(
                f"attention must be 'auto', 'dense', 'flash', or "
                f"'flash-interpret', got {self.attention!r}")
        if self.moe_dispatch not in ("auto", "dense", "sort"):
            raise ValueError(
                f"moe_dispatch must be 'auto', 'dense', or 'sort', got "
                f"{self.moe_dispatch!r}")
        if self.weight_quant not in ("none", "int8", "fp8"):
            raise ValueError(
                f"weight_quant must be 'none', 'int8', or 'fp8', got "
                f"{self.weight_quant!r}")
        if self.matmul_dtype not in ("auto", "f32", "int8", "fp8"):
            raise ValueError(
                f"matmul_dtype must be 'auto', 'f32', 'int8', or 'fp8', "
                f"got {self.matmul_dtype!r}")
        if self.matmul_dtype in ("int8", "fp8") \
                and self.weight_quant != self.matmul_dtype:
            raise ValueError(
                f"matmul_dtype {self.matmul_dtype!r} needs weights stored "
                f"in the same dtype (weight_quant is {self.weight_quant!r});"
                f" quantize_weights first, or use --weight-dtype")
    scan_layers: bool = True  # lax.scan over the layer stack
    # Fused cross-entropy head (ops/fused_ce.py): compute the loss in vocab
    # chunks without materializing [B,S,V] f32 logits — at Llama vocab
    # sizes those (plus their cotangent) are the step's largest activations.
    # Single-stage training path only; the pipeline keeps the logits head.
    fused_ce: bool = False
    ce_chunk: int = 8192

    @property
    def activation_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @property
    def weight_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.param_dtype)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    def num_params(self) -> int:
        """Total parameter count (all experts counted)."""
        d, v = self.embed_dim, self.vocab_size
        attn = d * self.head_dim * (
            self.num_heads * 2 + self.num_kv_heads * 2)
        if self.is_moe:
            mlp = self.num_experts * 3 * d * self.mlp_dim + d * self.num_experts
        else:
            mlp = 3 * d * self.mlp_dim
        norms = 2 * d
        per_layer = attn + mlp + norms
        return v * d * 2 + self.num_layers * per_layer + d

    def active_params(self) -> int:
        """Params touched per token (MoE: only selected experts)."""
        if not self.is_moe:
            return self.num_params()
        d = self.embed_dim
        inactive = (self.num_experts - self.num_selected) * 3 * d * self.mlp_dim
        return self.num_params() - self.num_layers * inactive


CONFIGS: Dict[str, ModelConfig] = {}


def _register(cfg: ModelConfig) -> ModelConfig:
    CONFIGS[cfg.name] = cfg
    return cfg


# ---- Llama-3 dense family (BASELINE configs 3 & 4) ----
LLAMA3_8B = _register(ModelConfig(
    name="llama3-8b", vocab_size=128_256, embed_dim=4096, num_layers=32,
    num_heads=32, num_kv_heads=8, head_dim=128, mlp_dim=14_336,
    max_seq_len=8192))

LLAMA3_70B = _register(ModelConfig(
    name="llama3-70b", vocab_size=128_256, embed_dim=8192, num_layers=80,
    num_heads=64, num_kv_heads=8, head_dim=128, mlp_dim=28_672,
    max_seq_len=8192))

# ---- Mixtral MoE family (BASELINE config 5) ----
MIXTRAL_8X7B = _register(ModelConfig(
    name="mixtral-8x7b", vocab_size=32_000, embed_dim=4096, num_layers=32,
    num_heads=32, num_kv_heads=8, head_dim=128, mlp_dim=14_336,
    max_seq_len=32_768, rope_theta=1_000_000.0,
    num_experts=8, num_selected=2))

# ---- single-chip bench config (~420M params, fits v5e 16 GB with Adam).
# head_dim 128 like the real Llama-3 family: full MXU lanes in the flash
# kernels and half the flat batch*head grid rows vs 16x64 at equal FLOPs.
# fused_ce on: at vocab 32768 the f32 logits + cotangent are the step's
# largest activations (2 x B*S*V*4B of pure HBM traffic) — the bench
# number must measure the head the production path ships with, and the
# flag had silently defaulted off here (BENCH_r05). Parity vs the dense
# head is pinned in tests/test_train.py::test_fused_ce_matches_logits_path
# and the op-level grads test.
# attention="flash": the benched HLO must CONTAIN the Pallas kernel —
# bench.py's flash_kernel_in_hlo flag exists to catch a silent dense
# fallback, and "auto" left the choice to the trainer's mesh heuristics.
# Forced here, any TPU lowering of this config carries the Mosaic
# custom-call; off-TPU the same kernel runs interpret-mode, parity-pinned
# in tests/test_train.py::test_config_attention_flash_matches_dense.
LLAMA3_BENCH = _register(ModelConfig(
    name="llama3-bench", vocab_size=32_768, embed_dim=1024, num_layers=24,
    num_heads=8, num_kv_heads=4, head_dim=128, mlp_dim=4096,
    max_seq_len=2048, remat_policy="dots", fused_ce=True,
    attention="flash"))

# ---- CPU-mesh test miniatures (dims divisible by 2-way tp/sp/fsdp) ----
LLAMA_TEST = _register(ModelConfig(
    name="llama-test", vocab_size=256, embed_dim=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=16, mlp_dim=128,
    max_seq_len=128, dtype="float32", remat=False))

MIXTRAL_TEST = _register(ModelConfig(
    name="mixtral-test", vocab_size=256, embed_dim=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=16, mlp_dim=128,
    max_seq_len=128, num_experts=4, num_selected=2,
    dtype="float32", remat=False))


def get_config(name: str, **overrides) -> ModelConfig:
    if name not in CONFIGS:
        raise KeyError(f"unknown model {name!r}; know {sorted(CONFIGS)}")
    cfg = CONFIGS[name]
    return replace(cfg, **overrides) if overrides else cfg
