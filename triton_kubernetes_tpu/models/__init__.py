"""Bundled workload model families.

The reference provisions clusters but ships no workload beyond a guestbook
example (SURVEY.md §2.3); BASELINE.md makes a MaxText-class trainer the
acceptance test for the provisioned TPU slices, so this package carries the
model zoo: the Llama-3 dense family and the Mixtral MoE family, written as
pure-JAX pytree models with logical-axis annotations consumed by
``triton_kubernetes_tpu.parallel``.
"""

from .config import (
    CONFIGS,
    ModelConfig,
    get_config,
)
from .llama import forward, init_params, logical_axes, quantize_weights
from .generate import (
    KVCache,
    decode_step,
    generate,
    init_cache,
    prefill,
    sample_token,
)
from .paged import (
    KV_DTYPES,
    PagedKVCache,
    init_paged_cache,
    paged_decode_step,
    paged_prefill,
    paged_prefill_chunk,
)
from . import mixtral

__all__ = [
    "CONFIGS",
    "ModelConfig",
    "get_config",
    "forward",
    "init_params",
    "logical_axes",
    "quantize_weights",
    "mixtral",
    "KVCache",
    "init_cache",
    "prefill",
    "decode_step",
    "generate",
    "sample_token",
    "KV_DTYPES",
    "PagedKVCache",
    "init_paged_cache",
    "paged_prefill",
    "paged_prefill_chunk",
    "paged_decode_step",
]
