"""Mixtral MoE family — the expert-parallel gate workload (BASELINE config 5).

Architecturally this is the Llama decoder with the dense SwiGLU swapped for
the top-2-routed expert layer; the implementation therefore *is*
``models.llama`` with an MoE config (num_experts > 0), re-exported here so
the family has a stable import path. Expert weights carry the "expert"
logical axis → the ``expert`` mesh axis; the router all-to-all is XLA's
lowering of the dispatch/combine einsums in ``ops/moe.py``.
"""

from __future__ import annotations

from .config import ModelConfig, get_config
from .llama import (
    forward, forward_hidden, init_params, logical_axes, remat_block,
    resolve_attention)

__all__ = ["forward", "forward_hidden", "init_params", "logical_axes",
           "remat_block", "resolve_attention", "config_8x7b", "ModelConfig"]


def config_8x7b(**overrides) -> ModelConfig:
    return get_config("mixtral-8x7b", **overrides)
