"""KV-cache autoregressive generation for the bundled model families.

TPU-first decode loop: everything is static-shaped — the KV cache is
allocated at ``max_len`` up front ([L, B, max_len, Hkv, Dh]) and written
with ``dynamic_update_slice``; the decode loop is one ``lax.scan`` whose
carry is (cache, last token, position, rng), so the whole
prefill-then-N-steps program jits once and never retraces as text grows.
Unwritten cache slots need no explicit mask: attention scores use explicit
key positions (``arange(max_len)``), and the causal test ``q_pos >= k_pos``
already excludes every slot past the current position.

The per-layer math reuses ``llama._qkv`` / ``llama._mlp`` (same weight
pytree, same block order), so greedy decode reproduces the training
forward's argmax exactly — see tests/test_generate.py. For MoE families
use a dropless config at inference (``capacity_factor >= num_experts /
num_selected``): capacity-based token dropping depends on how many tokens
route together, which differs between single-token decode and full-sequence
prefill and would make cached decode diverge from the training forward.

No reference analog: the reference is an infrastructure CLI (SURVEY.md
§2.5); serving is part of the workload stack the TPU build adds.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import causal_attention
from ..ops.rotary import rotary_tables
from .config import ModelConfig
from . import llama


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, max_len, Hkv, Dh] activation dtype
    v: jnp.ndarray  # [L, B, max_len, Hkv, Dh]
    length: jnp.ndarray  # [] int32 — tokens written so far


def init_cache(config: ModelConfig, batch: int, max_len: int) -> KVCache:
    shape = (config.num_layers, batch, max_len,
             config.num_kv_heads, config.head_dim)
    z = jnp.zeros(shape, config.activation_dtype)
    return KVCache(k=z, v=z, length=jnp.zeros((), jnp.int32))


def prefill(
    params,
    tokens: jnp.ndarray,  # [B, P] int32 prompt
    config: ModelConfig,
    cache: KVCache,
    last_logits_only: bool = False,
    last_position: Optional[jnp.ndarray] = None,  # [B] int32
) -> Tuple[jnp.ndarray, KVCache]:
    """Run the prompt through the stack, filling cache[:, :, :P].

    Returns (logits f32, cache) — [B, P, V], or [B, 1, V] when
    ``last_logits_only`` (generation only samples the last position, and
    the full-prompt unembed is B*P*V f32, easily the largest buffer of a
    long-prompt prefill). ``last_position`` is the ragged generalization:
    unembed only each sequence's own position (right-padded batches,
    where the interesting logits sit at ``length - 1``, not ``P - 1``).
    Prompt attention is plain causal over the prompt itself (nothing
    cached yet).
    """
    b, p = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32), (b, p))
    cos, sin = rotary_tables(
        config.head_dim, config.max_seq_len, config.rope_theta)
    x = params["embed"].astype(config.activation_dtype)[tokens]

    def body(carry, layer_and_cache):
        x = carry
        layer, ck, cv = layer_and_cache
        q, k, v = llama._qkv(x, layer, config, cos, sin, positions)
        attn = causal_attention(q, k, v, positions, positions)
        x = llama.project_out(x, attn, layer, config)
        y, _ = llama._mlp(x, layer, config)
        ck = lax.dynamic_update_slice(ck, k, (0, 0, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, 0, 0, 0))
        return x + y, (ck, cv)

    x, (ck, cv) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    if last_position is not None:
        idx = jnp.reshape(last_position, (-1, 1, 1)).astype(jnp.int32)
        x = jnp.take_along_axis(x, idx, axis=1)  # [B, 1, D]
    elif last_logits_only:
        x = x[:, -1:, :]
    logits = llama.unembed(x, params, config)
    return logits, KVCache(k=ck, v=cv, length=jnp.asarray(p, jnp.int32))


def decode_step(
    params,
    token: jnp.ndarray,  # [B] int32 — the latest token
    config: ModelConfig,
    cache: KVCache,
) -> Tuple[jnp.ndarray, KVCache]:
    """One autoregressive step: returns (logits [B, V] f32, updated cache)."""
    b = token.shape[0]
    ad = config.activation_dtype
    max_len = cache.k.shape[2]
    pos = cache.length  # scalar: where this token goes
    positions = jnp.broadcast_to(pos, (b, 1)).astype(jnp.int32)
    k_positions = jnp.broadcast_to(
        jnp.arange(max_len, dtype=jnp.int32), (b, max_len))
    cos, sin = rotary_tables(
        config.head_dim, config.max_seq_len, config.rope_theta)
    x = params["embed"].astype(ad)[token[:, None]]  # [B, 1, D]

    def body(carry, layer_and_cache):
        x = carry
        layer, ck, cv = layer_and_cache
        q, k, v = llama._qkv(x, layer, config, cos, sin, positions)
        ck = lax.dynamic_update_slice(ck, k, (0, pos, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, pos, 0, 0))
        # Slots past pos have k_pos > q_pos and mask themselves out.
        attn = causal_attention(q, ck, cv, positions, k_positions)
        x = llama.project_out(x, attn, layer, config)
        y, _ = llama._mlp(x, layer, config)
        return x + y, (ck, cv)

    x, (ck, cv) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    logits = llama.unembed(x, params, config)[:, 0, :]
    return logits, KVCache(k=ck, v=cv, length=pos + 1)


def sample_token(
    logits: jnp.ndarray,  # [B, V] f32
    key: jax.Array,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Greedy when temperature == 0; else temperature with optional
    top-k and/or top-p (nucleus) filtering — filters compose: top-k cuts
    first, then top-p trims the survivors' probability mass."""
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        # Nucleus: keep the smallest prefix of the probability-sorted
        # vocab whose mass reaches top_p. The test `cum - p < top_p`
        # (mass *before* each token) always keeps the top token, so the
        # support is never empty.
        order = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p
        masked = jnp.where(keep, sorted_logits, -jnp.inf)
        inverse = jnp.argsort(order, axis=-1)
        logits = jnp.take_along_axis(masked, inverse, axis=-1)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(
    params,
    prompt: jnp.ndarray,  # [B, P] int32
    config: ModelConfig,
    max_new_tokens: int,
    key: Optional[jax.Array] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: Optional[int] = None,
) -> Dict[str, jnp.ndarray]:
    """Prefill + N decode steps; returns {"tokens": [B, N], "done": [B]}.

    Static-shaped: always runs ``max_new_tokens`` steps; once a sequence
    emits ``eos_id`` its subsequent slots repeat eos (the done mask sticks).
    """
    b, p = prompt.shape
    if key is None:
        key = jax.random.PRNGKey(0)
    max_len = p + max_new_tokens
    if max_len > config.max_seq_len:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({config.max_seq_len})")
    if config.is_moe and (config.capacity_factor
                          < config.num_experts / config.num_selected):
        raise ValueError(
            "MoE generation needs dropless routing (capacity-based token "
            f"dropping is sequence-length-dependent, so cached decode would "
            f"diverge from the training forward): set capacity_factor >= "
            f"num_experts/num_selected = "
            f"{config.num_experts / config.num_selected}, got "
            f"{config.capacity_factor}")

    def sample(logits, done, key):
        tok = sample_token(logits, key, temperature, top_k, top_p)
        if eos_id is not None:
            tok = jnp.where(done, eos_id, tok)
            done = done | (tok == eos_id)
        return tok, done

    cache = init_cache(config, b, max_len)
    logits, cache = prefill(params, prompt, config, cache,
                            last_logits_only=True)
    key, sub = jax.random.split(key)
    tok0, done0 = sample(logits[:, -1, :], jnp.zeros((b,), bool), sub)

    def step(carry, _):
        tok, cache, done, key = carry
        logits, cache = decode_step(params, tok, config, cache)
        key, sub = jax.random.split(key)
        nxt, done = sample(logits, done, sub)
        return (nxt, cache, done, key), nxt

    # N-1 decode steps: the first token comes from prefill's logits, and no
    # decode runs whose logits would never be sampled.
    (_, _, done, _), rest = lax.scan(
        step, (tok0, cache, done0, key), None, length=max_new_tokens - 1)
    tokens = jnp.concatenate([tok0[:, None], jnp.transpose(rest)], axis=1) \
        if max_new_tokens > 1 else tok0[:, None]
    return {"tokens": tokens, "done": done}
