"""Named device meshes over TPU slices.

Axis vocabulary (a superset of MaxText's, minus host-offload axes):

- ``data``   — pure data parallelism (params replicated). Rides DCN across
  slices; lowest-bandwidth axis, so it is the *outermost* mesh dim.
- ``stage``  — pipeline-parallel stage axis (DCN- or ICI-mapped).
- ``fsdp``   — fully-sharded data parallelism: batch AND params sharded.
- ``seq``    — sequence/context parallelism (ring attention).
- ``expert`` — expert parallelism for MoE layers.
- ``tensor`` — tensor (Megatron-style) parallelism; highest-bandwidth axis,
  innermost so it maps onto the tightest ICI ring.

Unused axes just have size 1 — shardings that name them become no-ops, so a
single model definition serves every parallelism configuration.

The reference tool has no analog of any of this (SURVEY.md §2.5); the mesh is
the TPU-native replacement for what a GPU stack would assemble out of
NCCL process groups.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_STAGE = "stage"
AXIS_FSDP = "fsdp"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_TENSOR = "tensor"

# Outermost (lowest bandwidth, DCN-friendly) → innermost (tightest ICI ring).
MESH_AXES: Tuple[str, ...] = (
    AXIS_DATA, AXIS_STAGE, AXIS_FSDP, AXIS_SEQ, AXIS_EXPERT, AXIS_TENSOR)


@dataclass(frozen=True)
class MeshConfig:
    """Requested parallelism degrees. ``-1`` on at most one axis means
    "absorb all remaining devices" (mirrors MaxText's convention)."""

    data: int = 1
    stage: int = 1
    fsdp: int = -1
    seq: int = 1
    expert: int = 1
    tensor: int = 1

    def sizes(self) -> Dict[str, int]:
        return {
            AXIS_DATA: self.data,
            AXIS_STAGE: self.stage,
            AXIS_FSDP: self.fsdp,
            AXIS_SEQ: self.seq,
            AXIS_EXPERT: self.expert,
            AXIS_TENSOR: self.tensor,
        }

    def resolve(self, n_devices: int) -> Dict[str, int]:
        """Fill in the -1 axis and validate the product against n_devices."""
        sizes = self.sizes()
        wildcard = [a for a, s in sizes.items() if s == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wildcard}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcard:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"fixed axes product {fixed} does not divide {n_devices} devices")
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices but {n_devices} are available")
        for axis, s in sizes.items():
            if s < 1:
                raise ValueError(f"axis {axis!r} resolved to {s}")
        return sizes


def create_mesh(
    config: MeshConfig | None = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a 6-axis named Mesh over ``devices`` (default: all).

    Uses ``mesh_utils.create_device_mesh`` when possible so the axis order
    maps onto the physical ICI torus (innermost axis = nearest neighbors);
    falls back to a plain reshape for virtual/CPU device sets.
    """
    config = config or MeshConfig()
    devs = list(devices) if devices is not None else list(jax.devices())
    sizes = config.resolve(len(devs))
    shape = tuple(sizes[a] for a in MESH_AXES)
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(shape, devices=np.asarray(devs))
    except Exception:
        arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, MESH_AXES)


def batch_shard_axes() -> Tuple[str, ...]:
    """Mesh axes over which the global batch dimension is split."""
    return (AXIS_DATA, AXIS_FSDP)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1) if hasattr(mesh.shape, "get") else dict(
        zip(mesh.axis_names, mesh.devices.shape))[axis]


@dataclass(frozen=True)
class ParallelismPlan:
    """A resolved plan: mesh config + the knobs the trainer needs to know
    about (whether ring attention is on, how many microbatches for PP)."""

    mesh_config: MeshConfig = field(default_factory=MeshConfig)
    ring_attention: bool = False  # shard sequence via ops.ring_attention
    microbatches: int = 1  # pipeline microbatches (>=stage count when stage>1)

    def validate(self, n_devices: int) -> Dict[str, int]:
        sizes = self.mesh_config.resolve(n_devices)
        if sizes[AXIS_SEQ] > 1 and not self.ring_attention:
            raise ValueError(
                "seq axis >1 requires ring_attention=True (dense attention "
                "cannot shard the sequence dimension)")
        if sizes[AXIS_STAGE] > 1 and self.microbatches % sizes[AXIS_STAGE] != 0:
            raise ValueError(
                f"microbatches ({self.microbatches}) must be a multiple of "
                f"pipeline stages ({sizes[AXIS_STAGE]})")
        return sizes


def describe_mesh(mesh: Mesh) -> str:
    parts = [f"{a}={s}" for a, s in zip(mesh.axis_names, mesh.devices.shape)
             if s > 1]
    return "mesh(" + (", ".join(parts) or "single-device") + ")"
