"""Multi-host scale-out: DCN×ICI hybrid meshes, per-process data sharding,
a local multi-process launcher, and goodput measurement.

The single-process meshes (parallel/mesh.py) prove every parallelism mode
steps correctly; this module is what makes them *span processes*, the way
the pod slices the topology layer labels actually run ("Podracer
architectures", PAPERS.md): a **DCN data-parallel axis across
``jax.distributed`` processes** and the existing ICI axes (fsdp / tensor /
seq / expert / stage) **within** each process. Placement rule, enforced by
:func:`create_hybrid_mesh`: the DCN-friendly axes (``data``, ``stage``) are
the outermost mesh dims and must land on process boundaries; the ICI axes
must fit inside one process's devices — an ICI axis silently spanning DCN
would turn every FSDP all-gather into a cross-host transfer.

Everything here degrades LOUDLY, never silently: environments that cannot
host cross-process collectives (a jax/jaxlib without gloo CPU collectives,
or no ``jax.distributed`` at all) raise :class:`MultiHostUnavailable` with
a bounded machine-readable ``reason`` — callers (tests, CI evidence, the
trainer CLI) skip with that reason instead of aborting, per the same
contract as the old-jax shard_map gaps in utils/jaxcompat.py.

Local harness: :func:`launch_trainers` spins up N worker processes of the
real trainer (``python -m triton_kubernetes_tpu.train``) on this machine —
each with its own ``--xla_force_host_platform_device_count`` virtual CPU
devices, a shared coordinator on a deterministic port, and (optionally) a
distinct pinned CPU core so the A/B measures DCN scale-out rather than
intra-op thread-pool reallocation. :func:`run_goodput` composes that with
PR 4's emergency-checkpoint + verified-restore machinery: a mid-run
slice-wide SIGTERM (the GKE preemption warning delivered to every pod of a
reclaimed slice), a relaunch with ``--resume``, and a report of
useful-steps/s *including* the recovery window — goodput, the honest
metric, not steps/s of the lucky uninterrupted run.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .mesh import (
    AXIS_DATA, AXIS_FSDP, AXIS_STAGE, MESH_AXES, MeshConfig, create_mesh)

# The trainer's "environment cannot host this run" exit code
# (EX_UNAVAILABLE): distinct from config errors (2), anomaly aborts (4)
# and resume-me (75) so launchers and CI classify a skipped harness as a
# skip, never as a failure or a retry. Single-sourced from constants.py
# (lint rule TK8S104).
from ..constants import EXIT_UNSUPPORTED

# Reason slugs for MultiHostUnavailable — bounded, machine-readable, the
# same contract as CheckpointIntegrityError.reason.
REASON_NO_DISTRIBUTED = "no-jax-distributed"
REASON_NO_CPU_COLLECTIVES = "no-cpu-collectives"
REASON_NO_COLLECTIVES_FLAG = "no-cpu-collectives-flag"
REASON_NO_PROCESS_ARRAY = "no-process-local-array-api"
REASON_HOST_CEILING = "host-parallel-ceiling"


class MultiHostUnavailable(RuntimeError):
    """This environment cannot run the multi-process harness. Carries a
    bounded ``reason`` slug so skips are typed and greppable — the
    harness must skip LOUDLY, never abort the process."""

    def __init__(self, message: str, reason: str):
        super().__init__(message)
        self.reason = reason


class MeshPlacementError(ValueError):
    """A hybrid mesh request that would misplace axes across the
    DCN/ICI boundary (or feed a batch that does not divide across
    processes)."""


# --------------------------------------------------------------- capability

def support_report() -> Dict[str, Any]:
    """What this jax/jaxlib can do, WITHOUT touching jax config or
    initializing a backend (safe from a parent/test process). Keys:
    ``ok`` plus a ``reason`` slug when not ok."""
    if not hasattr(jax, "distributed"):
        return {"ok": False, "reason": REASON_NO_DISTRIBUTED,
                "detail": f"jax {jax.__version__} has no jax.distributed"}
    try:
        from jax._src.lib import xla_client
        has_gloo = hasattr(xla_client._xla, "make_gloo_tcp_collectives")
    except Exception:
        has_gloo = False
    if not has_gloo:
        return {"ok": False, "reason": REASON_NO_CPU_COLLECTIVES,
                "detail": "jaxlib has no gloo CPU collectives; "
                          "cross-process CPU programs cannot run"}
    return {"ok": True, "reason": "",
            "detail": f"jax {jax.__version__} with gloo CPU collectives"}


def require_multihost() -> None:
    """Raise :class:`MultiHostUnavailable` (typed reason) unless this
    environment can run cross-process CPU collectives."""
    rep = support_report()
    if not rep["ok"]:
        raise MultiHostUnavailable(rep["detail"], rep["reason"])


def enable_cpu_collectives() -> None:
    """Select the gloo CPU collectives implementation. MUST run before
    ``jax.distributed.initialize`` / backend init: on jax 0.4.x the flag
    is config-only (the ``JAX_CPU_COLLECTIVES_IMPLEMENTATION`` env var is
    NOT read), and without it every cross-process program dies with
    "Multiprocess computations aren't implemented on the CPU backend".
    Never call this without a distributed init to follow — a gloo
    selection with no distributed client crashes backend creation."""
    require_multihost()
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:
        raise MultiHostUnavailable(
            f"this jax has gloo collectives but no "
            f"jax_cpu_collectives_implementation config ({e})",
            REASON_NO_COLLECTIVES_FLAG) from e


# ------------------------------------------------------------- hybrid mesh

def process_major_devices(
        devices: Optional[Sequence[jax.Device]] = None) -> List[jax.Device]:
    """All devices ordered process-major (then by id): the order under
    which the outermost mesh dims land on process boundaries. Raises
    :class:`MeshPlacementError` on uneven per-process device counts."""
    devs = list(devices) if devices is not None else list(jax.devices())
    devs.sort(key=lambda d: (d.process_index, d.id))
    counts: Dict[int, int] = {}
    for d in devs:
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    if len(set(counts.values())) > 1:
        raise MeshPlacementError(
            f"uneven devices per process: {counts} — hybrid meshes need "
            f"every process to contribute the same ICI block")
    return devs


def create_hybrid_mesh(
    config: MeshConfig | None = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> "jax.sharding.Mesh":
    """A DCN×ICI mesh: ``data`` (and ``stage``) may span processes over
    DCN; ``fsdp``/``seq``/``expert``/``tensor`` must fit within one
    process's devices. Single-process calls degrade to
    :func:`..mesh.create_mesh` exactly (same axis order, same device
    layout), so callers can use this unconditionally."""
    from jax.sharding import Mesh

    devs = process_major_devices(devices)
    n_proc = len({d.process_index for d in devs})
    config = config or MeshConfig()
    sizes = config.resolve(len(devs))
    if n_proc > 1:
        dcn = sizes[AXIS_DATA] * sizes[AXIS_STAGE]
        ici = 1
        for axis in MESH_AXES:
            if axis not in (AXIS_DATA, AXIS_STAGE):
                ici *= sizes[axis]
        local = len(devs) // n_proc
        if dcn % n_proc:
            raise MeshPlacementError(
                f"DCN axes data×stage = {dcn} must be a multiple of the "
                f"process count ({n_proc}): data is the outermost "
                f"(lowest-bandwidth) axis and must land on process "
                f"boundaries (got mesh {sizes})")
        if local % ici:
            raise MeshPlacementError(
                f"ICI axes fsdp×seq×expert×tensor = {ici} must fit within "
                f"one process's {local} devices (got mesh {sizes}); an "
                f"ICI axis spanning processes would ride DCN and turn "
                f"every FSDP/TP collective into a cross-host transfer")
        shape = tuple(sizes[a] for a in MESH_AXES)
        arr = np.asarray(devs).reshape(shape)
        return Mesh(arr, MESH_AXES)
    return create_mesh(config, devices=devs)


def default_mesh_config(
    base: MeshConfig, n_processes: Optional[int] = None) -> MeshConfig:
    """The hybrid default: the ``data`` axis spans the processes (DCN
    data-parallel), everything else stays as requested. A ``data`` of 0
    means "auto" (process count); explicit values are validated against
    the process boundary by :func:`create_hybrid_mesh` later."""
    n = n_processes if n_processes is not None else jax.process_count()
    data = base.data or max(n, 1)
    return MeshConfig(data=data, stage=base.stage, fsdp=base.fsdp,
                      seq=base.seq, expert=base.expert, tensor=base.tensor)


# ----------------------------------------------- fused DCN gradient sync

def supports_fused_dcn(mesh: "jax.sharding.Mesh") -> bool:
    """True when ``mesh`` is pure DCN data-parallelism (every non-``data``
    axis is 1) — the layout :func:`make_fused_dcn_step` handles."""
    return all(mesh.shape[a] == 1 for a in MESH_AXES if a != AXIS_DATA)


def make_fused_dcn_step(config: Any, mesh: "jax.sharding.Mesh",
                        optimizer: Any, precision: Any = None):
    """A DDP train step that crosses DCN exactly ONCE per step.

    The XLA-partitioned step (train/trainer.make_train_step) lets GSPMD
    insert the data-parallel gradient psums, which it does per-parameter:
    ~2 all-reduces per layer sprinkled through the backward. Over ICI that
    scheduling is free; over DCN every one of those reduces pays the
    cross-host round-trip latency plus inter-worker skew, and the step
    serializes on the slowest of ~dozens of small collectives ("Podracer
    architectures": keep DCN traffic to one bucketed gradient exchange).

    This builds the step as a full-manual ``shard_map`` over the whole
    (pure data-parallel) mesh instead: each shard computes its local
    gradients on its own batch rows, the gradient tree is raveled into
    ONE flat vector (the loss/aux metrics ride along in the same
    buffer), a single ``psum`` crosses the ``data`` axis, and the
    optimizer applies the averaged gradients locally — replicated state
    stays bit-identical across shards because every shard applies the
    identical update. The emitted HLO carries exactly one all-reduce.

    Same contract as ``make_train_step``: jitted ``(state, batch) ->
    (state, metrics)``, state donated, metrics carrying loss / aux_loss /
    grad_norm. The mean-of-per-shard-means loss equals the global-batch
    mean ONLY with equal shard sizes — this function does not check
    that; the trainer pins ``batch_size % (data*fsdp) == 0`` before
    building the step, and custom feeds can validate theirs with
    :func:`process_batch_bounds`. Per-step losses then match the
    single-process trajectory to float reassociation. Raises :class:`MeshPlacementError` on meshes with
    sharded non-data axes — callers fall back to the XLA path (sharded
    params have no single-bucket exchange; that regime wants ICI).
    """
    import jax.numpy as jnp
    import optax
    from jax.flatten_util import ravel_pytree
    from jax.sharding import PartitionSpec as P

    from ..train.precision import apply_policy
    from ..train.trainer import TrainState, batch_spec, loss_fn
    from ..utils.jaxcompat import shard_map

    if not supports_fused_dcn(mesh):
        raise MeshPlacementError(
            f"fused DCN sync needs a pure data-parallel mesh (every "
            f"non-data axis 1), got {dict(mesh.shape)}; sharded "
            f"params/activations must use the XLA-partitioned step")
    config = apply_policy(config, precision)
    n_data = mesh.shape[AXIS_DATA]

    def body(state: "TrainState", batch: Dict[str, Any]):
        tokens = batch["tokens"]  # this shard's rows only
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (_, metrics), grads = grad_fn(
            state.params, tokens, config, None, 1, 1, None)
        flat, unravel = ravel_pytree(grads)
        packed = jnp.concatenate(
            [flat, jnp.stack([metrics["loss"], metrics["aux_loss"]])])
        # The one DCN crossing: gradients + metrics in a single buffer.
        packed = jax.lax.psum(packed, AXIS_DATA) / n_data
        grads = unravel(packed[:-2])
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": packed[-2], "aux_loss": packed[-1],
                   "grad_norm": optax.global_norm(grads)}
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt), metrics

    step = shard_map(
        body, mesh=mesh,
        in_specs=(P(), {"tokens": batch_spec()}),
        out_specs=(P(), P()), check_vma=False)
    # tk8s: donate-safe(restore re-places leaves with an explicit device
    # copy before the loop — the PR 8 zero-copy device_put corruption fix
    # — so the donated TrainState never aliases host numpy; callers
    # always rebind the returned state)
    return jax.jit(step, donate_argnums=(0,))


# ------------------------------------------------- per-process data sharding

def process_batch_bounds(
    global_batch: int,
    process_index: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> Tuple[int, int]:
    """[lo, hi) rows of the global batch this process owns. The batch dim
    shards over ``(data, fsdp)`` data-major (trainer.batch_spec) and the
    data axis is process-major, so each process owns one contiguous row
    block."""
    p = process_index if process_index is not None else jax.process_index()
    n = num_processes if num_processes is not None else jax.process_count()
    if n < 1 or not 0 <= p < n:
        raise MeshPlacementError(
            f"process_index {p} out of range for {n} processes")
    if global_batch % n:
        raise MeshPlacementError(
            f"global batch {global_batch} must divide across {n} "
            f"processes (each host feeds only its own shard)")
    rows = global_batch // n
    return p * rows, (p + 1) * rows


def make_batch_placer(mesh: "jax.sharding.Mesh",
                      spec: Any = None) -> Callable[[Any], Any]:
    """A ``place`` function for :class:`..train.data.DevicePrefetch`:
    takes one *global* host batch (pytree of arrays, batch-major), slices
    out this process's rows, and forms the global ``jax.Array`` from
    process-local data — the host never transfers rows it does not own.
    Single-process meshes slice nothing and behave like a sharded
    ``device_put``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils.jaxcompat import make_process_array

    if spec is None:
        spec = P((AXIS_DATA, AXIS_FSDP), None)
    sharding = NamedSharding(mesh, spec)

    def place(batch: Any) -> Any:
        def leaf(x):
            x = np.asarray(x)
            # Ownership comes from the SHARDING, not process arithmetic:
            # when the batch axes (data, fsdp) live inside each process
            # — e.g. the stage axis is what spans DCN — every host owns
            # every row and local_block returns the full extent, where
            # a rows/n_processes split would hand make_process_array
            # half the rows it expects and crash the first batch.
            return make_process_array(
                sharding, local_block(x, sharding), x.shape)

        return jax.tree.map(leaf, batch)

    return place


def local_batch_rows(mesh: "jax.sharding.Mesh", spec: Any,
                     global_rows: int) -> int:
    """How many batch rows THIS process computes under ``spec`` — the
    local share that per-row device-time modeling (``--device-ms-per-
    row``) must scale with. Derived from the sharding's addressable
    indices, so a stage-spanning DCN mesh (batch replicated per host)
    correctly reports the FULL batch, not ``global_rows/n_processes``."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    probe = np.empty((global_rows, 1), np.int8)
    return local_block(probe, sharding).shape[0]


def local_full_value(leaf: Any) -> np.ndarray:
    """Assemble a leaf's FULL global value from this process's shards.
    Requires the leaf to be process-locally complete — every byte of the
    global array present on local devices, which is exactly the DCN
    data-parallel placement (params/optimizer replicated over ``data``,
    sharded only over intra-process ICI axes). Raises
    :class:`MeshPlacementError` when shards are missing — a leaf sharded
    ACROSS processes has no single-writer checkpoint story here."""
    if not hasattr(leaf, "addressable_shards"):
        return np.asarray(leaf)
    try:
        if leaf.is_fully_addressable:
            return np.asarray(leaf)
    except Exception:
        pass
    # Replicated axes surface the same block once per local replica:
    # deduplicate by index so every block is copied exactly once, and
    # check coverage by element count over the disjoint blocks — no
    # full-shape bool mask (+1 byte/element of transient host memory on
    # every save of the multi-GB leaves this path exists for).
    unique = {}
    for shard in leaf.addressable_shards:
        key = tuple((s.start or 0, s.stop) for s in shard.index)
        unique.setdefault(key, shard)
    blocks = list(unique.values())
    if len(blocks) == 1 and np.prod(
            np.asarray(blocks[0].data).shape, dtype=np.int64) == np.prod(
            leaf.shape, dtype=np.int64):
        # Fully-replicated leaf (the common DCN case): one block IS the
        # global value — skip the output buffer + copy entirely.
        return np.asarray(blocks[0].data)
    out = np.empty(leaf.shape, leaf.dtype)
    covered = 0
    for shard in blocks:
        block = np.asarray(shard.data)
        out[shard.index] = block
        covered += block.size
    if covered != out.size:
        raise MeshPlacementError(
            f"leaf of shape {leaf.shape} is not process-locally complete "
            f"(sharded across processes): single-writer checkpointing "
            f"requires the DCN axis to carry only replicated state")
    return out


def local_block(leaf: np.ndarray, sharding: Any) -> np.ndarray:
    """This process's block of a full-global host array under
    ``sharding`` — the inverse of :func:`local_full_value`, fed to
    ``make_process_array`` on restore. Computed from the sharding's
    addressable device indices (per-dim min start / max stop)."""
    leaf = np.asarray(leaf)
    index_map = sharding.devices_indices_map(tuple(leaf.shape))
    local = [idx for dev, idx in index_map.items()
             if dev.process_index == jax.process_index()]
    if not local:
        raise MeshPlacementError("sharding has no addressable devices here")
    slices = []
    for dim in range(leaf.ndim):
        starts = [idx[dim].start or 0 for idx in local]
        stops = [idx[dim].stop if idx[dim].stop is not None
                 else leaf.shape[dim] for idx in local]
        slices.append(slice(min(starts), max(stops)))
    return leaf[tuple(slices)]


def barrier(name: str) -> None:
    """Cross-process sync point (no-op single-process)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


_PROCESS_MAX_CACHE: Optional[Tuple[Any, int, int, Any]] = None


def _process_max(value: int) -> int:
    """Max over every process's contributed int — ONE tiny collective on
    a flat process-major mesh (each process's local devices carry its
    value). The shared primitive under :func:`agree_from_rank0` and
    :class:`SyncedPreemptionGuard`; every process must call it at the
    same program point."""
    global _PROCESS_MAX_CACHE
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..utils.jaxcompat import make_process_array

    if _PROCESS_MAX_CACHE is None:
        devs = process_major_devices()
        mesh = Mesh(np.asarray(devs), ("all",))
        sharding = NamedSharding(mesh, P("all"))
        n_local = len([d for d in devs
                       if d.process_index == jax.process_index()])
        _PROCESS_MAX_CACHE = (sharding, n_local, len(devs),
                              jax.jit(lambda x: x.max()))
    sharding, n_local, n_total, reduce_max = _PROCESS_MAX_CACHE
    local = np.full((n_local,), value, np.int64)
    return int(reduce_max(make_process_array(sharding, local, (n_total,))))


def agree_from_rank0(value: Optional[int]) -> Optional[int]:
    """Every process's copy of rank 0's verdict (a step number or None).

    The decision-consistency primitive for control flow that gates a
    collective: "is step N already committed?" answered per-rank from
    the shared filesystem can RACE the writer (rank 0 commits between
    its own scan and a slow peer's, the peer skips the save it would
    otherwise join, rank 0 waits in the commit barrier forever). One
    tiny max-collective makes every rank branch on the same answer.
    Collective: every process must call at the same program point.
    Non-rank-0 arguments are ignored; ``value`` must be >= 0.
    """
    if jax.process_count() == 1:
        return value
    mine = 0
    if jax.process_index() == 0:
        if value is not None and value < 0:
            raise ValueError(f"agree_from_rank0 needs value >= 0, "
                             f"got {value}")
        mine = 1 if value is None else int(value) + 2
    agreed = _process_max(mine)
    return None if agreed <= 1 else agreed - 2


# ----------------------------------------------- synced preemption agreement

class SyncedPreemptionGuard:
    """A :class:`..train.resilience.PreemptionGuard` whose ``requested``
    is a cross-process *agreement*, not a local flag read.

    Why: signal delivery skews across workers. If worker A stops
    dispatching at step k while worker B dispatches step k+1, B's step
    blocks forever in a collective A never joins — the kill deadlocks
    instead of checkpointing. Here every ``requested`` read runs one tiny
    all-reduce (max over per-process flags), so all workers agree on the
    same answer at the same loop position and stop on the same step.

    The agreement itself is a collective, so every process must call
    ``requested`` at identical loop positions — true in the pipelined /
    resilient loop (one poll per dispatch + one per segment, and control
    flow is deterministic). ``check_every`` thins the collectives: only
    every Nth read pays one (others return the last agreed value), which
    keeps the per-dispatch poll from serializing the async step pipeline;
    the invocation COUNT still aligns across processes, so collectives
    pair up 1:1. Single-process instances never build a collective.
    """

    def __init__(self, signals: Optional[Tuple[int, ...]] = None,
                 check_every: int = 1):
        from ..train.resilience import PreemptionGuard

        if check_every < 1:
            raise ValueError(f"check_every must be >= 1, got {check_every}")
        self._base = PreemptionGuard(signals) if signals is not None \
            else PreemptionGuard()
        self.check_every = check_every
        self._calls = 0
        self._agreed = False

    # PreemptionGuard surface -------------------------------------------
    def install(self) -> "SyncedPreemptionGuard":
        self._base.install()
        return self

    def uninstall(self) -> None:
        self._base.uninstall()

    def trip(self) -> None:
        self._base.trip()

    @property
    def signum(self):
        return self._base.signum

    @property
    def requested(self) -> bool:
        if self._agreed:
            return True
        if jax.process_count() == 1:
            return self._base.requested
        self._calls += 1
        if self._calls % self.check_every:
            return False
        self._agreed = self._agree(self._base.requested)
        return self._agreed

    # agreement ---------------------------------------------------------
    def _agree(self, flag: bool) -> bool:
        # "Any process requested" == max over per-process flags; shares
        # _process_max (one mesh/jit cache) with agree_from_rank0.
        return _process_max(int(flag)) > 0

    def __enter__(self) -> "SyncedPreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


# ------------------------------------------- coordinated checkpoint wrapper

class CoordinatedCheckpoint:
    """Single-writer-per-shard checkpoint coordination for DCN
    data-parallel meshes, wrapping a
    :class:`..train.checkpoint.CheckpointManager`.

    Placement makes this simple: the DCN axis carries only replicated
    state (params / optimizer sharded over intra-process ICI axes), so
    **process 0 holds every byte** and is the single writer — it saves
    the host-assembled tree through the unmodified manager (manifest
    commit included) while every process barriers on the commit, so no
    rank can race ahead of (or outlive) a half-written step. Restores
    read the same files on every process (shared filesystem — the
    JobSet's shared checkpoint volume) into a host tree, then re-place
    each leaf onto the global mesh from process-local data. Quarantine
    renames are process-0-only; verification verdicts are deterministic
    (same bytes → same verdict), so ranks agree without messaging, and a
    step that vanishes mid-verify because the writer quarantined it
    first reports as a clean integrity failure, not a raw OSError.

    Coordinated saves are always synchronous (the barrier IS the commit
    point); the async-save window the single-process manager allows is
    deliberately given up here.
    """

    def __init__(self, mgr: Any):
        self._mgr = mgr
        self._rank0 = jax.process_index() == 0

    # -- read-only passthroughs (shared filesystem, any rank) ------------
    @property
    def directory(self) -> str:
        return self._mgr.directory

    @property
    def last_restored_step(self):
        return self._mgr.last_restored_step

    def _fresh(self):
        """Non-writer ranks' orbax index only tracks their own saves
        (none): re-scan the shared directory so every rank answers step
        queries identically — a rank answering from a stale index would
        diverge from its peers' control flow and deadlock a barrier."""
        if not self._rank0:
            self._mgr.reload()
        return self._mgr

    def latest_step(self):
        """Rank 0's answer on every rank (one tiny collective — call in
        lockstep). A per-rank shared-FS scan would race the writer: a
        peer observing rank 0's just-committed step skips the save its
        siblings join and strands them in the commit barrier."""
        return agree_from_rank0(
            self._mgr.latest_step() if self._rank0 else None)

    def all_steps(self):
        return self._fresh().all_steps()

    def latest_verified_step(self):
        """Rank 0's verdict on every rank — see :meth:`latest_step`
        (verification is rank 0's read + hash; verdicts are
        deterministic, so skipping the peer re-hash is also cheaper)."""
        return agree_from_rank0(
            self._mgr.latest_verified_step() if self._rank0 else None)

    def verify_step(self, step: int) -> None:
        # Deliberately per-rank (every rank reads + hashes), NOT routed
        # through agree_from_rank0: resume's candidate loop
        # (checkpoint.restore_newest_verified) is not lockstep — rank 0
        # can quarantine a candidate before a slow peer's initial scan,
        # so peers legitimately verify different candidate lists, and a
        # collective here would deadlock exactly the way the
        # agreement primitive exists to prevent. The N-rank re-hash at
        # resume is the price of that safety.
        from ..train.checkpoint import CheckpointIntegrityError

        try:
            self._mgr.verify_step(step)
        except CheckpointIntegrityError:
            raise
        except OSError as e:
            # The writer rank quarantined (renamed) this step while we
            # were mid-hash: same verdict it reached, typed.
            raise CheckpointIntegrityError(
                f"step {step} vanished mid-verify "
                f"(quarantined by the writer rank): {e}",
                reason="missing-step") from e

    def quarantine(self, step: int, reason: str = "corrupt") -> str:
        if self._rank0:
            return self._mgr.quarantine(step, reason)
        return f"(quarantined by rank 0: step {step}, {reason})"

    # -- coordinated write path ------------------------------------------
    def save(self, step: int, state: Any, wait: bool = True,
             kind: str = "scheduled") -> None:
        del wait  # coordinated saves are always synchronous
        if self._rank0:
            # The commit barrier is reached even when the write fails
            # (disk full, quota): peers unblock, THEN rank 0 re-raises
            # — a failed save must never strand its peers in the
            # barrier. (If rank 0 dies outright, the coordination
            # service's failure detector terminates the peers loudly —
            # the backstop either way.)
            try:
                host = jax.tree.map(local_full_value, state)
                self._mgr.save(step, host, wait=True, kind=kind)
            finally:
                self.barrier(f"ckpt-save-{kind}-{step}")
            return
        self.barrier(f"ckpt-save-{kind}-{step}")

    def restore(self, state_like: Any, step: Optional[int] = None,
                verify: bool = True) -> Any:
        # Proactive mesh-fit check on the PLACEMENT target: the abstract
        # tree handed to the inner manager deliberately drops shardings
        # (the host read is unsharded), which also used to skip the
        # manager's own divisibility check entirely — a wrong-shape
        # coordinated restore surfaced as a raw XLA partition error from
        # _place instead of the pinned MeshMismatchError. Check
        # state_like (which carries the live shardings) BEFORE the enter
        # barrier: every rank holds the same mesh, so every rank reaches
        # the same verdict and raises together — no stranded barrier.
        from ..train.checkpoint import CheckpointManager as _Mgr

        _Mgr._check_mesh_fits(state_like)
        # Concrete numpy templates, not ShapeDtypeStructs: a sharding-less
        # abstract leaf makes orbax fall back to the sharding recorded at
        # SAVE time, which references devices other ranks don't have when
        # the writer ran at a different world size (the elastic 4->8
        # regrow: a 1-process save restored by 2 processes). A numpy
        # template forces the host read this path is built around.
        abstract = jax.tree.map(
            lambda l: np.zeros(tuple(getattr(l, "shape", ())),
                               getattr(l, "dtype", None)),
            state_like)
        self.barrier(f"ckpt-restore-enter-{step}")
        if self._rank0:
            # Rank 0 decides (and quarantines) FIRST; the barrier orders
            # its renames before any other rank scans candidates — and
            # is reached (finally) even when every candidate fails
            # verification, so the typed CheckpointIntegrityError
            # propagates on rank 0 instead of deadlocking its peers;
            # they fail on their own restore of the now-empty directory
            # or are terminated by the coordination service when rank 0
            # exits.
            try:
                host = self._mgr.restore(abstract, step=step, verify=verify)
            finally:
                self.barrier(f"ckpt-restore-decided-{step}")
        else:
            self.barrier(f"ckpt-restore-decided-{step}")
            # Anything newer that failed verification is quarantined away
            # by now, so newest-≤-step here IS rank 0's choice; skip the
            # redundant re-hash. The reload sees rank 0's renames.
            self._mgr.reload()
            host = self._mgr.restore(abstract, step=step, verify=False)
        placed = jax.tree.map(
            lambda np_leaf, like: self._place(np_leaf, like),
            host, state_like)
        return placed

    @staticmethod
    def _place(np_leaf: np.ndarray, like: Any) -> Any:
        import jax.numpy as jnp

        from ..utils.jaxcompat import make_process_array

        sharding = getattr(like, "sharding", None)
        if sharding is None:
            return np_leaf
        np_leaf = np.asarray(np_leaf)
        placed = make_process_array(
            sharding, local_block(np_leaf, sharding), np_leaf.shape)
        # Device-side copy to sever host aliasing: CPU device_put may
        # zero-copy the numpy block, and the train step DONATES its
        # state — donating a host-aliased buffer lets XLA write into
        # numpy-owned (soon freed) memory, which surfaced as NaN losses
        # a few steps after every restore and then a segfault. The copy
        # op's outputs are fresh device allocations, safe to donate.
        return jnp.copy(placed)

    def barrier(self, name: str) -> None:
        barrier(f"tk8s-{name}")

    def close(self) -> None:
        self._mgr.close()


# ------------------------------------------------------------ local launcher

def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def pick_coordinator_port(tag: str = "") -> int:
    """Deterministic coordinator port for a local run: the JobSet
    coordinator port plus a stable offset derived from ``tag`` (distinct
    harness runs get distinct default ports), advanced past any port
    already in use so two concurrent harnesses never fight."""
    from ..topology.jobset import COORDINATOR_PORT

    base = COORDINATOR_PORT + 1 + (zlib.crc32(tag.encode()) % 2000)
    for port in range(base, base + 100):
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", port))
            except OSError:
                continue
            return port
    raise RuntimeError(f"no free coordinator port in [{base}, {base + 100})")


@dataclass
class WorkerExit:
    """One worker's outcome: the per-rank log file is the rank-tagged
    record (worker-N.log), its tail inlined for failure triage."""

    process_id: int
    returncode: int
    log_path: str
    tail: str = ""


@dataclass
class LaunchReport:
    returncodes: List[int] = field(default_factory=list)
    workers: List[WorkerExit] = field(default_factory=list)
    wall_seconds: float = 0.0
    killed: bool = False           # the preempt plan fired
    report: Optional[Dict[str, Any]] = None  # rank 0's --report-json

    @property
    def ok(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)


def worker_env(
    process_id: int,
    n_processes: int,
    port: int,
    devices_per_process: int = 1,
    extra: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """The environment one local worker runs under — the same variables
    the JobSet injects on GKE (topology/jobset.py), plus the virtual-CPU
    and thread-pinning knobs that make N processes on one machine behave
    like N hosts: each worker sees only its own
    ``--xla_force_host_platform_device_count`` devices, and intra-op
    threading is disabled so throughput differences measure process
    scale-out, not thread-pool reallocation."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # Append to (never clobber) inherited XLA_FLAGS, so an
        # operator's --xla_dump_to etc. survives into the workers.
        "XLA_FLAGS": (f"{env.get('XLA_FLAGS', '')} "
                      f"--xla_force_host_platform_device_count="
                      f"{devices_per_process} "
                      f"--xla_cpu_multi_thread_eigen=false").strip(),
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "TPU_WORKER_ID": str(process_id),
        "NUM_TPU_WORKERS": str(n_processes),
        "OMP_NUM_THREADS": "1",
        "OPENBLAS_NUM_THREADS": "1",
        "PYTHONPATH": _repo_root() + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.update(extra or {})
    return env


def _pin_to_core(core: int) -> Optional[Callable[[], None]]:
    if not hasattr(os, "sched_setaffinity"):
        return None

    def pin() -> None:
        try:
            os.sched_setaffinity(0, {core})
        except OSError:
            pass  # containers may deny affinity; run unpinned

    return pin


def _tail(path: str, n: int = 20) -> str:
    try:
        with open(path, errors="replace") as f:
            return "".join(f.readlines()[-n:])
    except OSError:
        return ""


def launch_trainers(
    trainer_args: Sequence[str],
    *,
    n_processes: int = 2,
    devices_per_process: int = 1,
    run_dir: str,
    tag: str = "",
    port: Optional[int] = None,
    env_extra: Optional[Dict[str, str]] = None,
    timeout: float = 600.0,
    pin_cores: bool = True,
    preempt_after_marker: Optional[str] = None,
    preempt_grace: float = 120.0,
    report_json: bool = True,
) -> LaunchReport:
    """Run the real trainer as ``n_processes`` local workers and wait.

    Every worker executes ``python -m triton_kubernetes_tpu.train
    <trainer_args> --distributed on`` under :func:`worker_env`; stdout+
    stderr land in ``run_dir/worker-N.log`` (the rank-tagged record).
    ``pin_cores`` pins worker i to core ``i % cpu_count`` so co-located
    workers emulate separate hosts.

    ``preempt_after_marker``: once the string appears in worker 0's log,
    SIGTERM is sent to EVERY worker — the slice-wide GKE preemption
    warning (a reclaimed slice signals all its pods; a single-rank
    signal would deadlock the others in a collective the stopped rank
    never joins). Workers are expected to emergency-checkpoint and exit
    75; stragglers are SIGKILLed after ``preempt_grace``.

    Raises :class:`MultiHostUnavailable` (typed) when the environment
    cannot host the run — callers skip loudly, they never crash.
    """
    require_multihost()
    os.makedirs(run_dir, exist_ok=True)
    port = port if port is not None else pick_coordinator_port(tag or run_dir)
    n_cores = os.cpu_count() or 1
    report_path = os.path.join(run_dir, "report.json")
    args = list(trainer_args) + ["--distributed", "on"]
    if report_json and "--report-json" not in args:
        args += ["--report-json", report_path]

    procs: List[subprocess.Popen] = []
    logs: List[str] = []
    t0 = time.perf_counter()
    try:
        for i in range(n_processes):
            log_path = os.path.join(run_dir, f"worker-{i}.log")
            logs.append(log_path)
            env = worker_env(i, n_processes, port, devices_per_process,
                             env_extra)
            preexec = _pin_to_core(i % n_cores) if pin_cores else None
            with open(log_path, "w") as log_f:
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "triton_kubernetes_tpu.train",
                     *args],
                    cwd=_repo_root(), env=env, stdout=log_f,
                    stderr=subprocess.STDOUT, preexec_fn=preexec))
        killed = False
        deadline = t0 + timeout
        # Marker scan state: a persistent offset into worker 0's log so
        # each poll reads only newly appended bytes, plus a marker-sized
        # carry for a marker torn across two reads — O(n) total I/O on
        # the same filesystem the workers checkpoint to, not O(n^2).
        deliver_kill = preempt_after_marker is not None
        log_offset = 0
        log_carry = ""
        while True:
            rcs = [p.poll() for p in procs]
            if all(rc is not None for rc in rcs):
                break
            if not killed and any(rc not in (None, 0) for rc in rcs):
                # A worker died while peers still run: those peers are
                # (or soon will be) blocked in a collective the dead
                # rank never joins. Reap them NOW instead of burning
                # the rest of the timeout — the dead worker's rc/tail
                # carries the real cause, survivors report SIGKILL.
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                break
            if any(rc == 0 for rc in rcs):
                deliver_kill = False  # run is ending cleanly: no kill
            if time.perf_counter() >= deadline:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                break
            if deliver_kill and not killed:
                try:
                    with open(logs[0], errors="replace") as f:
                        f.seek(log_offset)
                        chunk = f.read()
                        log_offset = f.tell()
                except OSError:
                    chunk = ""
                window = log_carry + chunk
                if preempt_after_marker in window:
                    for p in procs:
                        if p.poll() is None:
                            p.send_signal(signal.SIGTERM)
                    killed = True
                    deadline = time.perf_counter() + preempt_grace
                else:
                    keep = len(preempt_after_marker) - 1
                    log_carry = window[-keep:] if keep > 0 else ""
            time.sleep(0.05)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    wall = time.perf_counter() - t0
    workers = [WorkerExit(i, p.returncode, logs[i], _tail(logs[i]))
               for i, p in enumerate(procs)]
    report = None
    if os.path.exists(report_path):
        try:
            with open(report_path) as f:
                report = json.load(f)
        except ValueError:
            report = None
    return LaunchReport(
        returncodes=[p.returncode for p in procs], workers=workers,
        wall_seconds=wall, killed=killed, report=report)


@dataclass
class ElasticPhase:
    """One fleet shape in an elastic restart storyline: how many worker
    processes and virtual devices each gets, plus per-phase trainer-arg
    overrides (e.g. a larger ``--steps`` target) and an optional
    slice-wide preemption marker ending the phase early."""

    n_processes: int
    devices_per_process: int = 1
    extra_args: Sequence[str] = ()
    preempt_after_marker: Optional[str] = None
    preempt_grace: float = 120.0


def elastic_restart(
    trainer_args: Sequence[str],
    *,
    phases: Sequence[ElasticPhase],
    run_dir: str,
    tag: str = "",
    timeout: float = 600.0,
    env_extra: Optional[Dict[str, str]] = None,
    pin_cores: bool = True,
) -> List[LaunchReport]:
    """Run the trainer through a sequence of differently-sized fleets —
    the 8→4→8 storyline as one call.

    Phase 0 launches fresh; every later phase appends ``--resume
    --elastic`` so the restart negotiates its mesh from the newest
    manifest's recorded shape instead of its flags (the trainer args
    must therefore carry ``--checkpoint-dir``/``--emergency-dir``).
    Each phase gets its own ``run_dir/phase-N-PxD`` directory and
    coordinator port. Stops early when a phase neither finished nor
    exited for resume (rc 75) — a fleet with no durable state to hand
    forward would just burn the remaining phases' timeouts.
    """
    from ..train.resilience import EXIT_RESUME

    reports: List[LaunchReport] = []
    for idx, ph in enumerate(phases):
        args = list(trainer_args) + list(ph.extra_args)
        if idx and "--resume" not in args:
            args.append("--resume")
        if idx and "--elastic" not in args:
            args.append("--elastic")
        phase_dir = os.path.join(
            run_dir,
            f"phase-{idx}-{ph.n_processes}x{ph.devices_per_process}")
        rep = launch_trainers(
            args, n_processes=ph.n_processes,
            devices_per_process=ph.devices_per_process,
            run_dir=phase_dir, tag=f"{tag or run_dir}-p{idx}",
            env_extra=env_extra, timeout=timeout, pin_cores=pin_cores,
            preempt_after_marker=ph.preempt_after_marker,
            preempt_grace=ph.preempt_grace)
        reports.append(rep)
        if not all(rc in (0, EXIT_RESUME) for rc in rep.returncodes):
            break
    return reports


# ------------------------------------------------------------------ goodput

@dataclass
class GoodputReport:
    """Useful-steps/s including the recovery window — the honest
    scale-out metric ("Podracer architectures", PAPERS.md §goodput).
    ``useful_steps`` counts only steps that survived into the final
    state; steps trained past the last durable checkpoint and then
    replayed after the kill are ``wasted_steps`` and still cost wall
    clock, which is exactly what goodput charges for."""

    n_processes: int = 0
    target_steps: int = 0
    useful_steps: int = 0
    wasted_steps: int = 0
    wall_seconds: float = 0.0            # both phases + relaunch overhead
    goodput_steps_per_sec: float = 0.0   # useful_steps / wall_seconds
    raw_steps_per_sec: float = 0.0       # uninterrupted phase-2 rate
    emergency_step: Optional[int] = None
    resumed_step: Optional[int] = None
    phases: List[Dict[str, Any]] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {
            "n_processes": self.n_processes,
            "target_steps": self.target_steps,
            "useful_steps": self.useful_steps,
            "wasted_steps": self.wasted_steps,
            "wall_seconds": round(self.wall_seconds, 3),
            "goodput_steps_per_sec": round(self.goodput_steps_per_sec, 4),
            "raw_steps_per_sec": round(self.raw_steps_per_sec, 4),
            "emergency_step": self.emergency_step,
            "resumed_step": self.resumed_step,
            "phases": self.phases,
        }


def run_goodput(
    trainer_args: Sequence[str],
    *,
    n_processes: int = 2,
    devices_per_process: int = 1,
    run_dir: str,
    target_steps: int,
    kill_marker: str = "checkpoint saved",
    tag: str = "goodput",
    timeout: float = 600.0,
    env_extra: Optional[Dict[str, str]] = None,
) -> GoodputReport:
    """One kill → emergency-checkpoint → verified-restore → continue
    cycle across processes, timed end to end.

    Phase 1 launches the trainers and SIGTERMs every worker once
    ``kill_marker`` appears in rank 0's log (defaults to the first
    scheduled checkpoint commit, guaranteeing the kill lands mid-run
    with durable progress behind it). Workers emergency-checkpoint and
    exit 75 — the same protocol the JobSet podFailurePolicy restarts.
    Phase 2 relaunches with ``--resume``; the trainer restores the
    newest *verified* step (the emergency save) and finishes. The clock
    never stops: recovery time, replayed steps, and relaunch overhead
    all land in the denominator.

    ``trainer_args`` must NOT contain ``--resume``/``--steps``; pass
    ``target_steps`` instead. Raises :class:`MultiHostUnavailable`
    (typed) when the environment cannot host the run, and
    ``RuntimeError`` when a phase breaks protocol (wrong exit codes, no
    emergency checkpoint, lost steps).
    """
    from ..train.resilience import EXIT_RESUME

    base = list(trainer_args) + ["--steps", str(target_steps)]
    t0 = time.perf_counter()
    phase1 = launch_trainers(
        base, n_processes=n_processes,
        devices_per_process=devices_per_process,
        run_dir=os.path.join(run_dir, "phase1"), tag=f"{tag}-1",
        timeout=timeout, preempt_after_marker=kill_marker,
        env_extra=env_extra)
    if not phase1.killed:
        raise RuntimeError(
            f"phase 1 finished before the kill marker {kill_marker!r} "
            f"appeared — lower --checkpoint-every or raise --steps "
            f"(rcs={phase1.returncodes})")
    if any(rc != EXIT_RESUME for rc in phase1.returncodes):
        tails = "\n".join(w.tail for w in phase1.workers
                          if w.returncode != EXIT_RESUME)
        raise RuntimeError(
            f"preempted workers must exit {EXIT_RESUME}, got "
            f"{phase1.returncodes}:\n{tails}")
    p1 = phase1.report or {}
    phase2 = launch_trainers(
        base + ["--resume"], n_processes=n_processes,
        devices_per_process=devices_per_process,
        run_dir=os.path.join(run_dir, "phase2"), tag=f"{tag}-2",
        timeout=timeout, env_extra=env_extra)
    wall = time.perf_counter() - t0
    if any(rc != 0 for rc in phase2.returncodes):
        tails = "\n".join(w.tail for w in phase2.workers if w.returncode)
        raise RuntimeError(
            f"resumed run failed (rcs={phase2.returncodes}):\n{tails}")
    p2 = phase2.report or {}
    resumed = int(p2.get("start_step", 0))
    done = resumed + int(p2.get("steps", 0))
    if done != target_steps:
        raise RuntimeError(
            f"resumed run ended at step {done}, wanted {target_steps}")
    wasted = max(int(p1.get("steps", 0)) - resumed, 0)
    report = GoodputReport(
        n_processes=n_processes, target_steps=target_steps,
        useful_steps=done, wasted_steps=wasted, wall_seconds=wall,
        goodput_steps_per_sec=done / max(wall, 1e-9),
        raw_steps_per_sec=float(p2.get("steps_per_sec", 0.0)),
        emergency_step=p1.get("emergency_step"),
        resumed_step=resumed,
        phases=[
            {"phase": "preempted", "returncodes": phase1.returncodes,
             "steps": p1.get("steps"), "losses": p1.get("losses"),
             "wall_seconds": round(phase1.wall_seconds, 3)},
            {"phase": "resumed", "returncodes": phase2.returncodes,
             "steps": p2.get("steps"), "losses": p2.get("losses"),
             "wall_seconds": round(phase2.wall_seconds, 3)},
        ])
    return report
