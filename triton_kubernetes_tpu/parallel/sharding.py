"""Logical-axis → mesh-axis rules and NamedSharding helpers.

Models annotate every parameter/activation with *logical* axis names
("embed", "heads", "mlp", …); this module maps those onto the physical mesh
axes via a rules table (the flax ``logical_axis_rules`` idea, implemented
standalone so models stay pure-JAX pytrees).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_STAGE,
    AXIS_TENSOR,
)

MeshAxes = Union[str, Tuple[str, ...], None]

# The default layout. Key facts baked in:
# - batch splits over (data, fsdp): FSDP shards both params and batch.
# - params' embed dim shards over fsdp  → all-gathered per layer during the
#   forward pass (XLA inserts the collectives), classic FSDP/ZeRO-3.
# - heads/mlp/vocab shard over tensor   → Megatron-style TP, innermost ICI.
# - activations' sequence dim shards over seq → ring attention.
# - MoE expert dim shards over expert.
DEFAULT_RULES: Dict[str, MeshAxes] = {
    "batch": (AXIS_DATA, AXIS_FSDP),
    "sequence": AXIS_SEQ,
    "embed": AXIS_FSDP,
    "heads": AXIS_TENSOR,
    "kv_heads": AXIS_TENSOR,
    "head_dim": None,
    "mlp": AXIS_TENSOR,
    "vocab": AXIS_TENSOR,
    "expert": AXIS_EXPERT,
    # The scanned layer dim shards over the pipeline-stage axis: each stage
    # group holds its contiguous L/S chunk (a no-op on stage=1 meshes), so
    # train/pipeline.py's [L] -> [S, L/S] reshape is layout-preserving.
    "layers": AXIS_STAGE,
    "stage": AXIS_STAGE,
    "norm": None,
}


def logical_to_spec(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, MeshAxes]] = None,
    mesh: Optional[Mesh] = None,
) -> PartitionSpec:
    """Translate ("embed", "mlp") → PartitionSpec("fsdp", "tensor").

    Mesh axes already used by an earlier dim are dropped (a mesh axis may
    appear at most once in a PartitionSpec); axes absent from ``mesh`` are
    also dropped so the same rules work on sub-meshes.
    """
    rules = DEFAULT_RULES if rules is None else rules
    available = set(mesh.axis_names) if mesh is not None else None
    used: set = set()
    entries = []
    for ax in logical_axes:
        if ax is None:
            entries.append(None)
            continue
        if ax not in rules:
            raise KeyError(f"no sharding rule for logical axis {ax!r}")
        mapped = rules[ax]
        if mapped is None:
            entries.append(None)
            continue
        axes = (mapped,) if isinstance(mapped, str) else tuple(mapped)
        keep = tuple(
            a for a in axes
            if a not in used and (available is None or a in available))
        used.update(keep)
        if not keep:
            entries.append(None)
        elif len(keep) == 1:
            entries.append(keep[0])
        else:
            entries.append(keep)
    # Trim trailing Nones for readability; PartitionSpec pads implicitly.
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def logical_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, MeshAxes]] = None,
) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes, rules, mesh=mesh))


def spec_tree_from_logical(
    logical_tree: Any,
    rules: Optional[Dict[str, MeshAxes]] = None,
    mesh: Optional[Mesh] = None,
) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: logical_to_spec(axes, rules, mesh=mesh),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(a, (str, type(None))) for a in x),
    )


def shard_pytree(
    tree: Any,
    logical_tree: Any,
    mesh: Mesh,
    rules: Optional[Dict[str, MeshAxes]] = None,
) -> Any:
    """Device-put a pytree of arrays according to its logical-axis pytree."""
    specs = spec_tree_from_logical(logical_tree, rules, mesh=mesh)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree,
        specs,
    )
