"""Device-mesh and sharding layer for the bundled TPU workloads.

The reference has no parallelism runtime at all (SURVEY.md §2.5: zero
NCCL/MPI/tensor code in gadkins/triton-kubernetes); its only "fan-out" is
creating N identical VMs (create/node.go:266-323). The TPU-native equivalent
this package provides is the standard JAX SPMD stack: a named
``jax.sharding.Mesh`` over the slice's ICI torus, logical-axis→mesh-axis
rules, and ``NamedSharding`` helpers that the bundled models/trainer use to
lay out params and activations so collectives ride ICI.

Multi-host scale-out lives in :mod:`.multihost` (imported lazily by
callers — it is only needed once ``jax.distributed`` is in play): hybrid
DCN×ICI meshes, per-process input sharding, coordinated checkpointing,
and the local multi-process launcher/goodput harness.
"""

from .mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_STAGE,
    AXIS_TENSOR,
    MESH_AXES,
    MeshConfig,
    create_mesh,
)
from .sharding import (
    DEFAULT_RULES,
    logical_sharding,
    logical_to_spec,
    shard_pytree,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_EXPERT",
    "AXIS_FSDP",
    "AXIS_SEQ",
    "AXIS_STAGE",
    "AXIS_TENSOR",
    "MESH_AXES",
    "MeshConfig",
    "create_mesh",
    "DEFAULT_RULES",
    "logical_to_spec",
    "logical_sharding",
    "shard_pytree",
]
