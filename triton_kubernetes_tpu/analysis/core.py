"""Linter engine: file loading, the rule registry, suppressions.

Design constraints, in order:

* **stdlib only** — :mod:`ast` + :mod:`tokenize`; this must run on the
  provisioning-only install (no jax, no third-party linter).
* **root-relative** — every rule addresses files by POSIX-style path
  relative to a configurable root, so the test suite can build minimal
  known-bad trees under ``tmp_path`` and the same rule code checks both
  the fixture and the real repo.
* **two rule shapes** — per-file rules see one :class:`FileContext`;
  project rules see the whole :class:`Project` (cross-file constant
  agreement, catalog/docs drift).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# What `tk8s lint` scans when no explicit paths are given: the package,
# the CI scripts, and the two top-level entrypoints. tests/ is excluded
# by design — fixtures there *intentionally* violate invariants.
DEFAULT_SCAN_ROOTS: Tuple[str, ...] = (
    "triton_kubernetes_tpu", "scripts", "bench.py", "__graft_entry__.py",
)

SUPPRESS_RE = re.compile(
    r"tk8s-lint:\s*disable=(?P<codes>TK8S\d{3}(?:\s*,\s*TK8S\d{3})*)"
    r"(?P<rest>.*)")

# The attestation rule TK8S102 looks for (see rules.DonationAttestation).
# Matched against a joined comment block, so the why may span lines and
# contain parens (greedy to the block's last `)`).
DONATE_SAFE_RE = re.compile(r"tk8s:\s*donate-safe\((?P<why>.*)\)",
                            re.DOTALL)


@dataclass(frozen=True)
class Finding:
    """One diagnostic. ``path`` is root-relative POSIX."""

    code: str
    rule: str
    path: str
    line: int
    col: int
    message: str

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> Dict[str, object]:
        return {"code": self.code, "rule": self.rule, "path": self.path,
                "line": self.line, "col": self.col, "message": self.message}


@dataclass(frozen=True)
class Suppression:
    codes: Tuple[str, ...]
    reason: str
    line: int          # physical line the `disable=` comment sits on
    end_line: int      # last line of the comment block (reason may span
                       # consecutive full-line comments until the `)`)
    own_line: bool     # a comment-only block also covers the next line


@dataclass
class FileContext:
    """One parsed source file plus its comment map."""

    path: str                    # root-relative POSIX
    source: str
    tree: ast.AST
    comments: Dict[int, str] = field(default_factory=dict)
    suppressions: List[Suppression] = field(default_factory=list)
    parse_error: Optional[str] = None

    def comment_in_range(self, lo: int, hi: int,
                         pattern: re.Pattern) -> Optional[re.Match]:
        """First regex match over the comments on lines [lo, hi]."""
        for ln in range(lo, hi + 1):
            text = self.comments.get(ln)
            if text:
                m = pattern.search(text)
                if m:
                    return m
        return None

    def block_comment_text(self, node: ast.AST) -> str:
        """The contiguous full-line comment block immediately above
        ``node``, plus any comments inline within its span, joined —
        where statement-level attestations like donate-safe live."""
        lines = self.source.splitlines()

        def full_line(ln: int) -> bool:
            return (ln in self.comments and 1 <= ln <= len(lines)
                    and lines[ln - 1].lstrip().startswith("#"))

        start = getattr(node, "lineno", 1)
        end = getattr(node, "end_lineno", None) or start
        block: List[str] = []
        ln = start - 1
        while full_line(ln):
            block.insert(0, self.comments[ln])
            ln -= 1
        for inner in range(start, end + 1):
            if inner in self.comments:
                block.append(self.comments[inner])
        return " ".join(c.lstrip("# ").strip() for c in block)

    def suppressed(self, code: str, line: int) -> bool:
        """True if a well-formed (reason-carrying) suppression covers
        ``code`` at ``line``: same-line, or a comment-only line
        immediately above."""
        for s in self.suppressions:
            if code not in s.codes or not s.reason.strip():
                continue
            if s.line == line or (s.own_line and s.end_line == line - 1):
                return True
        return False


def _comment_map(source: str) -> Dict[int, str]:
    """line -> comment text, via tokenize (comments inside string
    literals never count)."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # the ast parse reports the real problem
    return out


def load_file(root: Path, rel: str) -> FileContext:
    source = (root / rel).read_text(encoding="utf-8")
    try:
        tree: ast.AST = ast.parse(source, filename=rel)
        err = None
    except SyntaxError as e:
        tree = ast.Module(body=[], type_ignores=[])
        err = f"{e.msg} (line {e.lineno})"
    comments = _comment_map(source)
    lines = source.splitlines()

    def full_line(ln: int) -> bool:
        return (ln in comments and 1 <= ln <= len(lines)
                and lines[ln - 1].lstrip().startswith("#"))

    sups: List[Suppression] = []
    for ln in sorted(comments):
        m = SUPPRESS_RE.search(comments[ln])
        if m is None:
            continue
        codes = tuple(c.strip() for c in m.group("codes").split(","))
        own = full_line(ln)
        # The mandatory reason: `(...)`. An own-line suppression extends
        # over the whole contiguous full-line comment block, so real
        # explanations need not cram one line; the reason runs to the
        # LAST `)` in the block (reasons may themselves contain parens,
        # e.g. "close() already quarantines").
        rest, end = m.group("rest").strip(), ln
        while own and full_line(end + 1):
            end += 1
            rest += " " + comments[end].lstrip("# ").strip()
        rm = re.match(r"\((?P<reason>.*)\)", rest, re.DOTALL)
        reason = rm.group("reason").strip() if rm else ""
        sups.append(Suppression(codes=codes, reason=reason, line=ln,
                                end_line=end, own_line=own))
    return FileContext(path=rel, source=source, tree=tree,
                       comments=comments, suppressions=sups,
                       parse_error=err)


@dataclass
class Project:
    """Every scanned file, addressable root-relative."""

    root: Path
    files: Dict[str, FileContext] = field(default_factory=dict)

    def file(self, rel: str) -> Optional[FileContext]:
        """Fetch (loading lazily) a file a project rule needs even when
        it is outside the scanned set — e.g. a docs .md is read raw via
        :meth:`read_text`, but pinned-constant sites are .py files that
        may not be under an explicitly restricted scan."""
        if rel in self.files:
            return self.files[rel]
        p = self.root / rel
        if not p.is_file():
            return None
        ctx = load_file(self.root, rel)
        self.files[rel] = ctx
        return ctx

    def read_text(self, rel: str) -> Optional[str]:
        p = self.root / rel
        if not p.is_file():
            return None
        return p.read_text(encoding="utf-8")


class Rule:
    """Base class. Subclasses set ``code``/``name``/``summary`` and
    override one (or both) of the check hooks."""

    code: str = ""
    name: str = ""
    summary: str = ""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()

    # -- helpers shared by subclasses ------------------------------------
    def finding(self, ctx_or_path, line: int, col: int,
                message: str) -> Finding:
        path = (ctx_or_path.path if isinstance(ctx_or_path, FileContext)
                else str(ctx_or_path))
        return Finding(code=self.code, rule=self.name, path=path,
                       line=line, col=col, message=message)


RULES: List[Rule] = []


def register(cls):
    """Class decorator: instantiate and add to the active registry."""
    RULES.append(cls())
    return cls


def discover(root: Path, scan: Sequence[str]) -> List[str]:
    rels: List[str] = []
    for entry in scan:
        p = root / entry
        if p.is_file() and p.suffix == ".py":
            rels.append(Path(entry).as_posix())
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts:
                    continue
                rels.append(f.relative_to(root).as_posix())
    return rels


def _suppression_hygiene(ctx: FileContext) -> List[Finding]:
    """TK8S100: every disable must carry a non-empty reason. Emitted by
    the engine (not a registered rule instance) so it cannot itself be
    disabled."""
    out = []
    for s in ctx.suppressions:
        if not s.reason.strip():
            out.append(Finding(
                code="TK8S100", rule="suppression-hygiene", path=ctx.path,
                line=s.line, col=0,
                message="tk8s-lint disable without a reason — write "
                        "disable=CODE(<why this is safe here>)"))
    return out


def lint_project(root, paths: Optional[Sequence[str]] = None,
                 rules: Optional[Sequence[Rule]] = None,
                 ) -> Tuple[List[Finding], Dict[str, object]]:
    """Run the registry over ``root``. Returns (findings, stats).

    ``paths`` restricts the per-file scan (project rules still load the
    specific files they pin). Suppressed findings are dropped; malformed
    suppressions surface as TK8S100.
    """
    root = Path(root)
    active = list(rules) if rules is not None else list(RULES)
    scan = list(paths) if paths else list(DEFAULT_SCAN_ROOTS)
    project = Project(root=root)
    for rel in discover(root, scan):
        project.file(rel)
    scanned = list(project.files)

    findings: List[Finding] = []
    for rel in scanned:
        ctx = project.files[rel]
        findings.extend(_suppression_hygiene(ctx))
        if ctx.parse_error:
            findings.append(Finding(
                code="TK8S199", rule="syntax", path=rel, line=1, col=0,
                message=f"file does not parse: {ctx.parse_error}"))
            continue
        for rule in active:
            findings.extend(rule.check_file(ctx))
    for rule in active:
        findings.extend(rule.check_project(project))

    kept = []
    for f in findings:
        ctx = project.files.get(f.path)
        if (f.code != "TK8S100" and ctx is not None
                and ctx.suppressed(f.code, f.line)):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    stats: Dict[str, object] = {
        "files_checked": len(scanned),
        "rules": sorted({r.code for r in active} | {"TK8S100"}),
    }
    return kept, stats
