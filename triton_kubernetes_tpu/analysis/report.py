"""Reporters: human terminal text and the JSON evidence document.

The JSON shape is the `static-analysis-evidence` CI artifact
(scripts/ci/static_analysis_evidence.py uploads it), so it is versioned
and additive-only.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from .core import RULES, Finding

JSON_VERSION = 1


def render_human(findings: Sequence[Finding],
                 stats: Dict[str, object]) -> str:
    lines: List[str] = []
    for f in findings:
        lines.append(f"{f.location()}: {f.code} [{f.rule}] {f.message}")
    n = len(findings)
    rules = stats.get("rules", [])
    lines.append(
        f"{'FAIL' if n else 'OK'}: {n} finding{'s' if n != 1 else ''} "
        f"({stats.get('files_checked', 0)} files, {len(rules)} rules)")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding],
                stats: Dict[str, object]) -> str:
    by_code: Dict[str, int] = {}
    for f in findings:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    doc = {
        "version": JSON_VERSION,
        "files_checked": stats.get("files_checked", 0),
        "rules": [
            {"code": r.code, "name": r.name, "summary": r.summary}
            for r in sorted(RULES, key=lambda r: r.code)
        ],
        "findings": [f.to_dict() for f in findings],
        "summary": {"total": len(findings), "by_code": by_code},
    }
    return json.dumps(doc, indent=2, sort_keys=True)
