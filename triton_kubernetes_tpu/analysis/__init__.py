"""Repo-native static analysis: the invariants PRs 1-8 established,
checked mechanically.

Every hard bug in this repo's history has been an invariant violation a
tree walk can catch: donating a host-aliased buffer (PR 8's memory
corruption), importing ``jax.experimental.shard_map`` raw instead of
through ``utils/jaxcompat.py`` (a C++ abort, not an exception, on old
jax), sleeping under the cloudsim lock, a port constant drifting at one
of its jax-free duplication sites. ``tk8s lint`` encodes each of those
as a ``TK8S1xx`` rule over stdlib :mod:`ast` — no third-party linter
dependency, matching the metrics/trace ethos.

Public surface:

* :func:`lint_project` — run every rule over a repo root, returns
  (findings, stats);
* :data:`RULES` — the active rule registry;
* :class:`Finding` — one diagnostic;
* reporters in :mod:`.report` (human text + JSON evidence).

Suppressions are inline comments with a mandatory reason::

    time.sleep(0.1)  # tk8s-lint: disable=TK8S103(latency knob; lock not held)

A reasonless ``disable`` is itself an error (TK8S100). Policy and the
rule catalog: docs/guide/static-analysis.md.
"""

from .core import (
    DEFAULT_SCAN_ROOTS,
    FileContext,
    Finding,
    Project,
    RULES,
    Rule,
    lint_project,
    register,
)
from . import rules as _rules  # noqa: F401  (importing registers the rules)
from .report import render_human, render_json

__all__ = [
    "DEFAULT_SCAN_ROOTS",
    "FileContext",
    "Finding",
    "Project",
    "RULES",
    "Rule",
    "lint_project",
    "register",
    "render_human",
    "render_json",
]
