"""The TK8S1xx rule set: one rule per bug class PRs 1-8 fixed by hand.

Each rule's docstring names the historical incident it mechanizes; the
full catalog with suppression policy lives in
docs/guide/static-analysis.md. Codes are stable — tests pin them, and
suppression comments reference them — so renumbering is a breaking
change.

Engine-reserved codes (emitted by :mod:`.core`, not here): TK8S100
(suppression without a reason), TK8S199 (file does not parse).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .core import (
    DONATE_SAFE_RE,
    FileContext,
    Finding,
    Project,
    Rule,
    register,
)

PKG = "triton_kubernetes_tpu"
JAXCOMPAT = f"{PKG}/utils/jaxcompat.py"


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_map(tree: ast.AST) -> Dict[str, str]:
    """local name -> fully qualified origin, for every import binding.

    ``import time`` -> {time: time}; ``import subprocess as sp`` ->
    {sp: subprocess}; ``from time import sleep`` -> {sleep: time.sleep}.
    Relative imports keep their leading dots (callers match suffixes).
    """
    out: Dict[str, str] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(n, ast.ImportFrom):
            mod = "." * n.level + (n.module or "")
            for a in n.names:
                out[a.asname or a.name] = f"{mod}.{a.name}"
    return out


def resolve_call(node: ast.Call, imports: Dict[str, str]) -> Optional[str]:
    """Fully qualified dotted name of the callee, through the file's
    import aliases. ``sp.run(...)`` with ``import subprocess as sp``
    resolves to ``subprocess.run``."""
    name = dotted(node.func)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    origin = imports.get(head)
    if origin is None:
        return name
    return f"{origin}.{rest}" if rest else origin


# ---------------------------------------------------------------------------
# TK8S101 — jaxcompat discipline
# ---------------------------------------------------------------------------

@register
class JaxcompatDiscipline(Rule):
    """``jax.experimental.shard_map`` and ``jax.experimental.pallas``
    may be imported ONLY inside utils/jaxcompat.py.

    History: on jax < 0.5 the old ``auto=`` shard_map spelling aborts
    the whole process with a C++ crash (not a catchable exception), and
    ``pltpu.CompilerParams`` does not exist (it is TPUCompilerParams).
    utils/jaxcompat.py is the one adapter that translates; a raw import
    anywhere else reintroduces the crash on exactly the environments CI
    cannot reach.
    """

    code = "TK8S101"
    name = "jaxcompat-discipline"
    summary = ("jax.experimental.shard_map / pallas imports only inside "
               "utils/jaxcompat.py")

    GATED = ("jax.experimental.shard_map", "jax.experimental.pallas")

    def _gated(self, module: str) -> bool:
        return any(module == g or module.startswith(g + ".")
                   for g in self.GATED)

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path == JAXCOMPAT:
            return ()
        rule = self
        out: List[Finding] = []

        def report(node: ast.AST, name: str) -> None:
            out.append(rule.finding(
                ctx, node.lineno, node.col_offset,
                f"{name} used outside utils/jaxcompat.py — route it "
                f"through the jaxcompat adapter (raw use aborts the "
                f"process on jax < 0.5)"))

        class _Visitor(ast.NodeVisitor):
            def visit_Import(self, n: ast.Import) -> None:
                for a in n.names:
                    if rule._gated(a.name):
                        report(n, a.name)

            def visit_ImportFrom(self, n: ast.ImportFrom) -> None:
                if n.level != 0 or not n.module:
                    return
                if rule._gated(n.module):
                    report(n, n.module)
                elif n.module == "jax.experimental":
                    for a in n.names:
                        full = f"jax.experimental.{a.name}"
                        if rule._gated(full):
                            report(n, full)

            def visit_Attribute(self, n: ast.Attribute) -> None:
                # Report only the outermost chain: descending after a
                # match would re-report every gated prefix of the same
                # expression (jax.experimental.pallas.tpu would fire
                # twice).
                full = dotted(n)
                if full and rule._gated(full):
                    report(n, full)
                    return
                self.generic_visit(n)

        _Visitor().visit(ctx.tree)
        return out


# ---------------------------------------------------------------------------
# TK8S102 — donation-aliasing attestation
# ---------------------------------------------------------------------------

@register
class DonationAttestation(Rule):
    """Every ``donate_argnums``/``donate_argnames`` site must carry a
    ``# tk8s: donate-safe(<why>)`` attestation.

    History (PR 8): on jax 0.4.37 CPU, ``device_put`` can zero-copy a
    host numpy buffer; donating that host-aliased array corrupted
    memory a few steps after every restore — NaN losses, then a
    segfault. Donation is an aliasing contract the type system cannot
    see; the attestation forces the author to state why the donated
    buffer is device-owned and never read again.
    """

    code = "TK8S102"
    name = "donate-attestation"
    summary = "donate_argnums sites need a # tk8s: donate-safe(<why>)"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            kw = next((k for k in n.keywords
                       if k.arg in ("donate_argnums", "donate_argnames")),
                      None)
            if kw is None:
                continue
            m = DONATE_SAFE_RE.search(ctx.block_comment_text(n))
            if m is None:
                yield self.finding(
                    ctx, n.lineno, n.col_offset,
                    "buffer donation without a '# tk8s: donate-safe(<why>)' "
                    "attestation — state why the donated operand is "
                    "device-owned and never read after this call "
                    "(donating a host-aliased buffer corrupts memory on "
                    "zero-copy backends)")
            elif not m.group("why").strip():
                yield self.finding(
                    ctx, n.lineno, n.col_offset,
                    "donate-safe attestation has an empty reason — say "
                    "why the donated buffer cannot alias host memory")


# ---------------------------------------------------------------------------
# TK8S103 — lock discipline
# ---------------------------------------------------------------------------

@register
class LockDiscipline(Rule):
    """No sleeps, subprocess, or network I/O lexically inside a
    ``with <...lock...>:`` block.

    History: cloudsim's deterministic ``op_latency`` knob originally
    slept while holding the simulator RLock, serializing the wavefront
    it existed to measure; the fix ("sleeps outside the lock") is a
    one-line ordering constraint nothing enforced. Scope matches where
    locks guard hot shared state: executor/, serve/, manager/, and
    utils/metrics.py.
    """

    code = "TK8S103"
    name = "lock-discipline"
    summary = "no sleep/subprocess/socket/HTTP under a held lock"

    SCOPES = (f"{PKG}/executor/", f"{PKG}/serve/", f"{PKG}/manager/")
    FILES = (f"{PKG}/utils/metrics.py",)
    BLOCKING = ("time.sleep", "subprocess.", "socket.",
                "urllib.request.", "http.client.", "requests.")

    def _in_scope(self, path: str) -> bool:
        return path.startswith(self.SCOPES) or path in self.FILES

    def _is_lock(self, item: ast.withitem) -> bool:
        expr = item.context_expr
        # `with self._lock:` / `with lock:` / `with pool.state_lock:` —
        # anything whose terminal name mentions "lock".
        name = dotted(expr)
        if name is None and isinstance(expr, ast.Call):
            name = dotted(expr.func)
        return bool(name) and "lock" in name.rsplit(".", 1)[-1].lower()

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not self._in_scope(ctx.path):
            return
        imports = import_map(ctx.tree)
        for n in ast.walk(ctx.tree):
            if not isinstance(n, (ast.With, ast.AsyncWith)):
                continue
            if not any(self._is_lock(i) for i in n.items):
                continue
            for inner in ast.walk(ast.Module(body=n.body, type_ignores=[])):
                if not isinstance(inner, ast.Call):
                    continue
                callee = resolve_call(inner, imports)
                if callee and (callee in self.BLOCKING
                               or callee.startswith(self.BLOCKING)):
                    yield self.finding(
                        ctx, inner.lineno, inner.col_offset,
                        f"{callee} called while a lock is held — move the "
                        f"blocking call outside the `with` block (it "
                        f"serializes every thread contending this lock)")


# ---------------------------------------------------------------------------
# TK8S104 — pinned-constant agreement
# ---------------------------------------------------------------------------

@register
class PinnedConstants(Rule):
    """Port and exit-code constants duplicated across the jax boundary
    must literal-match ``triton_kubernetes_tpu/constants.py`` (or import
    from it) at every registered site.

    History: COORDINATOR_PORT, SERVE_PORT, and exit 75 are deliberately
    duplicated jax-free (rendering must not import the jax-loaded train
    package) and were pinned equal only by individual tests — a new
    duplication site silently escaped the convention.
    """

    code = "TK8S104"
    name = "pinned-constants"
    summary = ("cross-file port/exit-code duplication sites must match "
               "constants.py")

    CANONICAL = f"{PKG}/constants.py"
    # canonical name -> [(site file, local name), ...]
    SITES: Dict[str, List[Tuple[str, str]]] = {
        "COORDINATOR_PORT": [
            (f"{PKG}/topology/jobset.py", "COORDINATOR_PORT"),
            (f"{PKG}/train/__main__.py", "COORDINATOR_PORT"),
        ],
        "SERVE_PORT": [
            (f"{PKG}/serve/server.py", "SERVE_PORT"),
            (f"{PKG}/topology/serving.py", "SERVE_PORT"),
        ],
        "EXIT_RESUME": [
            (f"{PKG}/train/resilience.py", "EXIT_RESUME"),
            (f"{PKG}/topology/jobset.py", "RESUME_EXIT_CODE"),
        ],
        "EXIT_UNSUPPORTED": [
            (f"{PKG}/parallel/multihost.py", "EXIT_UNSUPPORTED"),
        ],
        "EXIT_CONFIG": [
            (f"{PKG}/train/__main__.py", "EXIT_CONFIG"),
        ],
        "EXIT_ANOMALY": [
            (f"{PKG}/train/__main__.py", "EXIT_ANOMALY"),
        ],
    }

    @staticmethod
    def _literal_assign(tree: ast.AST, name: str
                        ) -> Optional[Tuple[object, int]]:
        for n in ast.walk(tree):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if (isinstance(t, ast.Name) and t.id == name
                            and isinstance(n.value, ast.Constant)):
                        return n.value.value, n.lineno
            elif (isinstance(n, ast.AnnAssign)
                  and isinstance(n.target, ast.Name)
                  and n.target.id == name
                  and isinstance(n.value, ast.Constant)):
                return n.value.value, n.lineno
        return None

    @staticmethod
    def _imports_from_constants(tree: ast.AST, canonical: str,
                                local: str) -> bool:
        for n in ast.walk(tree):
            if not isinstance(n, ast.ImportFrom):
                continue
            mod = n.module or ""
            if not (mod == "constants" or mod.endswith(".constants")
                    or mod == f"{PKG}.constants"):
                continue
            for a in n.names:
                if a.name == canonical and (a.asname or a.name) == local:
                    return True
        return False

    def check_project(self, project: Project) -> Iterable[Finding]:
        canon = project.file(self.CANONICAL)
        if canon is None:
            return
        for name, sites in self.SITES.items():
            got = self._literal_assign(canon.tree, name)
            if got is None:
                yield self.finding(
                    self.CANONICAL, 1, 0,
                    f"{name} missing from the canonical constants module")
                continue
            value, _ = got
            for rel, local in sites:
                site = project.file(rel)
                if site is None:
                    continue
                if self._imports_from_constants(site.tree, name, local):
                    continue
                lit = self._literal_assign(site.tree, local)
                if lit is None:
                    yield self.finding(
                        rel, 1, 0,
                        f"{local} is a registered duplication site of "
                        f"constants.{name} but neither assigns a literal "
                        f"nor imports it from {PKG}.constants")
                elif lit[0] != value:
                    yield self.finding(
                        rel, lit[1], 0,
                        f"{local} = {lit[0]!r} drifted from "
                        f"constants.{name} = {value!r} — the manifests "
                        f"and the runtime now disagree")


# ---------------------------------------------------------------------------
# TK8S105 — metrics-catalog drift
# ---------------------------------------------------------------------------

@register
class MetricsCatalogDrift(Rule):
    """Every ``tk8s_*`` family used anywhere must be declared in
    utils/metrics.py CATALOG, every CATALOG family must appear in
    docs/guide/observability.md, and every family the docs name must
    exist in CATALOG.

    History: CATALOG is "the single source of truth that docs and the
    ``tk8s metrics`` dump share" — but nothing checked it. A family
    registered ad hoc is invisible to ``register_catalog()`` (so the
    ``tk8s metrics`` zero-valued dump and Grafana discovery miss it) and
    to the docs table operators read.
    """

    code = "TK8S105"
    name = "metrics-catalog-drift"
    summary = "tk8s_* families must agree across code, CATALOG, and docs"

    CATALOG_FILE = f"{PKG}/utils/metrics.py"
    DOCS_FILE = "docs/guide/observability.md"
    FAMILY_RE = re.compile(r"tk8s_[a-z0-9_]*[a-z0-9]\*?")

    def _catalog(self, ctx: FileContext) -> Optional[Dict[str, int]]:
        for n in ast.walk(ctx.tree):
            value = None
            if (isinstance(n, ast.AnnAssign)
                    and isinstance(n.target, ast.Name)
                    and n.target.id == "CATALOG"):
                value = n.value
            elif isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "CATALOG"
                    for t in n.targets):
                value = n.value
            if isinstance(value, ast.Dict):
                return {k.value: k.lineno for k in value.keys
                        if isinstance(k, ast.Constant)}
        return None

    def check_project(self, project: Project) -> Iterable[Finding]:
        cat_ctx = project.file(self.CATALOG_FILE)
        if cat_ctx is None:
            return
        catalog = self._catalog(cat_ctx)
        if catalog is None:
            yield self.finding(self.CATALOG_FILE, 1, 0,
                               "no CATALOG dict found in the metrics module")
            return
        # code -> CATALOG
        for rel, ctx in list(project.files.items()):
            if not rel.endswith(".py"):
                continue
            for n in ast.walk(ctx.tree):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in ("counter", "gauge", "histogram")
                        and n.args
                        and isinstance(n.args[0], ast.Constant)
                        and isinstance(n.args[0].value, str)
                        and n.args[0].value.startswith("tk8s_")):
                    fam = n.args[0].value
                    if fam not in catalog:
                        yield self.finding(
                            rel, n.lineno, n.col_offset,
                            f"metric family {fam!r} is not declared in "
                            f"utils/metrics.py CATALOG — add it there "
                            f"(and to the observability docs table)")
        docs = project.read_text(self.DOCS_FILE)
        if docs is None:
            return
        # CATALOG -> docs
        for fam, lineno in sorted(catalog.items()):
            if fam not in docs:
                yield self.finding(
                    self.CATALOG_FILE, lineno, 0,
                    f"CATALOG family {fam!r} is missing from "
                    f"{self.DOCS_FILE} — document it in the metrics table")
        # docs -> CATALOG (names ending in `*` or `_` are wildcard
        # prose mentions like tk8s_train_*, not family names)
        for m in self.FAMILY_RE.finditer(docs):
            fam = m.group(0)
            if fam.endswith("*"):
                continue
            if docs[m.end():m.end() + 2].startswith(("_*", "*")):
                continue  # wildcard prose mention, e.g. tk8s_train_*
            for suffix in ("_bucket", "_sum", "_count"):
                # Exposition-sample spellings (the exemplar example in
                # the docs shows literal _bucket lines) resolve to
                # their histogram family, exactly as parse_prometheus
                # reassembles them.
                if fam.endswith(suffix) and fam[: -len(suffix)] in catalog:
                    fam = fam[: -len(suffix)]
                    break
            if fam not in catalog:
                line = docs.count("\n", 0, m.start()) + 1
                yield self.finding(
                    self.DOCS_FILE, line, 0,
                    f"docs name metric family {fam!r} which is not in "
                    f"utils/metrics.py CATALOG — stale docs or a typo'd "
                    f"family name")


# ---------------------------------------------------------------------------
# TK8S106 — typed-error discipline
# ---------------------------------------------------------------------------

@register
class TypedErrors(Rule):
    """No bare ``except:`` and no swallowed ``except Exception: pass``
    in executor/, workflows/, train/.

    History: the repo's error taxonomy (TransientApplyError vs
    FatalApplyError, CheckpointIntegrityError.reason slugs, typed
    workflow errors) exists so retry/fallback logic can classify — a
    blanket swallow upstream turns a classifiable fault into silence.
    Genuine best-effort paths (atexit, __del__) carry a suppression
    with the reason spelled out.
    """

    code = "TK8S106"
    name = "typed-errors"
    summary = "no bare except / swallowed `except Exception: pass`"

    SCOPES = (f"{PKG}/executor/", f"{PKG}/workflows/", f"{PKG}/train/")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.path.startswith(self.SCOPES):
            return
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.ExceptHandler):
                continue
            if n.type is None:
                yield self.finding(
                    ctx, n.lineno, n.col_offset,
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "too — catch a typed error (or at least Exception)")
                continue
            broad = (isinstance(n.type, ast.Name)
                     and n.type.id in ("Exception", "BaseException"))
            swallows = all(
                isinstance(s, ast.Pass)
                or (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                    and s.value.value is Ellipsis)
                for s in n.body)
            if broad and swallows:
                yield self.finding(
                    ctx, n.lineno, n.col_offset,
                    f"`except {n.type.id}: pass` swallows every fault "
                    f"unclassified — narrow the type, log it, or "
                    f"suppress with the best-effort reason spelled out")


# ---------------------------------------------------------------------------
# TK8S107 — resume determinism
# ---------------------------------------------------------------------------

@register
class ResumeDeterminism(Rule):
    """No wall-clock or global-RNG calls in the journal/checkpoint
    commit paths — time and randomness must come through the injectable
    seams (``clock``/``sleep`` ctor args, seeded ``random.Random``).

    History: the whole resume story — bitwise serial/parallel journal
    parity, kill-mid-wave resume, rollback stream replay — holds only
    because these paths are deterministic functions of their inputs. A
    naked ``time.time()`` in a journal write is invisible until a
    resume diff flakes in CI.
    """

    code = "TK8S107"
    name = "resume-determinism"
    summary = ("no naked time.time()/random.* in journal/checkpoint "
               "commit paths")

    FILES = (
        f"{PKG}/executor/engine.py",
        f"{PKG}/executor/cloudsim.py",
        f"{PKG}/train/checkpoint.py",
        f"{PKG}/train/resilience.py",
        f"{PKG}/serve/engine.py",
        f"{PKG}/serve/blocks.py",
        f"{PKG}/state/document.py",
    )
    BANNED = {
        "time.time", "time.time_ns", "datetime.datetime.now",
        "datetime.datetime.utcnow", "datetime.date.today", "uuid.uuid4",
        "random.random", "random.randint", "random.randrange",
        "random.choice", "random.choices", "random.shuffle",
        "random.sample", "random.uniform", "random.gauss",
        "random.getrandbits", "random.seed",
    }

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path not in self.FILES:
            return
        imports = import_map(ctx.tree)
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            callee = resolve_call(n, imports)
            if callee in self.BANNED:
                yield self.finding(
                    ctx, n.lineno, n.col_offset,
                    f"{callee}() in a resume-critical path — inject a "
                    f"clock/seeded RNG seam instead (ManualClock, the "
                    f"`sleep`/`clock` ctor args, random.Random(seed)); "
                    f"nondeterminism here breaks bitwise resume parity")


# ---------------------------------------------------------------------------
# TK8S110 — reconcile-loop determinism
# ---------------------------------------------------------------------------

@register
class OperatorDeterminism(Rule):
    """No wall-clock or global-RNG calls anywhere in ``operator/`` —
    the reconcile loop must take time through its injectable
    ``clock``/``sleep`` seams and randomness through seeded
    ``random.Random``.

    History: TK8S107 pins the same discipline for the journal/
    checkpoint *commit paths* file by file; the operator extends the
    stakes to a whole package — its tick journal, hysteresis counters,
    cooldown stamps, and the chaos harness's preempt-mid-reconcile
    replay are all deterministic functions of the injected clock, so a
    naked ``time.time()`` anywhere in the loop breaks corpus replay the
    same way it broke resume parity.
    """

    code = "TK8S110"
    name = "operator-determinism"
    summary = ("no naked time.time()/random.* anywhere in operator/ — "
               "use the injectable clock/seeded-RNG seams")

    SCOPES = (f"{PKG}/operator/",)
    BANNED = ResumeDeterminism.BANNED

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.path.startswith(self.SCOPES):
            return
        imports = import_map(ctx.tree)
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            callee = resolve_call(n, imports)
            if callee in self.BANNED:
                yield self.finding(
                    ctx, n.lineno, n.col_offset,
                    f"{callee}() in the reconcile loop — inject the "
                    f"clock/sleep ctor seams or a seeded "
                    f"random.Random instead; nondeterminism here "
                    f"breaks tick-journal replay and the chaos "
                    f"harness's preempt-mid-reconcile pins")


# ---------------------------------------------------------------------------
# TK8S111 — span-catalog drift
# ---------------------------------------------------------------------------

@register
class SpanCatalogDrift(Rule):
    """Every span/event name the engine, router, or operator emits must
    be declared in utils/trace.py SPAN_CATALOG, every catalog entry
    must appear in the span-catalog table of
    docs/guide/observability.md, and every span the table names must
    exist in the catalog.

    History: the TK8S105 pattern applied to traces. The fleet-merged
    Perfetto timeline and the flight recorder's /stats surface are only
    debuggable if span names are a closed, documented vocabulary — an
    ad-hoc emission would appear on operator timelines undocumented,
    and a renamed span would strand the docs (and any trace-processing
    script keyed on the old name) silently.
    """

    code = "TK8S111"
    name = "span-catalog-drift"
    summary = ("emitted span names must agree across serve/operator "
               "call sites, utils/trace.py SPAN_CATALOG, and the docs "
               "span table")

    CATALOG_FILE = f"{PKG}/utils/trace.py"
    DOCS_FILE = "docs/guide/observability.md"
    SCOPES = (f"{PKG}/serve/", f"{PKG}/operator/", f"{PKG}/train/")
    FILES = (CATALOG_FILE,)
    # A span name: dotted lowercase (`serve.prefill`, `route.place`).
    NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+$")
    # A docs span-table row: first cell is the backticked span name.
    ROW_RE = re.compile(
        r"^\|\s*`([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)`\s*\|", re.MULTILINE)

    def _catalog(self, ctx: FileContext) -> Optional[Dict[str, int]]:
        for n in ast.walk(ctx.tree):
            value = None
            if (isinstance(n, ast.AnnAssign)
                    and isinstance(n.target, ast.Name)
                    and n.target.id == "SPAN_CATALOG"):
                value = n.value
            elif isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "SPAN_CATALOG"
                    for t in n.targets):
                value = n.value
            if isinstance(value, ast.Dict):
                return {k.value: k.lineno for k in value.keys
                        if isinstance(k, ast.Constant)}
        return None

    def _emitted_name(self, call: ast.Call) -> Optional[ast.Constant]:
        """The span-name literal of a ``*.event(...)`` call: the first
        string constant among the leading positional args (position 0
        for TraceWriter.event, 1 for FlightRecorder.event — the
        request id ahead of it is never a literal)."""
        for a in call.args[:2]:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                return a
        return None

    def check_project(self, project: Project) -> Iterable[Finding]:
        cat_ctx = project.file(self.CATALOG_FILE)
        if cat_ctx is None:
            return
        catalog = self._catalog(cat_ctx)
        if catalog is None:
            yield self.finding(
                self.CATALOG_FILE, 1, 0,
                "no SPAN_CATALOG dict found in the trace module")
            return
        # emissions -> catalog
        for rel, ctx in list(project.files.items()):
            if not (rel.startswith(self.SCOPES) or rel in self.FILES):
                continue
            for n in ast.walk(ctx.tree):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "event"):
                    continue
                lit = self._emitted_name(n)
                if lit is None:
                    continue
                if lit.value not in catalog:
                    yield self.finding(
                        rel, n.lineno, n.col_offset,
                        f"span name {lit.value!r} is not declared in "
                        f"utils/trace.py SPAN_CATALOG — add it there "
                        f"(and to the span-catalog table in "
                        f"{self.DOCS_FILE})")
        docs = project.read_text(self.DOCS_FILE)
        if docs is None:
            return
        table = {m.group(1): docs.count("\n", 0, m.start()) + 1
                 for m in self.ROW_RE.finditer(docs)}
        # catalog -> docs table
        for span, lineno in sorted(catalog.items()):
            if span not in table:
                yield self.finding(
                    self.CATALOG_FILE, lineno, 0,
                    f"SPAN_CATALOG entry {span!r} is missing from the "
                    f"span-catalog table in {self.DOCS_FILE}")
        # docs table -> catalog
        for span, lineno in sorted(table.items()):
            if span not in catalog:
                yield self.finding(
                    self.DOCS_FILE, lineno, 0,
                    f"docs span table names {span!r} which is not in "
                    f"utils/trace.py SPAN_CATALOG — stale docs or a "
                    f"typo'd span name")


# ---------------------------------------------------------------------------
# TK8S108 — CLI/docs drift
# ---------------------------------------------------------------------------

@register
class CliDocsDrift(Rule):
    """Every ``--flag`` the user-facing entrypoints register must be
    documented somewhere under docs/.

    History: the trainer grew ~35 flags across five PRs; the guide
    pages (performance.md, workloads.md, serving.md) documented them by
    convention only, and several (--learning-rate, --dry-run, --stage)
    had silently never made it into any doc.
    """

    code = "TK8S108"
    name = "cli-docs-drift"
    summary = "every registered --flag must appear in docs/"

    CLI_FILES = (f"{PKG}/cli/main.py", f"{PKG}/train/__main__.py")

    def _docs_corpus(self, project: Project) -> Optional[str]:
        docs_dir = project.root / "docs"
        if not docs_dir.is_dir():
            return None
        parts = []
        for p in sorted(docs_dir.rglob("*.md")):
            parts.append(p.read_text(encoding="utf-8"))
        readme = project.root / "README.md"
        if readme.is_file():
            parts.append(readme.read_text(encoding="utf-8"))
        return "\n".join(parts)

    def check_project(self, project: Project) -> Iterable[Finding]:
        corpus = self._docs_corpus(project)
        if corpus is None:
            return
        for rel in self.CLI_FILES:
            ctx = project.file(rel)
            if ctx is None:
                continue
            for n in ast.walk(ctx.tree):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "add_argument"):
                    continue
                for a in n.args:
                    if (isinstance(a, ast.Constant)
                            and isinstance(a.value, str)
                            and a.value.startswith("--")
                            and a.value not in corpus):
                        yield self.finding(
                            ctx, n.lineno, n.col_offset,
                            f"flag {a.value} is not documented anywhere "
                            f"under docs/ — add it to the relevant guide "
                            f"page")


# ---------------------------------------------------------------------------
# TK8S109 — chaos-corpus schema
# ---------------------------------------------------------------------------

@register
class ChaosCorpusSchema(Rule):
    """Every ``tests/chaos_corpus/*.json`` entry must parse and match the
    corpus schema (triton_kubernetes_tpu/chaos/corpus.py).

    History: the corpus exists so every shrunk chaos counterexample
    replays as a pinned regression test (ISSUE 10). The replay tests
    load the whole directory and fail loudly on an invalid file — but
    only when they run; a hand-edited entry that stops validating would
    otherwise sit silent until the next full test pass. The lint gate
    reports the drift in seconds, file and reason named.
    """

    code = "TK8S109"
    name = "chaos-corpus-schema"
    summary = "tests/chaos_corpus entries must match the corpus schema"

    CORPUS_DIR = "tests/chaos_corpus"

    def check_project(self, project: Project) -> Iterable[Finding]:
        import json

        from ..chaos.corpus import validate_entry

        corpus = project.root / self.CORPUS_DIR
        if not corpus.is_dir():
            return
        for p in sorted(corpus.glob("*.json")):
            rel = p.relative_to(project.root).as_posix()
            try:
                entry = json.loads(p.read_text(encoding="utf-8"))
            except ValueError as e:
                yield self.finding(rel, 1, 0,
                                   f"corpus entry is not valid JSON: {e}")
                continue
            for problem in validate_entry(entry):
                yield self.finding(
                    rel, 1, 0,
                    f"corpus entry does not match the schema: {problem} "
                    f"(see triton_kubernetes_tpu/chaos/corpus.py)")


# ---------------------------------------------------------------------------
# TK8S112 — workload fault-kind drift
# ---------------------------------------------------------------------------

@register
class WorkloadFaultDrift(Rule):
    """The chaos workload fault vocabulary must agree everywhere it is
    spelled: ``WORKLOAD_FAULT_KINDS`` (the closed kind set) and
    ``WORKLOAD_DEFAULTS`` (its per-kind fields) in chaos/corpus.py, the
    ``_ARMS`` dispatch dict in chaos/workload.py, the ``workload_kinds``
    draws of generator profiles, and the ``workload`` key of the spec
    schema.

    History: the "silently inert rule" bug class (ISSUE 16) applied to
    workload faults. A kind with defaults but no arm dispatches to a
    KeyError only when first drawn; a kind an arm implements but the
    generator never draws is dead chaos coverage; a renamed kind strands
    committed corpus entries. All of these sit silent until a sweep
    happens to hit them — the lint gate names the drift in seconds.
    Each collection must stay a module-level literal: this rule reads
    them from the AST, so a computed value is itself a finding.
    """

    code = "TK8S112"
    name = "workload-fault-drift"
    summary = ("chaos workload fault kinds must agree across corpus.py, "
               "workload.py arms, and generator profile draws")

    CORPUS_FILE = f"{PKG}/chaos/corpus.py"
    ARMS_FILE = f"{PKG}/chaos/workload.py"
    GENERATOR_FILE = f"{PKG}/chaos/generator.py"

    @staticmethod
    def _assigned(tree: ast.AST, name: str) -> Optional[ast.AST]:
        for n in ast.walk(tree):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in n.targets):
                return n.value
            if (isinstance(n, ast.AnnAssign)
                    and isinstance(n.target, ast.Name)
                    and n.target.id == name and n.value is not None):
                return n.value
        return None

    @staticmethod
    def _str_elts(node: Optional[ast.AST]) -> Optional[List[str]]:
        if not isinstance(node, (ast.Tuple, ast.List)):
            return None
        out = [e.value for e in node.elts
               if isinstance(e, ast.Constant) and isinstance(e.value, str)]
        return out if len(out) == len(node.elts) else None

    @staticmethod
    def _dict_keys(node: Optional[ast.AST]) -> Optional[Dict[str, int]]:
        if not isinstance(node, ast.Dict):
            return None
        out = {k.value: k.lineno for k in node.keys
               if isinstance(k, ast.Constant) and isinstance(k.value, str)}
        return out if len(out) == len(node.keys) else None

    def check_project(self, project: Project) -> Iterable[Finding]:
        corpus = project.file(self.CORPUS_FILE)
        if corpus is None:
            return
        kinds_node = self._assigned(corpus.tree, "WORKLOAD_FAULT_KINDS")
        kinds = self._str_elts(kinds_node)
        if kinds is None or not kinds:
            yield self.finding(
                self.CORPUS_FILE, getattr(kinds_node, "lineno", 1), 0,
                "WORKLOAD_FAULT_KINDS must be a non-empty module-level "
                "tuple of string literals (this rule reads the AST)")
            return
        kind_set = set(kinds)
        defaults = self._dict_keys(
            self._assigned(corpus.tree, "WORKLOAD_DEFAULTS"))
        if defaults is None:
            yield self.finding(
                self.CORPUS_FILE, 1, 0,
                "WORKLOAD_DEFAULTS must be a module-level dict literal "
                "with string-literal keys")
        else:
            for kind in sorted(kind_set - set(defaults)):
                yield self.finding(
                    self.CORPUS_FILE, getattr(kinds_node, "lineno", 1), 0,
                    f"workload fault kind {kind!r} has no entry in "
                    f"WORKLOAD_DEFAULTS — its fields cannot round-trip "
                    f"through the spec schema")
            for kind, lineno in sorted(defaults.items()):
                if kind not in kind_set:
                    yield self.finding(
                        self.CORPUS_FILE, lineno, 0,
                        f"WORKLOAD_DEFAULTS names {kind!r} which is not "
                        f"in WORKLOAD_FAULT_KINDS — a stale or typo'd "
                        f"kind no scenario can ever draw")
        spec_keys = self._str_elts(self._assigned(corpus.tree,
                                                  "_SPEC_KEYS"))
        if spec_keys is not None and "workload" not in spec_keys:
            yield self.finding(
                self.CORPUS_FILE, 1, 0,
                "_SPEC_KEYS does not list 'workload' — generated "
                "workload faults would fail corpus validation")
        arms_ctx = project.file(self.ARMS_FILE)
        if arms_ctx is None:
            yield self.finding(
                self.CORPUS_FILE, getattr(kinds_node, "lineno", 1), 0,
                f"WORKLOAD_FAULT_KINDS is declared but {self.ARMS_FILE} "
                f"(the _ARMS dispatch) does not exist")
        else:
            arms = self._dict_keys(self._assigned(arms_ctx.tree, "_ARMS"))
            if arms is None:
                yield self.finding(
                    self.ARMS_FILE, 1, 0,
                    "_ARMS must be a module-level dict literal with "
                    "string-literal keys (the TK8S112 lint anchor)")
            else:
                for kind in sorted(kind_set - set(arms)):
                    yield self.finding(
                        self.ARMS_FILE, 1, 0,
                        f"workload fault kind {kind!r} has no arm in "
                        f"_ARMS — drawing it would KeyError at dispatch")
                for kind, lineno in sorted(arms.items()):
                    if kind not in kind_set:
                        yield self.finding(
                            self.ARMS_FILE, lineno, 0,
                            f"_ARMS implements {kind!r} which is not in "
                            f"WORKLOAD_FAULT_KINDS — dead chaos coverage "
                            f"no generator or corpus entry can reach")
        gen_ctx = project.file(self.GENERATOR_FILE)
        if gen_ctx is None:
            return
        profiles = self._assigned(gen_ctx.tree, "PROFILES")
        if not isinstance(profiles, ast.Dict):
            return
        for pval in profiles.values:
            if not isinstance(pval, ast.Dict):
                continue
            for k, v in zip(pval.keys, pval.values):
                if not (isinstance(k, ast.Constant)
                        and k.value == "workload_kinds"):
                    continue
                if not isinstance(v, (ast.Tuple, ast.List)):
                    yield self.finding(
                        self.GENERATOR_FILE, k.lineno, 0,
                        "workload_kinds must be a literal sequence of "
                        "(kind, weight) pairs")
                    continue
                for pair in v.elts:
                    name: Optional[ast.expr] = None
                    if isinstance(pair, (ast.Tuple, ast.List)) \
                            and pair.elts:
                        name = pair.elts[0]
                    if isinstance(name, ast.Constant) \
                            and isinstance(name.value, str):
                        if name.value not in kind_set:
                            yield self.finding(
                                self.GENERATOR_FILE, name.lineno, 0,
                                f"profile draws workload kind "
                                f"{name.value!r} which is not in "
                                f"WORKLOAD_FAULT_KINDS — generated "
                                f"specs would fail corpus validation")
                    else:
                        yield self.finding(
                            self.GENERATOR_FILE, pair.lineno, 0,
                            "workload_kinds entries must lead with a "
                            "string-literal kind name")


# ---------------------------------------------------------------------------
# TK8S113 — goodput vocabulary drift
# ---------------------------------------------------------------------------

@register
class GoodputVocabularyDrift(Rule):
    """The goodput category vocabulary must agree everywhere it is
    spelled: ``GOODPUT_CATEGORIES`` in utils/trace.py (the closed
    per-source vocabulary), every ``.transition("...")`` /
    ``.enter("...")`` category literal at the emitting sites
    (serve/train/operator/cli), the ``tk8s_goodput_seconds_total``
    family in the metrics CATALOG, and the Goodput-categories table of
    docs/guide/observability.md.

    History: the TK8S112 pattern applied to chip-time attribution. The
    whole point of the ledger is that categories PARTITION wall time
    against a closed vocabulary — a typo'd ``transition("dekode")``
    would raise only on the first tick that takes that path (or worse,
    a category added at a call site but not to the vocabulary would
    throw in production while every test passed), and a category
    missing from the docs table strands every dashboard keyed on it.
    Each collection must stay a module-level literal: this rule reads
    them from the AST, so a computed value is itself a finding.
    """

    code = "TK8S113"
    name = "goodput-vocabulary-drift"
    summary = ("goodput categories must agree across GOODPUT_CATEGORIES, "
               "transition() call sites, the metrics CATALOG, and the "
               "docs goodput table")

    VOCAB_FILE = f"{PKG}/utils/trace.py"
    METRICS_FILE = f"{PKG}/utils/metrics.py"
    DOCS_FILE = "docs/guide/observability.md"
    DOCS_HEADING = "### Goodput categories"
    SCOPES = (f"{PKG}/serve/", f"{PKG}/train/", f"{PKG}/operator/",
              f"{PKG}/cli/")
    # A docs goodput-table row: `source` then `category`, backticked.
    ROW_RE = re.compile(
        r"^\|\s*`([a-z]+)`\s*\|\s*`([a-z_]+)`\s*\|", re.MULTILINE)

    def _vocabulary(self, ctx: FileContext,
                    ) -> Optional[Dict[str, List[str]]]:
        """GOODPUT_CATEGORIES as {source: [category, ...]}, or None
        when it is not a pure module-level literal."""
        node = WorkloadFaultDrift._assigned(ctx.tree, "GOODPUT_CATEGORIES")
        if not isinstance(node, ast.Dict):
            return None
        out: Dict[str, List[str]] = {}
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                return None
            cats = WorkloadFaultDrift._str_elts(v)
            if cats is None:
                return None
            out[k.value] = cats
        return out

    def check_project(self, project: Project) -> Iterable[Finding]:
        vocab_ctx = project.file(self.VOCAB_FILE)
        if vocab_ctx is None:
            return
        vocab = self._vocabulary(vocab_ctx)
        if vocab is None:
            yield self.finding(
                self.VOCAB_FILE, 1, 0,
                "GOODPUT_CATEGORIES must be a module-level dict literal "
                "of string keys to string-literal tuples (this rule "
                "reads the AST)")
            return
        all_cats = {c for cats in vocab.values() for c in cats}
        # emitting sites -> vocabulary
        for rel, ctx in list(project.files.items()):
            if not rel.startswith(self.SCOPES):
                continue
            for n in ast.walk(ctx.tree):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in ("transition", "enter")
                        and n.args):
                    continue
                lit = n.args[0]
                if not (isinstance(lit, ast.Constant)
                        and isinstance(lit.value, str)):
                    continue
                if lit.value not in all_cats:
                    yield self.finding(
                        rel, n.lineno, n.col_offset,
                        f"goodput category {lit.value!r} is not in "
                        f"GOODPUT_CATEGORIES (utils/trace.py) — the "
                        f"recorder would raise the first time this "
                        f"path runs")
        # vocabulary -> metrics CATALOG (the counter family must exist
        # so the ledger's second sink cannot silently vanish)
        metrics_ctx = project.file(self.METRICS_FILE)
        if metrics_ctx is not None:
            families = WorkloadFaultDrift._dict_keys(
                WorkloadFaultDrift._assigned(metrics_ctx.tree, "CATALOG"))
            family_node = WorkloadFaultDrift._assigned(
                vocab_ctx.tree, "GOODPUT_FAMILY")
            family = (family_node.value
                      if isinstance(family_node, ast.Constant)
                      and isinstance(family_node.value, str) else None)
            if family is None:
                yield self.finding(
                    self.VOCAB_FILE, 1, 0,
                    "GOODPUT_FAMILY must be a module-level string "
                    "literal naming the chip-second counter family")
            elif families is not None and family not in families:
                yield self.finding(
                    self.VOCAB_FILE,
                    getattr(family_node, "lineno", 1), 0,
                    f"GOODPUT_FAMILY {family!r} is not declared in the "
                    f"metrics CATALOG (utils/metrics.py) — the ledger's "
                    f"metrics sink would emit an uncataloged family")
        # vocabulary <-> docs table
        docs = project.read_text(self.DOCS_FILE)
        if docs is None:
            return
        start = docs.find(self.DOCS_HEADING)
        if start < 0:
            yield self.finding(
                self.DOCS_FILE, 1, 0,
                f"no {self.DOCS_HEADING!r} section — the goodput "
                f"vocabulary must be documented as a table of "
                f"(source, category) rows")
            return
        end = docs.find("\n#", start + len(self.DOCS_HEADING))
        section = docs[start: end if end > 0 else len(docs)]
        base_line = docs.count("\n", 0, start)
        table = {(m.group(1), m.group(2)):
                 base_line + section.count("\n", 0, m.start()) + 1
                 for m in self.ROW_RE.finditer(section)}
        vocab_node = WorkloadFaultDrift._assigned(
            vocab_ctx.tree, "GOODPUT_CATEGORIES")
        for source, cats in sorted(vocab.items()):
            for cat in cats:
                if (source, cat) not in table:
                    yield self.finding(
                        self.VOCAB_FILE,
                        getattr(vocab_node, "lineno", 1), 0,
                        f"goodput category ({source!r}, {cat!r}) is "
                        f"missing from the Goodput-categories table in "
                        f"{self.DOCS_FILE}")
        for (source, cat), lineno in sorted(table.items()):
            if cat not in vocab.get(source, ()):
                yield self.finding(
                    self.DOCS_FILE, lineno, 0,
                    f"docs goodput table names ({source!r}, {cat!r}) "
                    f"which is not in GOODPUT_CATEGORIES — stale docs "
                    f"or a typo'd category")
