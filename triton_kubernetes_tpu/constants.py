"""Cross-layer pinned constants: ports and process exit codes.

These values cross the jax boundary: the rendering layer (``topology/``)
bakes them into Kubernetes manifests while the workload stack
(``train/``, ``serve/``, ``parallel/``) returns or listens on them at
runtime. The two sides must never import each other (rendering stays
importable on jax-less machines; the trainer never pulls the rendering
layer), so for eight PRs each value was *duplicated* at every use site
and pinned equal only by test convention (tests/test_topology.py,
tests/test_multihost.py).

This module is the single source of truth: it imports nothing, so every
layer can import it. Sites either import from here or keep a local
literal — in both cases ``tk8s lint`` rule TK8S104 enforces agreement
with this module at every registered duplication site, cross-file, at
lint time (docs/guide/static-analysis.md).
"""

from __future__ import annotations

# The jax.distributed coordinator port every worker dials (worker 0
# listens); rendered into the JobSet headless Service and container
# ports (topology/jobset.py), parsed by the trainer
# (train/__main__.py).
COORDINATOR_PORT = 8476

# The serving endpoint port: rendered into the Deployment/Service
# (topology/serving.py), bound by serve/server.py.
SERVE_PORT = 8000

# The router endpoint port (`tk8s route`): rendered into the router
# Deployment/Service (topology/serving.py), bound by serve/router.py's
# HTTP server. Distinct from SERVE_PORT so a router and a replica can
# share a pod network namespace during local runs.
ROUTE_PORT = 8001

# The operator endpoint port (`tk8s operate --operator-port`): rendered
# into the operator Deployment/Service (topology/serving.py), bound by
# operator/server.py. Distinct from the serving/router ports for the
# same local-run reason.
OPERATOR_PORT = 8002

# Process exit codes — bounded and machine-readable so launchers, the
# JobSet podFailurePolicy, and CI classify terminations without parsing
# logs:
#
# EXIT_CONFIG       (2)  bad/unsupported invocation: malformed CLI args
#                        or JobSet-injected distributed env
#                        (train/__main__.py).
# EXIT_ANOMALY      (4)  the loss-anomaly guard gave up after
#                        max_rollbacks consecutive trips
#                        (train/resilience.py AnomalyAbortedError).
# EXIT_UNSUPPORTED  (69) EX_UNAVAILABLE: the environment cannot host
#                        this run (no multi-host jax support) — a loud
#                        skip, never a failure (parallel/multihost.py).
# EXIT_RESUME       (75) EX_TEMPFAIL: "resume me" — a preemption-warned
#                        trainer saved an emergency checkpoint; the
#                        podFailurePolicy restarts it with --resume
#                        (train/resilience.py, topology/jobset.py).
EXIT_CONFIG = 2
EXIT_ANOMALY = 4
EXIT_UNSUPPORTED = 69
EXIT_RESUME = 75

# Serving quantization knobs (`tk8s serve --kv-dtype/--weight-dtype`).
# They cross the jax boundary the same way the ports do: the CLI parser
# registers them on jax-less machines while models/paged.py
# (init_paged_cache) and train/precision.py (quantize_for_decode)
# validate them at runtime — one tuple here keeps argparse and the
# engine from ever drifting. "fp8" (float8_e4m3fn) registers on every
# machine but resolves at engine init: where the runtime jax lacks the
# dtype it raises ops.quantization.Fp8UnavailableError — a loud typed
# failure, never a silent fallback.
KV_DTYPES = ("auto", "bf16", "int8", "fp8")
WEIGHT_DTYPES = ("auto", "int8", "fp8")
# Arithmetic dtype for the big serving matmuls (`tk8s serve
# --matmul-dtype`). Storage quantization (WEIGHT_DTYPES) says how the
# weights LIVE; this knob says how they CONTRACT. "f32" is the pinned
# reference (dequantize, then full-precision einsum); "int8"/"fp8" run
# the quantized-arithmetic path (ops.quantization.quantized_einsum:
# low-precision dot, f32/int32 accumulate, scales folded into the
# epilogue) and require the matching --weight-dtype; "auto" resolves at
# engine init — quantized arithmetic on TPU MXUs when the weights are
# quantized, the bitwise-f32 reference elsewhere.
MATMUL_DTYPES = ("auto", "f32", "int8", "fp8")
