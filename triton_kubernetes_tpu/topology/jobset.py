"""JobSet + headless-service rendering for multi-host JAX workloads.

The reference's per-VM bootstrap was a bash template baked into cloud-init
(install_rancher_agent.sh.tpl). The TPU-native equivalent is declarative: a
headless Service gives every worker a stable DNS name, and a JobSet-style
indexed Job provides ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` plus the
``jax.distributed`` coordinator address (worker 0), which is all
``jax.distributed.initialize()`` needs over DCN. Within a slice, collectives
ride ICI with no Kubernetes networking involvement at all — hence hostNetwork
for the coordinator port only.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .labels import selector_for_slice
from .slices import SliceSpec

# Single-sourced from the dependency-free constants module (rendering
# still never imports the jax-loaded train package). A preemption-warned
# worker saves an emergency checkpoint and exits RESUME_EXIT_CODE; the
# Job's podFailurePolicy recreates the pod instead of failing the job.
# Lint rule TK8S104 re-checks every duplication site cross-file.
from ..constants import COORDINATOR_PORT
from ..constants import EXIT_RESUME as RESUME_EXIT_CODE


def render_headless_service(name: str, namespace: str = "default") -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "clusterIP": "None",  # headless: DNS per pod
            "selector": {"jobset.tk8s.io/name": name},
            "ports": [{"name": "jax-coordinator", "port": COORDINATOR_PORT}],
        },
    }


def resize_jobset(
    name: str,
    spec: SliceSpec,
    workers: int,
    *,
    image: str,
    command: List[str],
    namespace: str = "default",
    env: Optional[Dict[str, str]] = None,
    trace_dir: Optional[str] = None,
    slice_id: Optional[str] = None,
) -> Dict[str, Any]:
    """The Job manifest for an elastically-resized train fleet: the same
    render as :func:`render_jobset` with ``completions`` forced to
    ``workers`` — hostnames, coordinator address and ``NUM_TPU_WORKERS``
    all re-derive from the new count, so the restarted workers negotiate
    their mesh (``--elastic``) against a consistent world size. The
    operator's train-fleet actuator
    (:func:`~..operator.trainfleet.jobset_actuator`) renders through
    this; applying it replaces the old Job (indexed Jobs have immutable
    completions, so a resize IS a replace — the checkpoint carries the
    progress across)."""
    if workers < 1:
        raise ValueError(f"resize_jobset: workers={workers} must be >= 1")
    return render_jobset(
        name, spec, slice_id if slice_id is not None else f"{name}-elastic",
        image, command, namespace=namespace, env=env,
        completions=workers, trace_dir=trace_dir)


def render_jobset(
    name: str,
    spec: SliceSpec,
    slice_id: str,
    image: str,
    command: List[str],
    namespace: str = "default",
    env: Optional[Dict[str, str]] = None,
    completions: Optional[int] = None,
    trace_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """An indexed-Job manifest: one pod per TPU host of the slice.

    ``trace_dir`` (e.g. ``/var/log/tk8s``) turns on the trainer's
    flight recorder: the command gains ``--trace-jsonl`` pointing into
    a hostPath volume mounted there, so every rank's ``train.*`` spans
    and goodput ledger survive the pod — a preempted or crashed
    worker's trace is exactly the one worth collecting for
    ``tk8s trace merge`` / ``tk8s goodput report``.
    """
    n = completions if completions is not None else spec.num_hosts
    hostnames = ",".join(
        f"{name}-{i}.{name}.{namespace}.svc" for i in range(n))
    coordinator = f"{name}-0.{name}.{namespace}.svc:{COORDINATOR_PORT}"
    base_env = {
        "TPU_WORKER_HOSTNAMES": hostnames,
        "JAX_COORDINATOR_ADDRESS": coordinator,
        "TPU_TOPOLOGY": spec.topology,
        "TPU_CHIPS_PER_HOST": str(spec.chips_per_host),
        "NUM_TPU_WORKERS": str(n),
    }
    base_env.update(env or {})
    command = list(command)
    if trace_dir is not None:
        # One file per rank: the trainer suffixes .rank{N} itself from
        # jax.process_index(), so every pod can share the same path.
        command += ["--trace-jsonl", f"{trace_dir}/trace.jsonl"]
    container = {
        "name": "worker",
        "image": image,
        "command": command,
        "env": (
            [{"name": k, "value": v} for k, v in sorted(base_env.items())]
            + [{
                # Worker id comes from the indexed-Job completion index.
                "name": "TPU_WORKER_ID",
                "valueFrom": {"fieldRef": {
                    "fieldPath": "metadata.annotations['batch.kubernetes.io/job-completion-index']"}},
            }]
        ),
        "ports": [{"containerPort": COORDINATOR_PORT}],
        "resources": {"limits": {"google.com/tpu": str(spec.chips_per_host)}},
    }
    pod_extra: Dict[str, Any] = {}
    if trace_dir is not None:
        # hostPath, not emptyDir: an emptyDir dies with the pod, and the
        # pod that died is the one whose ledger the postmortem needs.
        container["volumeMounts"] = [
            {"name": "tk8s-trace", "mountPath": trace_dir}]
        pod_extra["volumes"] = [
            {"name": "tk8s-trace",
             "hostPath": {"path": trace_dir,
                          "type": "DirectoryOrCreate"}}]
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {"jobset.tk8s.io/name": name,
                       "jobset.tk8s.io/slice-id": slice_id},
        },
        "spec": {
            "completions": n,
            "parallelism": n,
            "completionMode": "Indexed",
            # Real failures still fail fast (the FailJob rule below is the
            # old backoffLimit: 0 behavior); what must NOT count as
            # failure is the resilience protocol: a preemption-warned
            # worker exiting RESUME_EXIT_CODE after its emergency
            # checkpoint, or the pod being disrupted outright (node
            # drain, spot reclaim) — those recreate the pod, which
            # resumes from the newest verified checkpoint (the command
            # must pass --resume; docs/guide/fault-tolerance.md §6).
            "backoffLimit": 0,
            "podFailurePolicy": {"rules": [
                {"action": "Ignore",
                 "onExitCodes": {"containerName": "worker",
                                 "operator": "In",
                                 "values": [RESUME_EXIT_CODE]}},
                {"action": "Ignore",
                 "onPodConditions": [{"type": "DisruptionTarget",
                                      "status": "True"}]},
                {"action": "FailJob",
                 "onExitCodes": {"containerName": "worker",
                                 "operator": "NotIn",
                                 "values": [RESUME_EXIT_CODE]}},
            ]},
            "template": {
                "metadata": {"labels": {"jobset.tk8s.io/name": name}},
                "spec": {
                    "subdomain": name,  # pairs with the headless service
                    "restartPolicy": "Never",
                    "nodeSelector": selector_for_slice(spec, slice_id),
                    "containers": [container],
                    **pod_extra,
                },
            },
        },
    }
