"""Kubernetes manifest schema validation for every rendered object.

Round-1/2 verdicts flagged that manifest renders were only shape-tested —
a bad label value or a selector/template mismatch would surface on a user's
cluster, not in CI. This module validates rendered manifests against
distilled JSON Schemas of the K8s object model (metadata + DNS-1123 / label
grammar, workload selector-template agreement, container contract, port
ranges) plus the JobSet CRD shape, and the in-process
:class:`~..executor.cloudsim.CloudSimulator` runs it on every
``apply_manifest`` — so the simulator rejects what a real API server
would, like a ``kubectl apply --dry-run=server``.

The schemas are a structural subset of the upstream OpenAPI (no network in
CI, and the full OpenAPI is megabytes of mostly-optional fields); unknown
kinds (CRDs like velero.io Restore) validate against the generic object
schema only.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jsonschema

class ManifestError(ValueError):
    pass


# --- grammar fragments (K8s validation rules) ---------------------------
DNS1123_LABEL = r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$"          # names, ≤63
DNS1123_SUBDOMAIN = r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$"     # ns/names, ≤253
LABEL_VALUE = r"^(|[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?)$"  # ≤63
# Label/annotation key: optional dns-subdomain prefix + "/" + name part.
LABEL_KEY = (r"^([a-z0-9]([-a-z0-9.]*[a-z0-9])?/)?"
             r"[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")

_LABELS = {
    "type": "object",
    "propertyNames": {"pattern": LABEL_KEY, "maxLength": 317},
    "additionalProperties": {"type": "string", "pattern": LABEL_VALUE,
                             "maxLength": 63},
}

_METADATA = {
    "type": "object",
    "required": ["name"],
    "properties": {
        "name": {"type": "string", "pattern": DNS1123_SUBDOMAIN,
                 "maxLength": 253},
        "namespace": {"type": "string", "pattern": DNS1123_LABEL,
                      "maxLength": 63},
        "labels": _LABELS,
        "annotations": {"type": "object",
                        "propertyNames": {"pattern": LABEL_KEY}},
    },
}

_CONTAINER = {
    "type": "object",
    "required": ["name", "image"],
    "properties": {
        "name": {"type": "string", "pattern": DNS1123_LABEL, "maxLength": 63},
        "image": {"type": "string", "minLength": 1},
        "command": {"type": "array", "items": {"type": "string"}},
        "args": {"type": "array", "items": {"type": "string"}},
        "env": {"type": "array", "items": {
            "type": "object", "required": ["name"],
            "properties": {"name": {"type": "string", "minLength": 1}},
        }},
        "ports": {"type": "array", "items": {
            "type": "object", "required": ["containerPort"],
            "properties": {"containerPort": {
                "type": "integer", "minimum": 1, "maximum": 65535}},
        }},
        "resources": {"type": "object", "properties": {
            "limits": {"type": "object"},
            "requests": {"type": "object"},
        }},
    },
}

_POD_SPEC = {
    "type": "object",
    "required": ["containers"],
    "properties": {
        "containers": {"type": "array", "minItems": 1, "items": _CONTAINER},
        "initContainers": {"type": "array", "items": _CONTAINER},
        "nodeSelector": _LABELS,
        "hostNetwork": {"type": "boolean"},
        "subdomain": {"type": "string", "pattern": DNS1123_LABEL},
    },
}

_POD_TEMPLATE = {
    "type": "object",
    "required": ["spec"],
    "properties": {
        "metadata": {"type": "object",
                     "properties": {"labels": _LABELS}},
        "spec": _POD_SPEC,
    },
}

_SELECTOR = {
    "type": "object",
    "required": ["matchLabels"],
    "properties": {"matchLabels": _LABELS},
}

_GENERIC = {
    "type": "object",
    "required": ["apiVersion", "kind", "metadata"],
    "properties": {
        "apiVersion": {"type": "string", "minLength": 1},
        "kind": {"type": "string", "minLength": 1},
        "metadata": _METADATA,
    },
}


def _workload(extra_spec: Dict[str, Any],
              required: List[str]) -> Dict[str, Any]:
    return {
        **_GENERIC,
        "required": _GENERIC["required"] + ["spec"],
        "properties": {
            **_GENERIC["properties"],
            "spec": {
                "type": "object",
                "required": required,
                "properties": {
                    "selector": _SELECTOR,
                    "template": _POD_TEMPLATE,
                    **extra_spec,
                },
            },
        },
    }


SCHEMAS: Dict[str, Dict[str, Any]] = {
    "Deployment": _workload(
        {"replicas": {"type": "integer", "minimum": 0}},
        ["selector", "template"]),
    "DaemonSet": _workload({}, ["selector", "template"]),
    "Job": {
        **_GENERIC,
        "required": _GENERIC["required"] + ["spec"],
        "properties": {
            **_GENERIC["properties"],
            "spec": {
                "type": "object",
                "required": ["template"],
                "properties": {
                    "template": _POD_TEMPLATE,
                    "completions": {"type": "integer", "minimum": 0},
                    "parallelism": {"type": "integer", "minimum": 0},
                    "completionMode": {"enum": ["NonIndexed", "Indexed"]},
                    "backoffLimit": {"type": "integer", "minimum": 0},
                    "podFailurePolicy": {
                        "type": "object",
                        "required": ["rules"],
                        "properties": {"rules": {
                            "type": "array",
                            "items": {
                                "type": "object",
                                "required": ["action"],
                                "properties": {
                                    "action": {"enum": [
                                        "Ignore", "FailJob", "Count",
                                        "FailIndex"]},
                                    "onExitCodes": {
                                        "type": "object",
                                        "required": ["operator", "values"],
                                        "properties": {
                                            "containerName": {
                                                "type": "string"},
                                            "operator": {"enum": [
                                                "In", "NotIn"]},
                                            "values": {
                                                "type": "array",
                                                "items": {
                                                    "type": "integer"}},
                                        },
                                    },
                                    "onPodConditions": {
                                        "type": "array",
                                        "items": {
                                            "type": "object",
                                            "required": ["type"],
                                        },
                                    },
                                },
                            },
                        }},
                    },
                },
            },
        },
    },
    "Service": {
        **_GENERIC,
        "required": _GENERIC["required"] + ["spec"],
        "properties": {
            **_GENERIC["properties"],
            "spec": {
                "type": "object",
                "properties": {
                    "selector": _LABELS,
                    "clusterIP": {"type": "string"},
                    "type": {"enum": ["ClusterIP", "NodePort",
                                      "LoadBalancer", "ExternalName"]},
                    "ports": {"type": "array", "items": {
                        "type": "object",
                        "required": ["port"],
                        "properties": {
                            "port": {"type": "integer",
                                     "minimum": 1, "maximum": 65535},
                            "targetPort": {"type": ["integer", "string"]},
                            "nodePort": {"type": "integer",
                                         "minimum": 30000, "maximum": 32767},
                        },
                    }},
                },
            },
        },
    },
    # JobSet CRD (jobset.x-k8s.io): the multi-host TPU workload shape.
    "JobSet": {
        **_GENERIC,
        "required": _GENERIC["required"] + ["spec"],
        "properties": {
            **_GENERIC["properties"],
            "spec": {
                "type": "object",
                "required": ["replicatedJobs"],
                "properties": {
                    "replicatedJobs": {
                        "type": "array", "minItems": 1,
                        "items": {
                            "type": "object",
                            "required": ["name", "template"],
                            "properties": {
                                "name": {"type": "string",
                                         "pattern": DNS1123_LABEL},
                                "replicas": {"type": "integer", "minimum": 1},
                                "template": {
                                    "type": "object",
                                    "required": ["spec"],
                                    "properties": {"spec": {
                                        "type": "object",
                                        "required": ["template"],
                                        "properties": {
                                            "template": _POD_TEMPLATE},
                                    }},
                                },
                            },
                        },
                    },
                },
            },
        },
    },
}


def _check_selector_matches_template(manifest: Dict[str, Any]) -> None:
    """Workload invariant the schema alone can't express: every
    selector.matchLabels pair must appear in the pod template's labels
    (the API server rejects the object otherwise)."""
    spec = manifest.get("spec", {})
    selector = (spec.get("selector") or {}).get("matchLabels") or {}
    if not selector:
        return
    tmpl_labels = ((spec.get("template") or {}).get("metadata") or {}
                   ).get("labels") or {}
    for k, v in selector.items():
        if tmpl_labels.get(k) != v:
            raise ManifestError(
                f"{manifest.get('kind')}/{manifest['metadata'].get('name')}: "
                f"selector {k}={v} not present in template labels "
                f"{tmpl_labels}")


def _check_unique_container_names(manifest: Dict[str, Any]) -> None:
    def containers_of(pod_spec: Dict[str, Any]) -> List[Dict[str, Any]]:
        return list(pod_spec.get("containers") or []) + \
            list(pod_spec.get("initContainers") or [])

    pods: List[Dict[str, Any]] = []
    spec = manifest.get("spec", {})
    if "template" in spec and isinstance(spec["template"], dict):
        pods.append((spec["template"].get("spec") or {}))
    for rj in spec.get("replicatedJobs") or []:
        pods.append(((rj.get("template") or {}).get("spec") or {})
                    .get("template", {}).get("spec", {}))
    for pod in pods:
        names = [c.get("name") for c in containers_of(pod)]
        if len(names) != len(set(names)):
            raise ManifestError(
                f"{manifest.get('kind')}/{manifest['metadata'].get('name')}: "
                f"duplicate container names {names}")


# Precompiled once: the simulator validates on every apply, and
# jsonschema.validate would re-check and re-build the validator per call.
_VALIDATORS = {kind: jsonschema.Draft202012Validator(schema)
               for kind, schema in SCHEMAS.items()}
_GENERIC_VALIDATOR = jsonschema.Draft202012Validator(_GENERIC)


def validate_manifest(manifest: Dict[str, Any]) -> None:
    """Raise :class:`ManifestError` when a rendered object would be
    rejected by a Kubernetes API server (structural subset)."""
    if not isinstance(manifest, dict):
        raise ManifestError(f"manifest must be a mapping, got {manifest!r}")
    kind = manifest.get("kind")
    validator = _VALIDATORS.get(kind, _GENERIC_VALIDATOR)
    try:
        validator.validate(manifest)
    except jsonschema.ValidationError as e:
        path = ".".join(str(p) for p in e.absolute_path) or "<root>"
        raise ManifestError(
            f"{kind}/{(manifest.get('metadata') or {}).get('name')}: "
            f"{path}: {e.message}") from e
    _check_selector_matches_template(manifest)
    _check_unique_container_names(manifest)
