"""Manifest render CLI: the seam between the Terraform HCL modules and the
in-process render code.

The HCL modules under ``terraform/modules/`` provision cloud resources with
real providers, but their Kubernetes payloads (TPU runtime DaemonSets,
device plugin, slice-health probe, JobSet + headless service) are rendered
by THIS command and piped to ``kubectl apply -f -`` — one render
implementation for both execution paths, so the in-process simulator tests
pin exactly what the real path applies.

Usage:
    python -m triton_kubernetes_tpu.topology daemonsets \
        --accelerator v5p-64 [--topology 4x4x4] [--image IMG]
    python -m triton_kubernetes_tpu.topology jobset \
        --name train --accelerator v5p-64 --slice-id cluster-pool \
        [--topology TxTxT] [--image IMG] [--namespace NS] \
        [--env K=V ...] [--command CMD ARGS...]

Output: a Kubernetes List object (JSON) on stdout — kubectl-applyable.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List

from .daemonsets import (
    render_slice_health_daemonset,
    render_tpu_device_plugin,
    render_tpu_runtime_daemonset,
)
from .jobset import render_headless_service, render_jobset
from .slices import SliceSpec


def _as_list(items: List[Dict[str, Any]]) -> Dict[str, Any]:
    return {"apiVersion": "v1", "kind": "List", "items": items}


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="triton_kubernetes_tpu.topology")
    sub = parser.add_subparsers(dest="cmd", required=True)

    ds = sub.add_parser("daemonsets", help="TPU host-software DaemonSets")
    ds.add_argument("--accelerator", required=True)
    ds.add_argument("--topology", default="")
    ds.add_argument("--image", default="")

    js = sub.add_parser("jobset", help="multi-host JAX workload")
    js.add_argument("--name", required=True)
    js.add_argument("--accelerator", required=True)
    js.add_argument("--slice-id", required=True)
    js.add_argument("--topology", default="")
    js.add_argument("--image", default="tk8s/jax-tpu-runtime:0.1.0")
    js.add_argument("--namespace", default="default")
    js.add_argument("--env", action="append", default=[],
                    metavar="K=V")
    js.add_argument("--command", nargs=argparse.REMAINDER,
                    default=["python", "-m", "triton_kubernetes_tpu.train"])

    args = parser.parse_args(argv)
    spec = SliceSpec.from_accelerator(args.accelerator, args.topology or None)

    if args.cmd == "daemonsets":
        kwargs = {"image": args.image} if args.image else {}
        items = [render_tpu_runtime_daemonset(spec, **kwargs),
                 render_tpu_device_plugin(spec),
                 render_slice_health_daemonset(spec, **kwargs)]
    else:
        env = {}
        for kv in args.env:
            if "=" not in kv:
                parser.error(f"--env expects K=V, got {kv!r}")
            k, v = kv.split("=", 1)
            env[k] = v
        command = args.command or ["python", "-m", "triton_kubernetes_tpu.train"]
        items = [render_headless_service(args.name, args.namespace),
                 render_jobset(args.name, spec, args.slice_id,
                               image=args.image, command=command,
                               namespace=args.namespace, env=env)]

    json.dump(_as_list(items), sys.stdout, indent=2, sort_keys=True)
    sys.stdout.write("\n")
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    # Script mode only: a downstream `| head` closing early is a quiet
    # exit, not a traceback. The devnull dup2 prevents the interpreter's
    # shutdown flush from re-raising; in-process callers of main() keep
    # their stdout and see the exception instead.
    try:
        sys.exit(main())
    except BrokenPipeError:
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
