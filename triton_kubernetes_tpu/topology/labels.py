"""Kubernetes node-label scheme carrying ICI mesh coordinates.

The north-star requirement (BASELINE.json): "surface ICI mesh coordinates as
Kubernetes node labels so multi-host JAX jobs schedule slice-contiguously".
Two label families land on every TPU node:

* the standard GKE selectors (``cloud.google.com/gke-tpu-accelerator``,
  ``cloud.google.com/gke-tpu-topology``) that TPU-aware schedulers and
  device plugins already understand;
* our own ``tpu.tk8s.io/*`` labels: slice id, worker id, and the host's ICI
  coordinates (``ici-x``/``ici-y``/``ici-z``) so placement policies and
  debugging tools can reason about physical adjacency without provider APIs.

The reference's closest analog is the Rancher host-role labels
(``rancherHostLabelsConfig``, create/node.go: worker/etcd/control) — the same
"make topology visible to the scheduler as labels" move, one layer down.
"""

from __future__ import annotations

from typing import Dict, List

from .slices import SliceSpec

LABEL_PREFIX = "tpu.tk8s.io"
GKE_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"

AXIS_NAMES = ("x", "y", "z")


def host_labels_for_slice(spec: SliceSpec, slice_id: str) -> List[Dict[str, str]]:
    """Per-host label dicts for one slice, in TPU_WORKER_ID order."""
    out: List[Dict[str, str]] = []
    for worker_id, coord in enumerate(spec.host_coordinates()):
        labels = {
            GKE_ACCELERATOR_LABEL: spec.generation.gke_accelerator,
            GKE_TOPOLOGY_LABEL: spec.topology,
            f"{LABEL_PREFIX}/generation": spec.generation.name,
            f"{LABEL_PREFIX}/slice-id": slice_id,
            f"{LABEL_PREFIX}/worker-id": str(worker_id),
            f"{LABEL_PREFIX}/num-workers": str(spec.num_hosts),
            f"{LABEL_PREFIX}/chips-per-host": str(spec.chips_per_host),
        }
        for axis, c in zip(AXIS_NAMES, coord):
            labels[f"{LABEL_PREFIX}/ici-{axis}"] = str(c)
        out.append(labels)
    return out


def verify_slice_labels(node_labels: List[Dict[str, str]],
                        spec: SliceSpec, slice_id: str) -> List[str]:
    """Check a pool's per-host labels form the complete, correctly-ordered
    ICI coordinate set for ``spec`` — the post-repair invariant: a replaced
    slice whose coordinates are missing or shuffled would let a "slice-
    contiguous" placement silently straddle physical hosts. Returns a list
    of human-readable problems; empty means the labels are exactly what
    ``host_labels_for_slice`` would emit."""
    expected = host_labels_for_slice(spec, slice_id)
    problems: List[str] = []
    if len(node_labels) != len(expected):
        problems.append(
            f"slice {slice_id}: {len(node_labels)} labeled hosts, "
            f"expected {len(expected)}")
        return problems
    for worker_id, (got, want) in enumerate(zip(node_labels, expected)):
        for key, value in want.items():
            if got.get(key) != value:
                problems.append(
                    f"slice {slice_id} worker {worker_id}: label {key}="
                    f"{got.get(key)!r}, expected {value!r}")
    return problems


def selector_for_slice(spec: SliceSpec, slice_id: str) -> Dict[str, str]:
    """nodeSelector that pins a workload to one slice — the guarantee that a
    64-chip job never straddles slices (SURVEY.md §7 "hard parts")."""
    return {
        GKE_ACCELERATOR_LABEL: spec.generation.gke_accelerator,
        GKE_TOPOLOGY_LABEL: spec.topology,
        f"{LABEL_PREFIX}/slice-id": slice_id,
    }
