"""TPU generation table and slice-shape arithmetic.

Accelerator names follow the ``<gen>-<chips>`` convention used throughout
BASELINE.md (v5e-8, v5p-64, v5p-256): the number is the **chip count** of the
slice. Peak-FLOPs numbers are the public per-chip bf16 figures and drive MFU
accounting in ``train/mfu.py``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class TpuGeneration:
    name: str
    chips_per_host: int  # multi-host slices: chips per worker VM
    peak_bf16_tflops: float
    hbm_gb_per_chip: float
    ici_rank: int  # 2 => 2D torus (v5e/v6e), 3 => 3D torus (v4/v5p)
    gke_accelerator: str  # GKE nodeSelector accelerator value
    machine_type: str  # GKE TPU machine type for multi-host pools
    max_chips: int
    # Single-host machine types by chip count (GKE offers e.g.
    # ct5lp-hightpu-8t: all 8 v5e chips on ONE host — no DCN hop, so a
    # v5e-8 slice is a 1-node pool, not 2 nodes of 4).
    single_host_types: Tuple[Tuple[int, str], ...] = ()


TPU_GENERATIONS: Dict[str, TpuGeneration] = {
    "v4": TpuGeneration("v4", 4, 275.0, 32.0, 3, "tpu-v4-podslice", "ct4p-hightpu-4t", 4096),
    "v5e": TpuGeneration("v5e", 4, 197.0, 16.0, 2, "tpu-v5-lite-podslice", "ct5lp-hightpu-4t", 256,
                         ((1, "ct5lp-hightpu-1t"), (4, "ct5lp-hightpu-4t"),
                          (8, "ct5lp-hightpu-8t"))),
    "v5p": TpuGeneration("v5p", 4, 459.0, 95.0, 3, "tpu-v5p-slice", "ct5p-hightpu-4t", 8192),
    "v6e": TpuGeneration("v6e", 4, 918.0, 32.0, 2, "tpu-v6e-slice", "ct6e-standard-4t", 256,
                         ((1, "ct6e-standard-1t"), (4, "ct6e-standard-4t"),
                          (8, "ct6e-standard-8t"))),
}


def peak_bf16_tflops_for_kind(device_kind: str) -> float:
    """Per-chip bf16 peak for a jax ``device_kind`` string (e.g. 'TPU v5
    lite', 'TPU v5p chip', 'TPU v4'). Returns 0.0 when unrecognized so MFU
    reporting can be skipped rather than wrong."""
    kind = device_kind.lower()
    compact = kind.replace(" ", "").replace("tpu", "")
    for gen in TPU_GENERATIONS.values():
        if gen.name in compact:
            return gen.peak_bf16_tflops
    if "v5 lite" in kind or "v5e" in kind:
        return TPU_GENERATIONS["v5e"].peak_bf16_tflops
    if "v5p" in kind or "v5" in kind:
        return TPU_GENERATIONS["v5p"].peak_bf16_tflops
    if "v4" in kind:
        return TPU_GENERATIONS["v4"].peak_bf16_tflops
    if "v6" in kind:
        return TPU_GENERATIONS["v6e"].peak_bf16_tflops
    return 0.0


def parse_accelerator(name: str) -> Tuple[TpuGeneration, int]:
    """``"v5p-64"`` -> (v5p generation, 64 chips)."""
    gen_name, sep, count = name.partition("-")
    if gen_name not in TPU_GENERATIONS:
        raise ValueError(
            f"unknown TPU generation {gen_name!r}; know {sorted(TPU_GENERATIONS)}")
    if not sep or not count.isdigit() or int(count) < 1:
        raise ValueError(f"accelerator must be <gen>-<chips>, got {name!r}")
    gen = TPU_GENERATIONS[gen_name]
    chips = int(count)
    if chips > gen.max_chips:
        raise ValueError(f"{gen_name} slices max out at {gen.max_chips} chips")
    # Slices are host-aligned: sub-host slices exist only as 1- or 2-chip
    # configs; anything larger must be a whole number of hosts, or node
    # count / worker ids / coordinate labels would disagree with the
    # physical slice.
    if chips > 2 and chips % gen.chips_per_host != 0:
        raise ValueError(
            f"{name}: chip count must be 1, 2, or a multiple of "
            f"{gen.chips_per_host} (chips/host on {gen_name})")
    return gen, chips


def _balanced_factors(n: int, rank: int) -> List[int]:
    """Near-balanced factorization of n into `rank` factors, largest last —
    the shape XLA's ICI mesh wants (keep dims even where possible)."""
    dims = [1] * rank
    remaining = n
    # Greedy: repeatedly pull the smallest prime factor into the smallest dim.
    factors: List[int] = []
    d = 2
    while d * d <= remaining:
        while remaining % d == 0:
            factors.append(d)
            remaining //= d
        d += 1
    if remaining > 1:
        factors.append(remaining)
    for f in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= f
    return sorted(dims)


def default_topology(gen: TpuGeneration, chips: int) -> str:
    """Default ICI topology string for a slice, e.g. ``"4x4x4"`` (v5p-64) or
    ``"2x4"`` (v5e-8). Matches GKE's ``tpu-topology`` placement format."""
    if chips == 1:
        return "x".join(["1"] * gen.ici_rank)
    dims = _balanced_factors(chips, gen.ici_rank)
    return "x".join(str(d) for d in dims)


@dataclass(frozen=True)
class SliceSpec:
    """A fully-resolved slice: generation + chip count + topology."""

    generation: TpuGeneration
    chips: int
    topology: str

    @staticmethod
    def from_accelerator(name: str, topology: str | None = None) -> "SliceSpec":
        gen, chips = parse_accelerator(name)
        topo = topology or default_topology(gen, chips)
        dims = [int(d) for d in topo.split("x")]
        prod = 1
        for d in dims:
            prod *= d
        if prod != chips:
            raise ValueError(
                f"topology {topo} has {prod} chips but accelerator says {chips}")
        return SliceSpec(gen, chips, topo)

    @property
    def dims(self) -> List[int]:
        return [int(d) for d in self.topology.split("x")]

    @property
    def _single_host_type(self) -> str | None:
        """Machine type when this exact chip count fits one host, else
        None — the ONE lookup num_hosts and machine_type both key off, so
        host count and machine type can never disagree."""
        for c, mt in self.generation.single_host_types:
            if c == self.chips:
                return mt
        return None

    @property
    def num_hosts(self) -> int:
        # Prefer a single-host machine when the generation offers one for
        # this chip count (e.g. v5e-8 on ct5lp-hightpu-8t): every hop stays
        # on-board, and host count matches what the GKE API will accept for
        # that machine type (round-2 verdict weak #6).
        if self._single_host_type is not None:
            return 1
        return max(1, self.chips // self.generation.chips_per_host)

    @property
    def chips_per_host(self) -> int:
        """Chips each worker VM owns — per-slice, not per-generation (a
        single-host v5e-8 host owns all 8; a sub-host v5p-2 host is
        *granted* 2 even though the machine has 4 — the device plugin
        gates enumeration to the granted count, so health asserts and
        google.com/tpu limits use this value consistently)."""
        return self.chips // self.num_hosts

    @property
    def machine_type(self) -> str:
        return self._single_host_type or self.generation.machine_type

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    @property
    def peak_bf16_tflops(self) -> float:
        return self.chips * self.generation.peak_bf16_tflops

    def chip_coordinates(self) -> List[Tuple[int, ...]]:
        """All chip coordinates in the ICI torus, x-major (matches the
        TPU_WORKER_ID host-enumeration order)."""
        dims = self.dims
        coords: List[Tuple[int, ...]] = []

        def rec(prefix: Tuple[int, ...], rest: List[int]) -> None:
            if not rest:
                coords.append(prefix)
                return
            for i in range(rest[0]):
                rec(prefix + (i,), rest[1:])

        # Iterate last dim fastest so consecutive chips are ICI neighbors.
        rec((), dims)
        return coords

    def host_coordinates(self) -> List[Tuple[int, ...]]:
        """One coordinate per host: the coordinate of its first chip.
        Hosts own ``chips_per_host`` consecutive chips in enumeration order."""
        chips = self.chip_coordinates()
        step = max(1, self.chips_per_host)
        return [chips[i] for i in range(0, len(chips), step)]
