"""TPU slice topology: generations, ICI meshes, node labels, JobSets.

No reference analog — this is the layer the TPU fork adds (SURVEY.md §2.5:
"slice-contiguous scheduling ... has no reference analog at all"). It owns:

* the TPU generation table (v4/v5e/v5p/v6e: chips/host, peak TFLOPs, HBM,
  ICI torus rank) and slice-shape arithmetic;
* the node-label scheme that surfaces ICI mesh coordinates to the Kubernetes
  scheduler so multi-host JAX jobs land slice-contiguously;
* JobSet + headless-service rendering for ``jax.distributed`` initialization.
"""

from .slices import (
    TPU_GENERATIONS,
    SliceSpec,
    TpuGeneration,
    default_topology,
    parse_accelerator,
)
from .labels import (
    GKE_ACCELERATOR_LABEL,
    GKE_TOPOLOGY_LABEL,
    LABEL_PREFIX,
    host_labels_for_slice,
    selector_for_slice,
    verify_slice_labels,
)
from .jobset import render_headless_service, render_jobset, resize_jobset
from .serving import (
    render_disaggregated_deployments,
    render_operator_deployment,
    render_operator_service,
    render_router_deployment,
    render_router_service,
    render_serving_deployment,
    render_serving_service,
)

__all__ = [
    "GKE_ACCELERATOR_LABEL",
    "GKE_TOPOLOGY_LABEL",
    "LABEL_PREFIX",
    "SliceSpec",
    "TPU_GENERATIONS",
    "TpuGeneration",
    "default_topology",
    "host_labels_for_slice",
    "parse_accelerator",
    "render_disaggregated_deployments",
    "render_headless_service",
    "render_jobset",
    "resize_jobset",
    "render_operator_deployment",
    "render_operator_service",
    "render_router_deployment",
    "render_router_service",
    "render_serving_deployment",
    "render_serving_service",
    "selector_for_slice",
    "verify_slice_labels",
]
