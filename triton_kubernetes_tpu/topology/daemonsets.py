"""DaemonSet manifests for TPU node pools: libtpu/JAX runtime + device plugin
+ slice-health probe.

This replaces the reference's per-VM bootstrap role (Packer-baked images +
install_docker_rancher.sh.tpl / nvidia-era device plumbing, SURVEY.md §2.5
table): on TPU node pools, host software is declarative — a DaemonSet that
ships libtpu + a pinned JAX/XLA runtime, the TPU device plugin that exposes
``google.com/tpu`` resources, and a health DaemonSet whose readiness gate is
"libtpu can enumerate all local chips" (the slice-health probe SURVEY.md §5
calls for).
"""

from __future__ import annotations

from typing import Any, Dict

from .labels import GKE_ACCELERATOR_LABEL
from .slices import SliceSpec

DEFAULT_RUNTIME_IMAGE = "tk8s/jax-tpu-runtime:0.1.0"
DEFAULT_DEVICE_PLUGIN_IMAGE = "tk8s/tpu-device-plugin:0.1.0"


def _tpu_node_selector(spec: SliceSpec) -> Dict[str, str]:
    return {GKE_ACCELERATOR_LABEL: spec.generation.gke_accelerator}


def render_tpu_runtime_daemonset(spec: SliceSpec,
                                 image: str = DEFAULT_RUNTIME_IMAGE,
                                 namespace: str = "kube-system") -> Dict[str, Any]:
    """libtpu + JAX/XLA runtime DaemonSet (nvidia-docker analog, TPU-native)."""
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": "tpu-jax-runtime", "namespace": namespace},
        "spec": {
            "selector": {"matchLabels": {"app": "tpu-jax-runtime"}},
            "template": {
                "metadata": {"labels": {"app": "tpu-jax-runtime"}},
                "spec": {
                    "nodeSelector": _tpu_node_selector(spec),
                    "hostNetwork": True,  # ICI/DCN init needs host networking
                    "containers": [{
                        "name": "runtime",
                        "image": image,
                        "securityContext": {"privileged": True},
                        "volumeMounts": [
                            {"name": "libtpu", "mountPath": "/lib/libtpu"},
                            {"name": "dev", "mountPath": "/dev"},
                        ],
                        "env": [
                            {"name": "TPU_CHIPS_PER_HOST",
                             "value": str(spec.generation.chips_per_host)},
                        ],
                    }],
                    "volumes": [
                        {"name": "libtpu", "hostPath": {"path": "/lib/libtpu"}},
                        {"name": "dev", "hostPath": {"path": "/dev"}},
                    ],
                },
            },
        },
    }


def render_tpu_device_plugin(spec: SliceSpec,
                             image: str = DEFAULT_DEVICE_PLUGIN_IMAGE,
                             namespace: str = "kube-system") -> Dict[str, Any]:
    """Device plugin advertising ``google.com/tpu`` (nvidia-device-plugin analog)."""
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": "tpu-device-plugin", "namespace": namespace},
        "spec": {
            "selector": {"matchLabels": {"app": "tpu-device-plugin"}},
            "template": {
                "metadata": {"labels": {"app": "tpu-device-plugin"}},
                "spec": {
                    "nodeSelector": _tpu_node_selector(spec),
                    "priorityClassName": "system-node-critical",
                    "containers": [{
                        "name": "device-plugin",
                        "image": image,
                        "volumeMounts": [{
                            "name": "device-plugin-sock",
                            "mountPath": "/var/lib/kubelet/device-plugins",
                        }],
                    }],
                    "volumes": [{
                        "name": "device-plugin-sock",
                        "hostPath": {"path": "/var/lib/kubelet/device-plugins"},
                    }],
                },
            },
        },
    }


def render_slice_health_daemonset(spec: SliceSpec,
                                  image: str = DEFAULT_RUNTIME_IMAGE,
                                  namespace: str = "kube-system") -> Dict[str, Any]:
    """Readiness = libtpu enumerates all local chips (slice-health probe)."""
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": "tpu-slice-health", "namespace": namespace},
        "spec": {
            "selector": {"matchLabels": {"app": "tpu-slice-health"}},
            "template": {
                "metadata": {"labels": {"app": "tpu-slice-health"}},
                "spec": {
                    "nodeSelector": _tpu_node_selector(spec),
                    "containers": [{
                        "name": "probe",
                        "image": image,
                        "command": ["python", "-c",
                                    "import jax; assert len(jax.local_devices()) == "
                                    f"{spec.generation.chips_per_host}"],
                        "readinessProbe": {
                            "exec": {"command": [
                                "python", "-c",
                                "import jax; assert len(jax.local_devices()) == "
                                f"{spec.generation.chips_per_host}"]},
                            "periodSeconds": 60,
                        },
                    }],
                },
            },
        },
    }
