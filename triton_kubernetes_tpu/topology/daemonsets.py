"""DaemonSet manifests for TPU node pools: libtpu/JAX runtime + device plugin
+ slice-health probe.

This replaces the reference's per-VM bootstrap role (Packer-baked images +
install_docker_rancher.sh.tpl / nvidia-era device plumbing, SURVEY.md §2.5
table): on TPU node pools, host software is declarative — a DaemonSet that
ships libtpu + a pinned JAX/XLA runtime, the TPU device plugin that exposes
``google.com/tpu`` resources, and a health DaemonSet whose readiness gate is
"libtpu can enumerate all local chips" (the slice-health probe SURVEY.md §5
calls for).
"""

from __future__ import annotations

from typing import Any, Dict

from .labels import GKE_ACCELERATOR_LABEL
from .slices import SliceSpec

DEFAULT_RUNTIME_IMAGE = "tk8s/jax-tpu-runtime:0.1.0"
DEFAULT_DEVICE_PLUGIN_IMAGE = "tk8s/tpu-device-plugin:0.1.0"


def _tpu_node_selector(spec: SliceSpec,
                       per_host: bool = False) -> Dict[str, str]:
    sel = {GKE_ACCELERATOR_LABEL: spec.generation.gke_accelerator}
    if per_host:
        # Manifests that embed the per-slice chip count must only land on
        # matching hosts — a cluster can mix 4- and 8-chip hosts of one
        # generation (ct5lp-hightpu-4t vs -8t), and sub-host pools grant
        # fewer chips than the machine has. instance-type is set by
        # Kubernetes itself; chips-per-host is written by both
        # provisioning paths (topology/labels.py and the HCL nodepool).
        sel["node.kubernetes.io/instance-type"] = spec.machine_type
        sel["tpu.tk8s.io/chips-per-host"] = str(spec.chips_per_host)
    return sel


def _chip_variant(name: str, spec: SliceSpec) -> str:
    """Per-(machine shape, chip grant) manifest name
    (``tpu-jax-runtime-ct5lp-hightpu-8t-8c``): pools with the same shape
    AND grant share one DaemonSet; different shapes or sub-host grants —
    including same chips/host across generations — coexist instead of
    overwriting each other's env/assertions."""
    return f"{name}-{spec.machine_type}-{spec.chips_per_host}c"


def render_tpu_runtime_daemonset(spec: SliceSpec,
                                 image: str = DEFAULT_RUNTIME_IMAGE,
                                 namespace: str = "kube-system") -> Dict[str, Any]:
    """libtpu + JAX/XLA runtime DaemonSet (nvidia-docker analog, TPU-native)."""
    name = _chip_variant("tpu-jax-runtime", spec)
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "nodeSelector": _tpu_node_selector(spec, per_host=True),
                    "hostNetwork": True,  # ICI/DCN init needs host networking
                    "containers": [{
                        "name": "runtime",
                        "image": image,
                        "securityContext": {"privileged": True},
                        "volumeMounts": [
                            {"name": "libtpu", "mountPath": "/lib/libtpu"},
                            {"name": "dev", "mountPath": "/dev"},
                        ],
                        "env": [
                            {"name": "TPU_CHIPS_PER_HOST",
                             "value": str(spec.chips_per_host)},
                        ],
                    }],
                    "volumes": [
                        {"name": "libtpu", "hostPath": {"path": "/lib/libtpu"}},
                        {"name": "dev", "hostPath": {"path": "/dev"}},
                    ],
                },
            },
        },
    }


def render_tpu_device_plugin(spec: SliceSpec,
                             image: str = DEFAULT_DEVICE_PLUGIN_IMAGE,
                             namespace: str = "kube-system") -> Dict[str, Any]:
    """Device plugin advertising ``google.com/tpu`` (nvidia-device-plugin
    analog; triton_kubernetes_tpu/manager/device_plugin.py). Keyed by
    (machine shape, chip grant) like the runtime/health sets — each node
    belongs to exactly one pool, so exactly one variant matches it — and
    told its grant via TPU_CHIP_COUNT, so a sub-host v5p-2 pool advertises
    2 chips even though the host has 4 (the gating slices.py's
    chips_per_host contract relies on)."""
    name = _chip_variant("tpu-device-plugin", spec)
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "nodeSelector": _tpu_node_selector(spec, per_host=True),
                    "priorityClassName": "system-node-critical",
                    "containers": [{
                        "name": "device-plugin",
                        "image": image,
                        "env": [{"name": "TPU_CHIP_COUNT",
                                 "value": str(spec.chips_per_host)}],
                        "volumeMounts": [{
                            "name": "device-plugin-sock",
                            "mountPath": "/var/lib/kubelet/device-plugins",
                        }],
                    }],
                    "volumes": [{
                        "name": "device-plugin-sock",
                        "hostPath": {"path": "/var/lib/kubelet/device-plugins"},
                    }],
                },
            },
        },
    }


def render_slice_health_daemonset(spec: SliceSpec,
                                  image: str = DEFAULT_RUNTIME_IMAGE,
                                  namespace: str = "kube-system") -> Dict[str, Any]:
    """Readiness = libtpu enumerates all local chips (slice-health probe)."""
    name = _chip_variant("tpu-slice-health", spec)
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "nodeSelector": _tpu_node_selector(spec, per_host=True),
                    "containers": [{
                        "name": "probe",
                        "image": image,
                        "command": ["python", "-c",
                                    "import jax; assert len(jax.local_devices()) == "
                                    f"{spec.chips_per_host}"],
                        "readinessProbe": {
                            "exec": {"command": [
                                "python", "-c",
                                "import jax; assert len(jax.local_devices()) == "
                                f"{spec.chips_per_host}"]},
                            "periodSeconds": 60,
                        },
                    }],
                },
            },
        },
    }
