"""Deployment + Service rendering for the TPU serving workload.

The serving counterpart of ``jobset.py``'s JobSet/headless-service pair:
where training is a run-to-completion indexed Job spanning a whole
slice, serving is a long-lived Deployment of single-host replicas behind
a regular (cluster-IP'd) Service — requests need one stable VIP, not
per-pod DNS. Replicas pin to the labeled TPU node pool through the same
``selector_for_slice`` labels the trainer uses, which is the point: a
provisioned cluster's acceptance test is this workload serving real
traffic ("Evaluating Kubernetes Performance for GenAI Inference",
PAPERS.md), so the manifests must exercise the exact labels provisioning
promised.

Like ``jobset.RESUME_EXIT_CODE``, the serving port is duplicated here
rather than imported from ``serve/`` — rendering must never import the
jax-loaded workload stack (pinned equal in tests/test_topology.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .labels import selector_for_slice
from .slices import SliceSpec

# Single-sourced with serve.server.SERVE_PORT / serve.router's bind
# port from the dependency-free constants module (see module docstring;
# lint rule TK8S104).
from ..constants import OPERATOR_PORT, ROUTE_PORT, SERVE_PORT

APP_LABEL = "serve.tk8s.io/name"
MODEL_LABEL = "serve.tk8s.io/model"
ROLE_LABEL = "serve.tk8s.io/role"
# Disaggregated serving (docs/guide/serving.md §Disaggregation): which
# phase pool a replica belongs to — "prefill", "decode", or "colocated"
# (the classic both-phases replica). The router's two rings select
# endpoints by this label's Deployments.
POOL_LABEL = "serve.tk8s.io/pool"
POOLS = ("colocated", "prefill", "decode")


def default_serve_command(model: str, port: int = SERVE_PORT,
                          pool: str = "colocated") -> List[str]:
    """The container command the image contract expects: the CLI's
    ``serve`` verb, bound to all interfaces for the pod network."""
    cmd = ["triton-kubernetes-tpu", "serve", "--model", model,
           "--serve-host", "0.0.0.0", "--port", str(port)]
    if pool != "colocated":
        cmd += ["--pool", pool]
    return cmd


def render_serving_deployment(
    name: str,
    spec: SliceSpec,
    slice_id: str,
    image: str,
    model: str,
    replicas: int = 1,
    namespace: str = "default",
    env: Optional[Dict[str, str]] = None,
    command: Optional[List[str]] = None,
    pool: str = "colocated",
) -> Dict[str, Any]:
    """A Deployment of serving replicas on one labeled TPU pool.

    Each replica is a single-host engine owning ``spec.chips_per_host``
    chips (serving scales out in replicas behind the Service, not in
    slice-wide collectives), so the natural pool is a single-host slice
    shape like v5e-8; multi-host specs still render — each pod takes one
    host's chips. ``pool`` stamps the disaggregation phase label
    ("prefill"/"decode" replicas refuse the other phase's work;
    "colocated" runs both).
    """
    if pool not in POOLS:
        raise ValueError(f"pool must be one of {POOLS}, got {pool!r}")
    labels = {APP_LABEL: name, MODEL_LABEL: model, POOL_LABEL: pool}
    container = {
        "name": "server",
        "image": image,
        "command": command or default_serve_command(model, pool=pool),
        "env": [{"name": k, "value": v} for k, v in sorted(
            (env or {}).items())],
        "ports": [{"containerPort": SERVE_PORT, "name": "http"}],
        "resources": {"limits": {"google.com/tpu": str(spec.chips_per_host)}},
        # One endpoint serves liveness and readiness: the engine loop
        # answers /healthz as long as it can schedule at all.
        "readinessProbe": {
            "httpGet": {"path": "/healthz", "port": SERVE_PORT},
            "periodSeconds": 5,
        },
        "livenessProbe": {
            "httpGet": {"path": "/healthz", "port": SERVE_PORT},
            "initialDelaySeconds": 30,
            "periodSeconds": 10,
        },
    }
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": dict(labels)},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {APP_LABEL: name}},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "nodeSelector": selector_for_slice(spec, slice_id),
                    "containers": [container],
                },
            },
        },
    }


def render_disaggregated_deployments(
    name: str,
    spec: SliceSpec,
    slice_id: str,
    image: str,
    model: str,
    prefill_replicas: int = 1,
    decode_replicas: int = 1,
    namespace: str = "default",
    env: Optional[Dict[str, str]] = None,
) -> List[Dict[str, Any]]:
    """The disaggregated pair: ``{name}-prefill`` and ``{name}-decode``
    Deployments on the same labeled TPU pool, distinguished by the
    POOL_LABEL their pods carry and the ``--pool`` flag their servers
    run with. Front them with two headless Services (one per
    Deployment) and a router built with ``--decode-replica`` endpoints
    — sessions then prefill on one pool and migrate their KV pages to
    the other for the decode tail (docs/guide/serving.md
    §Disaggregation). Scale the pools independently: prefill replicas
    track *arrival* rate, decode replicas track *resident sessions*.
    """
    return [
        render_serving_deployment(
            f"{name}-prefill", spec, slice_id, image, model,
            replicas=prefill_replicas, namespace=namespace, env=env,
            pool="prefill"),
        render_serving_deployment(
            f"{name}-decode", spec, slice_id, image, model,
            replicas=decode_replicas, namespace=namespace, env=env,
            pool="decode"),
    ]


def render_serving_service(
    name: str,
    namespace: str = "default",
    service_type: str = "ClusterIP",
    headless: bool = False,
) -> Dict[str, Any]:
    """The VIP in front of the serving replicas. ``/metrics`` rides the
    same port, so a Prometheus scrape of the Service endpoints covers
    every replica with no extra wiring.

    ``headless=True`` renders ``clusterIP: None`` — per-pod DNS instead
    of one VIP, which is what the session-affine router needs: affinity
    only means something when the router can address a *specific*
    replica's KV pages, not whatever endpoint kube-proxy picks.
    """
    spec: Dict[str, Any] = {
        "type": service_type,
        "selector": {APP_LABEL: name},
        "ports": [{"name": "http", "port": SERVE_PORT,
                   "targetPort": SERVE_PORT}],
    }
    if headless:
        spec["clusterIP"] = "None"
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {APP_LABEL: name}},
        "spec": spec,
    }


def default_route_command(replica_urls: List[str],
                          port: int = ROUTE_PORT,
                          decode_urls: Optional[List[str]] = None,
                          ) -> List[str]:
    """The router container command: the CLI's ``route`` verb bound to
    all interfaces, one ``--replica`` per serving endpoint (and one
    ``--decode-replica`` per decode-pool endpoint in disaggregated
    mode, where ``--replica`` names the prefill pool)."""
    cmd = ["triton-kubernetes-tpu", "route",
           "--route-host", "0.0.0.0", "--port", str(port)]
    for url in replica_urls:
        cmd += ["--replica", url]
    for url in decode_urls or []:
        cmd += ["--decode-replica", url]
    return cmd


def render_router_deployment(
    name: str,
    image: str,
    replica_urls: List[str],
    replicas: int = 1,
    namespace: str = "default",
    env: Optional[Dict[str, str]] = None,
    command: Optional[List[str]] = None,
    decode_urls: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """The router Deployment beside the replica set.

    No TPU limits and no node selector: the router is pure CPU HTTP
    plumbing and schedules anywhere. ``replica_urls`` are the serving
    endpoints it fronts — with the replicas behind a headless Service
    (``render_serving_service(..., headless=True)``) these are the
    per-pod DNS names, which is what makes session affinity land on the
    pod actually holding the KV pages.
    """
    if not replica_urls:
        raise ValueError("router needs at least one replica URL")
    labels = {APP_LABEL: name, ROLE_LABEL: "router"}
    container = {
        "name": "router",
        "image": image,
        "command": command or default_route_command(
            replica_urls, decode_urls=decode_urls),
        "env": [{"name": k, "value": v} for k, v in sorted(
            (env or {}).items())],
        "ports": [{"containerPort": ROUTE_PORT, "name": "http"}],
        # Readiness ONLY: /healthz reflects REPLICA health (503 when
        # every replica is unreachable), which parks the router out of
        # its Service during a fleet outage. A liveness probe on the
        # same endpoint would have kubelet restart-loop perfectly
        # healthy router processes through that outage — restarting the
        # router cannot resurrect replicas.
        "readinessProbe": {
            "httpGet": {"path": "/healthz", "port": ROUTE_PORT},
            "periodSeconds": 5,
        },
    }
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": dict(labels)},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {APP_LABEL: name,
                                         ROLE_LABEL: "router"}},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {"containers": [container]},
            },
        },
    }


def render_router_service(
    name: str,
    namespace: str = "default",
    service_type: str = "ClusterIP",
) -> Dict[str, Any]:
    """The fleet's single front door: one VIP over the router pods
    (the routers are stateless — any of them hashes a session to the
    same replica, so scaling routers never splits affinity)."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {APP_LABEL: name, ROLE_LABEL: "router"}},
        "spec": {
            "type": service_type,
            "selector": {APP_LABEL: name, ROLE_LABEL: "router"},
            "ports": [{"name": "http", "port": ROUTE_PORT,
                       "targetPort": ROUTE_PORT}],
        },
    }


def default_operate_command(manager: str,
                            scrape_urls: Optional[List[str]] = None,
                            port: int = OPERATOR_PORT) -> List[str]:
    """The operator container command: the CLI's ``operate`` verb with
    its /metrics endpoint bound to all interfaces, scraping the fleet's
    per-replica endpoints. ``--non-interactive`` and ``--set`` are
    ROOT-parser flags and must precede the subcommand — and a pod has
    no TTY to answer prompts on."""
    cmd = ["triton-kubernetes-tpu", "--non-interactive",
           "--set", f"cluster_manager={manager}", "operate",
           "--operator-host", "0.0.0.0", "--operator-port", str(port)]
    for url in scrape_urls or []:
        cmd += ["--scrape", url]
    return cmd


def render_operator_deployment(
    name: str,
    image: str,
    manager: str,
    scrape_urls: Optional[List[str]] = None,
    namespace: str = "default",
    env: Optional[Dict[str, str]] = None,
    command: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """The reconcile operator Deployment.

    Exactly one replica, by design: the reconcile loop is a single
    writer against the state document (two operators would race the
    backend's state lock every tick and fight over scale decisions) —
    ``replicas: 1`` plus Recreate strategy is the poor-k8s leader
    election that matches the backend's locking model. CPU-only, no
    node selector: like the router, the operator is control-plane
    plumbing and schedules anywhere.
    """
    labels = {APP_LABEL: name, ROLE_LABEL: "operator"}
    container = {
        "name": "operator",
        "image": image,
        "command": command or default_operate_command(manager, scrape_urls),
        "env": [{"name": k, "value": v} for k, v in sorted(
            (env or {}).items())],
        "ports": [{"containerPort": OPERATOR_PORT, "name": "http"}],
        # Liveness (not readiness): /healthz goes 503 when the
        # reconcile loop thread died, and restarting the pod is exactly
        # the fix — the loop is the workload, there is no traffic to
        # park away.
        "livenessProbe": {
            "httpGet": {"path": "/healthz", "port": OPERATOR_PORT},
            "initialDelaySeconds": 10,
            "periodSeconds": 10,
        },
    }
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": dict(labels)},
        "spec": {
            "replicas": 1,
            "strategy": {"type": "Recreate"},
            "selector": {"matchLabels": {APP_LABEL: name,
                                         ROLE_LABEL: "operator"}},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {"containers": [container]},
            },
        },
    }


def render_operator_service(
    name: str,
    namespace: str = "default",
) -> Dict[str, Any]:
    """A ClusterIP over the operator pod — the Prometheus scrape target
    for the ``tk8s_operator_*`` families."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {APP_LABEL: name, ROLE_LABEL: "operator"}},
        "spec": {
            "type": "ClusterIP",
            "selector": {APP_LABEL: name, ROLE_LABEL: "operator"},
            "ports": [{"name": "http", "port": OPERATOR_PORT,
                       "targetPort": OPERATOR_PORT}],
        },
    }
