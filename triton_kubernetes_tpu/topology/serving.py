"""Deployment + Service rendering for the TPU serving workload.

The serving counterpart of ``jobset.py``'s JobSet/headless-service pair:
where training is a run-to-completion indexed Job spanning a whole
slice, serving is a long-lived Deployment of single-host replicas behind
a regular (cluster-IP'd) Service — requests need one stable VIP, not
per-pod DNS. Replicas pin to the labeled TPU node pool through the same
``selector_for_slice`` labels the trainer uses, which is the point: a
provisioned cluster's acceptance test is this workload serving real
traffic ("Evaluating Kubernetes Performance for GenAI Inference",
PAPERS.md), so the manifests must exercise the exact labels provisioning
promised.

Like ``jobset.RESUME_EXIT_CODE``, the serving port is duplicated here
rather than imported from ``serve/`` — rendering must never import the
jax-loaded workload stack (pinned equal in tests/test_topology.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .labels import selector_for_slice
from .slices import SliceSpec

# Single-sourced with serve.server.SERVE_PORT from the dependency-free
# constants module (see module docstring; lint rule TK8S104).
from ..constants import SERVE_PORT

APP_LABEL = "serve.tk8s.io/name"
MODEL_LABEL = "serve.tk8s.io/model"


def default_serve_command(model: str, port: int = SERVE_PORT) -> List[str]:
    """The container command the image contract expects: the CLI's
    ``serve`` verb, bound to all interfaces for the pod network."""
    return ["triton-kubernetes-tpu", "serve", "--model", model,
            "--serve-host", "0.0.0.0", "--port", str(port)]


def render_serving_deployment(
    name: str,
    spec: SliceSpec,
    slice_id: str,
    image: str,
    model: str,
    replicas: int = 1,
    namespace: str = "default",
    env: Optional[Dict[str, str]] = None,
    command: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """A Deployment of serving replicas on one labeled TPU pool.

    Each replica is a single-host engine owning ``spec.chips_per_host``
    chips (serving scales out in replicas behind the Service, not in
    slice-wide collectives), so the natural pool is a single-host slice
    shape like v5e-8; multi-host specs still render — each pod takes one
    host's chips.
    """
    labels = {APP_LABEL: name, MODEL_LABEL: model}
    container = {
        "name": "server",
        "image": image,
        "command": command or default_serve_command(model),
        "env": [{"name": k, "value": v} for k, v in sorted(
            (env or {}).items())],
        "ports": [{"containerPort": SERVE_PORT, "name": "http"}],
        "resources": {"limits": {"google.com/tpu": str(spec.chips_per_host)}},
        # One endpoint serves liveness and readiness: the engine loop
        # answers /healthz as long as it can schedule at all.
        "readinessProbe": {
            "httpGet": {"path": "/healthz", "port": SERVE_PORT},
            "periodSeconds": 5,
        },
        "livenessProbe": {
            "httpGet": {"path": "/healthz", "port": SERVE_PORT},
            "initialDelaySeconds": 30,
            "periodSeconds": 10,
        },
    }
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": dict(labels)},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {APP_LABEL: name}},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "nodeSelector": selector_for_slice(spec, slice_id),
                    "containers": [container],
                },
            },
        },
    }


def render_serving_service(
    name: str,
    namespace: str = "default",
    service_type: str = "ClusterIP",
) -> Dict[str, Any]:
    """The VIP in front of the serving replicas. ``/metrics`` rides the
    same port, so a Prometheus scrape of the Service endpoints covers
    every replica with no extra wiring."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {APP_LABEL: name}},
        "spec": {
            "type": service_type,
            "selector": {APP_LABEL: name},
            "ports": [{"name": "http", "port": SERVE_PORT,
                       "targetPort": SERVE_PORT}],
        },
    }
