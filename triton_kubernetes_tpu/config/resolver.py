"""The tri-modal input idiom as one object.

Every input in every workflow goes through ``InputResolver``: config value if
set, hard error in non-interactive mode, otherwise an interactive prompt —
optionally with live choices (cloud-API-backed in the reference,
driver-backed here). This is the ~90-times-repeated viper/promptui pattern
(SURVEY.md §5) factored once.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

from .config import Config
from .prompts import MissingInputError, Prompter, ValidationError, Validator


class InputResolver:
    def __init__(self, config: Config, prompter: Optional[Prompter],
                 non_interactive: bool):
        self.config = config
        self.prompter = prompter
        self.non_interactive = non_interactive

    def _missing(self, key: str) -> MissingInputError:
        return MissingInputError(f"{key} must be specified")

    def value(self, key: str, label: Optional[str] = None, *,
              default: Optional[Any] = None,
              validate: Optional[Validator] = None) -> Any:
        """Free-form input (promptui Prompt analog)."""
        if self.config.is_set(key):
            v = self.config.get(key)
            err = validate(v) if validate else None
            if err is not None:
                raise ValidationError(f"{key}: {err}")
            return v
        if self.non_interactive:
            if default is not None:
                return default
            raise self._missing(key)
        shown = str(default) if default is not None else None
        v = self.prompter.input(label or key, default=shown, validate=validate)
        if default is not None and v == shown:
            # Default accepted: return the original object, not its repr
            # (list/dict defaults must match the non-interactive path).
            return default
        return v

    def choose(self, key: str, label: str,
               options: Sequence[Tuple[str, Any]],
               default: Optional[Any] = None) -> Any:
        """Choice input (promptui Select analog). A configured value must
        match one of the options' values (or displays)."""
        if self.config.is_set(key):
            v = self.config.get(key)
            for display, value in options:
                if v == value or v == display:
                    return value
            raise ValidationError(
                f"{key}: {v!r} is not a valid choice "
                f"(valid: {[v2 for _, v2 in options]})")
        if self.non_interactive:
            if default is not None:
                return default
            raise self._missing(key)
        return self.prompter.select(label, options)

    def secret(self, key: str, label: str) -> Any:
        """Masked free-form input (key passphrases). Config-set values are
        honored like ``value``; non-interactive sessions never prompt
        (missing error), matching the silent-install contract."""
        if self.config.is_set(key):
            return self.config.get(key)
        if self.non_interactive:
            raise self._missing(key)
        return self.prompter.secret(label)

    def confirm(self, key: str, label: str) -> bool:
        """Yes/No (util/confirm_prompt.go analog). Non-interactive mode
        auto-confirms, matching the reference's silent installs."""
        if self.config.is_set(key):
            return bool(self.config.get(key))
        if self.non_interactive:
            return True
        return self.prompter.confirm(label)

    def flag(self, key: str, default: bool = False) -> bool:
        if self.config.is_set(key):
            return bool(self.config.get(key))
        return default
