"""Tri-modal input layer: flags/YAML/env > non-interactive error > prompt.

Reference analog: cobra flags + viper three-way precedence (cmd/root.go:49-66)
plus the idiom repeated ~90 times across the workflows::

    if viper.IsSet(k): use it
    elif nonInteractiveMode: error "k must be specified"
    else: promptui prompt with live-API-backed choices

SURVEY.md §5 calls this "the UX heart of the tool"; ``InputResolver`` is that
idiom as a single reusable object, with the silent-install YAML schema
(docs/guide/silent-install-yaml.md) as the config-file format.
"""

from .config import Config
from .prompts import (
    InteractivePrompter,
    MissingInputError,
    Prompter,
    ScriptedPrompter,
    ValidationError,
)
from .resolver import InputResolver

__all__ = [
    "Config",
    "InputResolver",
    "InteractivePrompter",
    "MissingInputError",
    "Prompter",
    "ScriptedPrompter",
    "ValidationError",
]
