"""Config value store with viper-style precedence.

Precedence (cmd/root.go:49-66 analog): explicit overrides (``--set k=v`` or
programmatic ``set()``) > config file (``--config file.yaml``, else
``$HOME/.triton-kubernetes-tpu.yaml`` if present) > environment variables
(AutomaticEnv analog, but namespaced: key ``aws_region`` reads
``$TK8S_AWS_REGION`` — a bare ``$AWS_REGION`` fallback would let unrelated
process env, e.g. the TPU runtime's own ``TPU_TOPOLOGY``, silently leak into
workflow inputs).

YAML support: the silent-install schema is intentionally flat (scalars plus
the ``nodes:`` list of dicts), so a tiny built-in parser covers it without a
yaml dependency; PyYAML is used when available.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Dict, Optional

DEFAULT_CONFIG_PATH = "~/.triton-kubernetes-tpu.yaml"


def parse_scalar(s: str) -> Any:
    s = s.strip()
    if s in ("true", "True"):
        return True
    if s in ("false", "False"):
        return False
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    if len(s) >= 2 and s[0] == s[-1] and s[0] in "\"'":
        return s[1:-1]
    return s


def _mini_yaml(text: str) -> Dict[str, Any]:
    """Parse the flat silent-install subset: ``key: value`` lines, lists of
    dicts via ``-`` items, one nesting level, ``#`` comments."""
    root: Dict[str, Any] = {}
    current_list: Optional[list] = None
    current_item: Optional[dict] = None
    list_indent = 0
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].rstrip()
        if not line.strip():
            continue
        indent = len(line) - len(line.lstrip())
        stripped = line.strip()
        if stripped.startswith("- "):
            if current_list is None:
                raise ValueError(f"list item outside a list: {raw!r}")
            current_item = {}
            current_list.append(current_item)
            list_indent = indent
            stripped = stripped[2:]
            if stripped:
                k, _, v = stripped.partition(":")
                current_item[k.strip()] = parse_scalar(v)
            continue
        if current_item is not None and indent > list_indent:
            k, _, v = stripped.partition(":")
            current_item[k.strip()] = parse_scalar(v)
            continue
        current_item = None
        current_list = None
        k, sep, v = stripped.partition(":")
        if not sep:
            raise ValueError(f"cannot parse line: {raw!r}")
        if v.strip() == "":
            current_list = []
            root[k.strip()] = current_list
        else:
            root[k.strip()] = parse_scalar(v)
    return root


def load_yaml_file(path: str) -> Dict[str, Any]:
    text = Path(os.path.expanduser(path)).read_text()
    try:
        import yaml  # type: ignore

        data = yaml.safe_load(text)
        return data if isinstance(data, dict) else {}
    except ImportError:
        return _mini_yaml(text)


class Config:
    def __init__(self, config_file: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 use_default_file: bool = True):
        """``use_default_file=False`` makes the config hermetic: no
        fallback to ~/.triton-kubernetes-tpu.yaml. Programmatic callers
        (automation building a silent context from explicit values) use
        it so an operator's leftover defaults cannot steer them."""
        self._overrides: Dict[str, Any] = {}
        self._file_values: Dict[str, Any] = {}
        self._env = env if env is not None else dict(os.environ)
        if config_file:
            self._file_values = load_yaml_file(config_file)
        elif use_default_file:
            default = Path(os.path.expanduser(DEFAULT_CONFIG_PATH))
            if default.is_file():
                self._file_values = load_yaml_file(str(default))

    def set(self, key: str, value: Any) -> None:
        self._overrides[key] = value

    def unset(self, key: str) -> None:
        self._overrides.pop(key, None)

    @staticmethod
    def _env_key(key: str) -> str:
        return "TK8S_" + key.upper().replace("-", "_")

    def is_set(self, key: str) -> bool:
        return (
            key in self._overrides
            or key in self._file_values
            or self._env_key(key) in self._env
        )

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._overrides:
            return self._overrides[key]
        if key in self._file_values:
            return self._file_values[key]
        if self._env_key(key) in self._env:
            return parse_scalar(self._env[self._env_key(key)])
        return default

    def to_dict(self) -> Dict[str, Any]:
        out = dict(self._file_values)
        out.update(self._overrides)
        return out

    def __repr__(self) -> str:  # pragma: no cover
        return f"Config({json.dumps(self.to_dict(), default=str)[:200]})"
