"""Prompt engines: interactive (promptui analog) and scripted (for tests).

Reference analog: promptui Select/Prompt used throughout create/ and util/
(e.g. util/confirm_prompt.go:10-35), with live cloud-API-backed choice lists.
"""

from __future__ import annotations

import abc
import sys
from typing import Any, Callable, List, Optional, Sequence, Tuple


class MissingInputError(ValueError):
    """Non-interactive mode and a required key is absent — the exact error
    contract the reference's guard-rail tests pin (e.g. destroy/cluster_test.go)."""


class ValidationError(ValueError):
    pass


Validator = Callable[[Any], Optional[str]]  # returns error message or None


class Prompter(abc.ABC):
    @abc.abstractmethod
    def select(self, label: str, options: Sequence[Tuple[str, Any]]) -> Any:
        """Pick one of (display, value) options; returns the value."""

    @abc.abstractmethod
    def input(self, label: str, default: Optional[str] = None,
              validate: Optional[Validator] = None) -> str: ...

    def confirm(self, label: str) -> bool:
        return self.select(label, [("Yes", True), ("No", False)])

    def secret(self, label: str) -> str:
        """Masked input (passphrases). Default: unmasked input — concrete
        prompters override with real masking."""
        return self.input(label)


class InteractivePrompter(Prompter):
    """Plain-stdin prompter (numbered select), stdio like the reference."""

    def __init__(self, infile=None, outfile=None):
        self.infile = infile or sys.stdin
        self.outfile = outfile or sys.stdout

    def _write(self, s: str) -> None:
        self.outfile.write(s)
        self.outfile.flush()

    def select(self, label: str, options: Sequence[Tuple[str, Any]]) -> Any:
        if not options:
            raise ValidationError(f"{label}: no options available")
        self._write(f"{label}:\n")
        for i, (display, _) in enumerate(options, 1):
            self._write(f"  {i}. {display}\n")
        while True:
            self._write(f"Select [1-{len(options)}]: ")
            line = self.infile.readline()
            if not line:
                raise EOFError(f"stdin closed while selecting {label!r}")
            choice = line.strip()
            if choice.isdigit() and 1 <= int(choice) <= len(options):
                return options[int(choice) - 1][1]
            # Also accept typing the display string exactly.
            for display, value in options:
                if choice == display:
                    return value
            self._write("Invalid selection.\n")

    def input(self, label: str, default: Optional[str] = None,
              validate: Optional[Validator] = None) -> str:
        suffix = f" [{default}]" if default not in (None, "") else ""
        while True:
            self._write(f"{label}{suffix}: ")
            line = self.infile.readline()
            if not line:
                raise EOFError(f"stdin closed while prompting {label!r}")
            value = line.strip() or (default or "")
            err = validate(value) if validate else None
            if err is None:
                return value
            self._write(f"{err}\n")

    def secret(self, label: str) -> str:
        """Masked when reading the real terminal (getpass: no echo, like
        the reference's promptui password mask, util/ssh_utils.go:22-28);
        plain readline when stdin is redirected (tests, pipes — getpass
        would grab the controlling tty and hang a scripted run)."""
        if self.infile is sys.stdin and sys.stdin.isatty():
            import getpass

            return getpass.getpass(f"{label}: ")
        self._write(f"{label}: ")
        line = self.infile.readline()
        if not line:
            raise EOFError(f"stdin closed while prompting {label!r}")
        return line.rstrip("\n")


class ScriptedPrompter(Prompter):
    """Deterministic prompter fed a list of answers (test fixture)."""

    def __init__(self, answers: Optional[List[Any]] = None):
        self.answers = list(answers or [])
        self.transcript: List[str] = []

    def _next(self, label: str) -> Any:
        if not self.answers:
            raise AssertionError(f"no scripted answer left for prompt {label!r}")
        self.transcript.append(label)
        return self.answers.pop(0)

    def select(self, label: str, options: Sequence[Tuple[str, Any]]) -> Any:
        if not options:
            raise ValidationError(f"{label}: no options available")
        ans = self._next(label)
        for display, value in options:
            if ans == display or ans == value:
                return value
        raise AssertionError(
            f"scripted answer {ans!r} not among options for {label!r}: "
            f"{[d for d, _ in options]}")

    def input(self, label: str, default: Optional[str] = None,
              validate: Optional[Validator] = None) -> str:
        ans = self._next(label)
        value = str(ans) if ans is not None else (default or "")
        if value == "" and default:
            value = default
        err = validate(value) if validate else None
        if err is not None:
            raise ValidationError(f"{label}: {err}")
        return value

    def secret(self, label: str) -> str:
        return str(self._next(label))
