"""TPU-first neural-net ops for the bundled workloads.

Everything here is jit-traceable pure JAX (static shapes, ``lax`` control
flow) so XLA can fuse elementwise work into the MXU matmuls; the
sequence-parallel path (``ring_attention``) is a ``shard_map`` program whose
KV rotation lowers to ``ppermute`` neighbor exchanges on the ICI ring.
"""

from .norms import rms_norm
from .rotary import apply_rotary, rotary_tables
from .attention import auto_attention, causal_attention
from .flash_attention import flash_attention
from .ring_attention import make_ring_attention, ring_attention_inner
from .moe import moe_layer, sort_router, top_k_router
from .paged_attention import (
    TRASH_PAGE,
    blocks_for,
    gather_pages,
    ragged_paged_attention,
    scatter_token,
)

__all__ = [
    "rms_norm",
    "apply_rotary",
    "rotary_tables",
    "auto_attention",
    "causal_attention",
    "flash_attention",
    "make_ring_attention",
    "ring_attention_inner",
    "moe_layer",
    "sort_router",
    "top_k_router",
    "TRASH_PAGE",
    "blocks_for",
    "gather_pages",
    "ragged_paged_attention",
    "scatter_token",
]
