"""Fused softmax cross-entropy head: loss without materializing logits.

The standard head computes ``logits = h @ W  ([B,S,V] f32)`` then
softmax-CE — at Llama vocab sizes the f32 logits (plus their cotangent in
backward) are the largest activations in the whole step and pure HBM
traffic (llama3-bench: 2 x batch*seq*32768*4B per step). This op runs the
vocab projection in chunks with an online logsumexp, so peak memory is
``[T, chunk]`` instead of ``[T, V]``; the backward recomputes each logits
chunk (flash-attention-style) and accumulates dH and dW chunkwise.

Exactness: same f32 accumulation as the reference path — pinned against
``optax.softmax_cross_entropy_with_integer_labels`` in
tests/test_train.py::test_fused_ce_matches_logits_path (f32 exact,
bf16 within tolerance).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

DEFAULT_CHUNK = 8192


def _chunked_w(w: jnp.ndarray, chunk: int):
    """[D, V] -> [nc, D, chunk] (vocab-padded); pads score -inf via mask
    handled by callers using the true V."""
    if chunk < 1:
        raise ValueError(f"ce_chunk must be >= 1, got {chunk}")
    d, v = w.shape
    pad = (-v) % chunk
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
    return w.reshape(d, -1, chunk).transpose(1, 0, 2), v + pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_cross_entropy(h, w, targets, chunk=DEFAULT_CHUNK):
    """Per-token CE loss [T] for hidden states h [T, D], head w [D, V],
    integer targets [T]. f32 math regardless of input dtype."""
    return _forward(h, w, targets, chunk)[0]


def _forward(h, w, targets, chunk):
    t, d = h.shape
    v = w.shape[1]
    wc, v_pad = _chunked_w(w, chunk)
    dtype = h.dtype

    def body(carry, xs):
        m, s, tgt = carry
        w_chunk, start = xs
        logits = jnp.einsum("td,dc->tc", h, w_chunk.astype(dtype),
                            preferred_element_type=jnp.float32)
        if v_pad != v:
            # Padded vocab columns must not contribute. When the chunk
            # divides the vocab (llama3-bench: 32768 % 8192 == 0) there is
            # no padding and the [T, C] mask+where never materializes.
            col = start + jnp.arange(chunk)
            logits = jnp.where(col[None, :] < v, logits, -jnp.inf)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]).sum(axis=-1)
        in_chunk = (targets >= start) & (targets < start + chunk)
        idx = jnp.clip(targets - start, 0, chunk - 1)
        tgt = tgt + jnp.where(in_chunk,
                              jnp.take_along_axis(
                                  logits, idx[:, None], axis=1)[:, 0], 0.0)
        return (m_new, s, tgt), None

    starts = jnp.arange(0, v_pad, chunk)
    init = (jnp.full((t,), -jnp.inf, jnp.float32),
            jnp.zeros((t,), jnp.float32), jnp.zeros((t,), jnp.float32))
    (m, s, tgt), _ = lax.scan(body, init, (wc, starts))
    lse = m + jnp.log(s)
    loss = lse - tgt
    return loss, (h, w, targets, lse)


def _backward(chunk, residuals, g):
    h, w, targets, lse = residuals
    t, d = h.shape
    v = w.shape[1]
    wc, v_pad = _chunked_w(w, chunk)
    dtype = h.dtype

    def body(dh, xs):
        w_chunk, start = xs
        logits = jnp.einsum("td,dc->tc", h, w_chunk.astype(dtype),
                            preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse[:, None])
        if v_pad != v:  # zero the padded columns' softmax mass (see fwd)
            col = start + jnp.arange(chunk)
            p = jnp.where(col[None, :] < v, p, 0.0)
        in_chunk = (targets >= start) & (targets < start + chunk)
        idx = jnp.clip(targets - start, 0, chunk - 1)
        onehot = (jnp.arange(chunk)[None, :] == idx[:, None]) & \
            in_chunk[:, None]
        dlogits = (p - onehot.astype(jnp.float32)) * g[:, None]  # [T, C]
        # Keep the f32 cotangent in both contractions (cast only the
        # w/h operands), matching the standard head's einsum VJP — a
        # bf16 round-trip here would drift gradients off the logits path.
        dh = dh + jnp.einsum("tc,dc->td", dlogits, w_chunk.astype(dtype),
                             preferred_element_type=jnp.float32)
        dw_chunk = jnp.einsum("td,tc->dc", h, dlogits,
                              preferred_element_type=jnp.float32)
        return dh, dw_chunk

    starts = jnp.arange(0, v_pad, chunk)
    dh, dw_stack = lax.scan(body, jnp.zeros((t, d), jnp.float32),
                            (wc, starts))
    dw = dw_stack.transpose(1, 0, 2).reshape(d, v_pad)[:, :v]
    return dh.astype(h.dtype), dw.astype(w.dtype), None


fused_cross_entropy.defvjp(
    lambda h, w, targets, chunk=DEFAULT_CHUNK: _forward(h, w, targets, chunk),
    _backward)
