"""Blockwise causal flash attention (fwd + bwd) as Pallas TPU kernels.

The einsum attention in ``ops/attention.py`` materializes the [Sq, Sk] logits
in HBM-sized intermediates; fine up to moderate S, but the HBM traffic grows
O(S^2). These kernels stream K/V blocks through VMEM with the online-softmax
recurrence (FlashAttention-2 style), keeping the working set at
O(block_q x block_k) with f32 VMEM scratch accumulators.

Forward — grid (batch*q_head, Sq/bq, Sk/bk), k-block innermost:
    s    = q . k^T * scale          (MXU, f32 accumulate)
    m'   = max(m, rowmax(s));  p = exp(s - m');  c = exp(m - m')
    l    = l*c + rowsum(p);    acc = acc*c + p . v
  last k-block: out = acc / l, and the row logsumexp L = m + log(l) is
  written as a residual so backward never re-runs the online recurrence.

Backward — two passes, both recomputing p = exp(s - L) blockwise:
  dQ pass, grid (batch*q_head, Sq/bq, Sk/bk), k innermost:
    dp = dO . v^T;  ds = p * (dp - D) * scale;  dq += ds . k
    where D = rowsum(dO * O) is precomputed outside (one fused elementwise).
  dK/dV pass, grid (batch*q_head, Sk/bk, Sq/bq), q innermost:
    dv += p^T . dO;  dk += ds^T . q
  GQA: dK/dV accumulate per *query* head and are group-summed outside the
  kernel ([B, Hq] -> [B, Hkv]); K/V blocks are index-mapped to the KV head
  (h // group) so the head-repeated K/V is never materialized in HBM.

Causal skip: blocks strictly above the diagonal are predicated out with
``pl.when`` — their FLOPs are never issued, halving compute for long S.

Numerics: logits/softmax in f32; the recomputed probabilities are cast to
the input dtype (bf16) for the MXU dots, matching the forward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.jaxcompat import pallas_tpu

pl, pltpu, _CompilerParams = pallas_tpu()

NEG_INF = -1e30

# Block sweep on v5e (llama3-bench, seq 2048, 2026-07-30, tok/s):
# q512/k1024 35.0k, q256/k1024 32.8k, q512/k512 33.1k, q1024/k1024 35.6k,
# q512/k2048 34.2k. Larger q blocks amortize the causal-mask bookkeeping.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


def _pick_block(default: int, s: int) -> int:
    """Largest 128-multiple <= default that divides the 128-padded
    sequence — a big default must never inflate padding (seq 1280 with
    block 1024 would pad to 2048; picking 640 pads nothing)."""
    sp = _round_up(s, 128)
    if sp <= default:
        return sp
    for b in range(default - default % 128, 127, -128):
        if sp % b == 0:
            return b
    return 128


def _causal_mask(s, qi, ki, block_q, block_k, sk):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    # Causal + padding mask (padded keys past sk never contribute).
    return jnp.where((q_pos >= k_pos) & (k_pos < sk), s, NEG_INF)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, block_q: int, block_k: int,
                  sk: int, num_k_blocks: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # k-blocks fully above the causal diagonal contribute nothing: the
    # earliest query row of this q-block is qi*block_q, the first key of the
    # k-block is ki*block_k.
    @pl.when(ki * block_k <= qi * block_q + block_q - 1)
    def _compute():
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        s = _causal_mask(s, qi, ki, block_q, block_k, sk)

        m_prev = m_ref[:]                          # [bq, 128] lane-replicated
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)          # broadcast -> [bq, 128]
        p = jnp.exp(s - m_new[:, :1])               # [bq, bk]
        corr = jnp.exp(m_prev - m_new)              # [bq, 128]
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ki == num_k_blocks - 1)
    def _finish():
        # Padded *query* rows still attend real keys (finite softmax); their
        # outputs are garbage but get sliced off by the wrapper, and their
        # gradients vanish because dO's zero-padding zeroes dp/ds/p.dO in
        # the backward kernels. The l == 0 guard below is defensive only
        # (a row with every key masked, e.g. sk rounded to 0 blocks).
        l = l_ref[:, :1]
        o_ref[0] = jnp.where(
            l > 0, acc_ref[:] / l, 0.0).astype(o_ref.dtype)
        # Row stats ride in an 8-lane trailer dim (the f32 sublane tile) —
        # Mosaic rejects (1, block_q) 2D row blocks.
        lse = jnp.where(l > 0, m_ref[:, :1] + jnp.log(l), jnp.inf)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


def _pad_seq(x, block):
    pad = (-x.shape[1]) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _to_flat(x):
    """[B, S, H, D] -> [B*H, S, D]: one flat batch-head grid axis gives
    Mosaic a clean (parallel, parallel, arbitrary) pipeline."""
    b, s, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)


def _kv_index(hq: int, hkv: int):
    group = hq // hkv

    def index(bh, i, j, *, axis):
        # bh = b*Hq + h  ->  flat KV row b*Hkv + h//group.
        row = (bh // hq) * hkv + (bh % hq) // group
        return (row, (j if axis == 2 else i), 0)

    return index


def _flash_forward_flat(qt, kt, vt, hq, hkv, sq, sk,
                        block_q, block_k, interpret):
    """Flat [B*H, S_padded, D] in; returns (out, lse) still padded/flat."""
    bhq, sq_p, d = qt.shape
    sk_p = kt.shape[1]
    num_k_blocks = sk_p // block_k
    grid = (bhq, sq_p // block_q, num_k_blocks)
    scale = d ** -0.5
    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k,
        sk=sk, num_k_blocks=num_k_blocks)
    kv = _kv_index(hq, hkv)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         functools.partial(kv, axis=2)),
            pl.BlockSpec((1, block_k, d),
                         functools.partial(kv, axis=2)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhq, sq_p, d), qt.dtype),
            jax.ShapeDtypeStruct((bhq, sq_p, 8), jnp.float32),
        ],
        scratch_shapes=[
            # m/l lane-replicated at 128 to match the f32 VMEM tile.
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)
    return out, lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref, dq_ref,
               acc_ref, *, scale: float, block_q: int, block_k: int,
               sk: int, num_k_blocks: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(ki * block_k <= qi * block_q + block_q - 1)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _causal_mask(s, qi, ki, block_q, block_k, sk)
        p = jnp.exp(s - lse_ref[0][:, :1])            # [bq, bk], normalized
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - dvec_ref[0][:, :1]) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale: float, block_q: int, block_k: int,
                sk: int, num_q_blocks: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(ki * block_k <= qi * block_q + block_q - 1)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _causal_mask(s, qi, ki, block_q, block_k, sk)
        p = jnp.exp(s - lse_ref[0][:, :1])             # [bq, bk]
        pt = p.astype(do.dtype)
        dv_acc[:] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - dvec_ref[0][:, :1]) * scale).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]

    @pl.when(qi == num_q_blocks - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(qt, kt, vt, out_flat, lse, g,
                    b, sq, sk, hq, hkv, d, block_q, block_k, interpret):
    """Residuals arrive already flat/padded from the forward ([B*H, S_p, D])
    so only the cotangent g needs the layout change here."""
    group = hq // hkv
    scale = d ** -0.5
    bhq, sq_p, _ = qt.shape
    sk_p = kt.shape[1]
    num_q_blocks = sq_p // block_q
    num_k_blocks = sk_p // block_k

    dot = _pad_seq(_to_flat(g), block_q)

    # D_i = rowsum(dO * O): one fused elementwise+reduce on the flat layout,
    # carried in the same 8-lane trailer layout as lse.
    dvec = jnp.einsum("rsd,rsd->rs", dot.astype(jnp.float32),
                      out_flat.astype(jnp.float32))
    dvec = jnp.broadcast_to(dvec[:, :, None], (bhq, sq_p, 8))

    kv = _kv_index(hq, hkv)
    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    row_spec = pl.BlockSpec((1, block_q, 8), lambda bh, qi, ki: (bh, qi, 0))
    k_spec = pl.BlockSpec((1, block_k, d), functools.partial(kv, axis=2))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, sk=sk,
                          num_k_blocks=num_k_blocks),
        grid=(bhq, num_q_blocks, num_k_blocks),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bhq, sq_p, d), qt.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, dvec)

    # dK/dV accumulate per query head (grid rows = B*Hq); the group-sum to
    # KV heads happens below in plain XLA on [B, Hq, Sk, D].
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0))
    row_spec2 = pl.BlockSpec((1, block_q, 8), lambda bh, ki, qi: (bh, qi, 0))
    k_spec2 = pl.BlockSpec((1, block_k, d), functools.partial(kv, axis=1))
    kout_spec = pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, sk=sk,
                          num_q_blocks=num_q_blocks),
        grid=(bhq, num_k_blocks, num_q_blocks),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[kout_spec, kout_spec],
        # Per-query-head partials stay f32 so the GQA group-sum below
        # accumulates at full precision; cast to the input dtype after.
        out_shape=[
            jax.ShapeDtypeStruct((bhq, sk_p, d), jnp.float32),
            jax.ShapeDtypeStruct((bhq, sk_p, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, dvec)

    dq = dq[:, :sq, :].reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
    # Flat rows are (b, h)-major with h = kv_head*group + g, so the group
    # dim folds out contiguously before the f32 sum down to Hkv (a size-1
    # group sum is a free reshape, so no special case for MHA).
    dk = dk[:, :sk, :].reshape(b, hkv, group, sk, d).sum(2)
    dv = dv[:, :sk, :].reshape(b, hkv, group, sk, d).sum(2)
    return (dq, dk.transpose(0, 2, 1, 3).astype(kt.dtype),
            dv.transpose(0, 2, 1, 3).astype(vt.dtype))


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.lru_cache(maxsize=64)
def _make_flash(b, sq, sk, hq, hkv, d, block_q, block_k, interpret):
    """Per-(shape, blocks) custom_vjp instance. The static dims live in this
    closure, which lets the forward save its residuals in the flat padded
    layout — the backward reuses them directly instead of re-transposing
    and re-padding q/k/v (three full-tensor HBM copies per layer saved)."""

    @jax.custom_vjp
    def fa(q, k, v):
        return fwd(q, k, v)[0]

    def fwd(q, k, v):
        qt = _pad_seq(_to_flat(q), block_q)
        kt = _pad_seq(_to_flat(k), block_k)
        vt = _pad_seq(_to_flat(v), block_k)
        out_flat, lse = _flash_forward_flat(
            qt, kt, vt, hq, hkv, sq, sk, block_q, block_k, interpret)
        out = out_flat[:, :sq, :].reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
        return out, (qt, kt, vt, out_flat, lse)

    def bwd(residuals, g):
        qt, kt, vt, out_flat, lse = residuals
        return _flash_backward(
            qt, kt, vt, out_flat, lse, g,
            b, sq, sk, hq, hkv, d, block_q, block_k, interpret)

    fa.defvjp(fwd, bwd)
    return fa


def flash_attention(q, k, v, block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False):
    """Causal GQA attention, [B, S, H, D] in/out (ops/attention.py contract,
    standard positions). ``interpret=True`` runs the kernels in the Pallas
    interpreter (CPU tests)."""
    if block_q is None:
        block_q = DEFAULT_BLOCK_Q
    if block_k is None:
        block_k = DEFAULT_BLOCK_K
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    block_q = _pick_block(block_q, sq)
    block_k = _pick_block(block_k, sk)
    return _make_flash(b, sq, sk, hq, hkv, d, block_q, block_k,
                       interpret)(q, k, v)
