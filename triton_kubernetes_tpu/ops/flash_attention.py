"""Blockwise causal flash attention as a Pallas TPU kernel.

The einsum attention in ``ops/attention.py`` materializes the [Sq, Sk] logits
in HBM-sized intermediates; fine up to moderate S, but the HBM traffic grows
O(S^2). This kernel streams K/V blocks through VMEM with the online-softmax
recurrence (FlashAttention-2 style), keeping the working set at
O(block_q x block_k) and the accumulator in f32 VMEM scratch:

  grid = (batch, q_head, Sq/bq, Sk/bk), k-block innermost ->
    s    = q . k^T * scale          (MXU, f32 accumulate)
    m'   = max(m, rowmax(s));  p = exp(s - m');  c = exp(m - m')
    l    = l*c + rowsum(p);    acc = acc*c + p . v
  last k-block: out = acc / l

GQA maps query head h to KV head h // (Hq // Hkv) in the BlockSpec index
maps, so K/V blocks are fetched once per group without materializing the
head-repeated K/V (the einsum path pays that broadcast).

Backward: custom VJP that recomputes attention with the einsum formulation
(standard remat trade — no O(S^2) residuals saved from the forward; the
recompute is itself fused by XLA). A full flash backward kernel can replace
it without changing the API.

Causal skip: k-blocks strictly above the diagonal are predicated out with
``pl.when`` — their FLOPs are never issued, halving compute for long S.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import causal_attention

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, block_q: int, block_k: int,
                  sk: int, num_k_blocks: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # k-blocks fully above the causal diagonal contribute nothing: the
    # earliest query row of this q-block is qi*block_q, the first key of the
    # k-block is ki*block_k.
    @pl.when(ki * block_k <= qi * block_q + block_q - 1)
    def _compute():
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        # Causal + padding mask (padded keys past sk never contribute).
        s = jnp.where((q_pos >= k_pos) & (k_pos < sk), s, NEG_INF)

        m_prev = m_ref[:]                          # [bq, 128] lane-replicated
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)          # broadcast -> [bq, 128]
        p = jnp.exp(s - m_new[:, :1])               # [bq, bk]
        corr = jnp.exp(m_prev - m_new)              # [bq, 128]
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ki == num_k_blocks - 1)
    def _finish():
        # Fully-masked rows (q padding) have l == 0; emit 0, not NaN.
        l = l_ref[:, :1]
        o_ref[0] = jnp.where(
            l > 0, acc_ref[:] / l, 0.0).astype(o_ref.dtype)


def _flash_forward(q, k, v, block_q: int, block_k: int, interpret: bool):
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    group = hq // hkv
    scale = d ** -0.5

    # [B, S, H, D] -> [B*H, S, D]: one flat batch·head grid axis gives
    # Mosaic a clean (parallel, parallel, arbitrary) pipeline.
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(b * hq, sq, d)
    kt = jnp.transpose(k, (0, 2, 1, 3)).reshape(b * hkv, sk, d)
    vt = jnp.transpose(v, (0, 2, 1, 3)).reshape(b * hkv, sk, d)

    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, pad_k), (0, 0)))
    sq_p, sk_p = sq + pad_q, sk + pad_k
    num_k_blocks = sk_p // block_k

    grid = (b * hq, sq_p // block_q, num_k_blocks)
    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k,
        sk=sk, num_k_blocks=num_k_blocks)

    def kv_index(bh, qi, ki):
        # bh = b*Hq + h  ->  flat KV row b*Hkv + h//group.
        return ((bh // hq) * hkv + (bh % hq) // group, ki, 0)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hq, sq_p, d), q.dtype),
        scratch_shapes=[
            # m/l lane-replicated at 128 to match the f32 VMEM tile.
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)

    out = out[:, :sq, :].reshape(b, hq, sq, d)
    return jnp.transpose(out, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128,
                    interpret: bool = False):
    """Causal GQA attention, [B, S, H, D] in/out (ops/attention.py contract,
    standard positions). ``interpret=True`` runs the kernel in the Pallas
    interpreter (CPU tests)."""
    hq, hkv = q.shape[2], k.shape[2]
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    return _flash_forward(q, k, v, block_q, block_k, interpret)


def _fwd(q, k, v, block_q, block_k, interpret):
    return flash_attention(q, k, v, block_q, block_k, interpret), (q, k, v)


def _bwd(block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: causal_attention(q_, k_, v_), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
