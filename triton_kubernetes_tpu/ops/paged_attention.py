"""Ragged paged-attention decode over a block-paged KV cache.

The serving engine (``serve/``) keeps K/V in fixed-size **pages** drawn
from one static pool (``[num_blocks, block_size, Hkv, Dh]`` per layer)
instead of one contiguous ``[B, max_len, ...]`` strip per sequence. A
per-sequence **block table** maps logical block ``j`` (tokens
``j*block_size .. (j+1)*block_size-1``) to a physical page, so sequences
of wildly different lengths share the pool with zero reallocation and the
decode program never retraces as the batch churns — the shape of every
operand is fixed by ``(max_batch, blocks_per_seq, block_size)``, not by
the text.

This module is the op layer of that design, kept at the same altitude as
``ops/attention.py``:

* :func:`gather_pages` — K or V for a batch of sequences, gathered
  through their block tables into logical-token order;
* :func:`ragged_paged_attention` — one decode step of attention for a
  batch at **heterogeneous** positions (each query at its own
  ``length-1``), reusing :func:`~.attention.causal_attention`'s explicit
  position masking so logical slots past a sequence's length — including
  whole table entries that still point at the shared trash page —
  contribute *exactly zero* (``exp(NEG_INF - m)`` underflows to 0.0), not
  approximately zero.

Pool-sharing convention (pinned in tests/test_paged_attention.py):
**page 0 is the trash page**. Allocators never hand it out; unused block-
table entries point at it; batched scatters of inactive batch slots land
in it. Correctness never depends on its contents.

On TPU the gather lowers to HBM loads driven by the (SMEM-resident) block
table — the shape the "Ragged Paged Attention" kernel literature
prescribes (PAPERS.md); a Pallas kernel that fuses the gather with the
flash inner loop can swap in underneath this interface without touching
callers, exactly like ``ops/flash_attention.py`` under ``auto_attention``.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from .attention import causal_attention

# Physical page every allocator must reserve: the scatter/gather sink for
# padded block-table entries and inactive batch slots.
TRASH_PAGE = 0


def blocks_for(length: int, block_size: int) -> int:
    """Pages needed to hold ``length`` tokens (host-side helper)."""
    if length <= 0:
        return 0
    return -(-length // block_size)


def gather_pages(
    pages: jnp.ndarray,  # [N, bs, Hkv, D] — the physical pool
    block_tables: jnp.ndarray,  # [B, T] int32 physical page ids
) -> jnp.ndarray:
    """K or V in logical token order: [B, T*bs, Hkv, D].

    Row ``b``, token ``t`` is ``pages[block_tables[b, t // bs], t % bs]``.
    Entries past a sequence's written length (trash-page refs included)
    gather garbage by design — the caller masks by position.
    """
    n, bs, hkv, d = pages.shape
    b, t = block_tables.shape
    return pages[block_tables].reshape(b, t * bs, hkv, d)


def ragged_paged_attention(
    q: jnp.ndarray,  # [B, 1, Hq, D] — this step's query per sequence
    k_pages: jnp.ndarray,  # [N, bs, Hkv, D]
    v_pages: jnp.ndarray,  # [N, bs, Hkv, D]
    block_tables: jnp.ndarray,  # [B, T] int32
    lengths: jnp.ndarray,  # [B] int32 — tokens written, incl. this one
) -> jnp.ndarray:
    """One decode step of attention for a ragged batch: [B, 1, Hq, D].

    Sequence ``b``'s query sits at position ``lengths[b] - 1`` and attends
    to every written slot of its own pages (the current token's K/V must
    already be scattered in — same contract as ``generate.decode_step``,
    which writes the cache before attending). GQA comes along for free
    from ``causal_attention``.
    """
    b, t = block_tables.shape
    bs = k_pages.shape[1]
    k = gather_pages(k_pages, block_tables)
    v = gather_pages(v_pages, block_tables)
    # Logical key positions 0..T*bs-1; the causal test q_pos >= k_pos
    # excludes both future slots and everything past length-1 — garbage
    # in padded/trash pages never reaches the softmax support.
    q_positions = (lengths[:, None] - 1).astype(jnp.int32)  # [B, 1]
    k_positions = jnp.broadcast_to(
        jnp.arange(t * bs, dtype=jnp.int32), (b, t * bs))
    return causal_attention(q, k, v, q_positions, k_positions)


def scatter_token(
    k_pages: jnp.ndarray,  # [N, bs, Hkv, D]
    v_pages: jnp.ndarray,
    k: jnp.ndarray,  # [B, 1, Hkv, D] — this step's K per sequence
    v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, T] int32
    positions: jnp.ndarray,  # [B] int32 — slot each token lands in
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write one token's K/V per sequence into its page: (k_pages, v_pages).

    Inactive batch slots must carry an all-trash block table (and any
    position): their writes land in the trash page, colliding only with
    each other, never with an allocated page.
    """
    b = positions.shape[0]
    bs = k_pages.shape[1]
    page = block_tables[jnp.arange(b), positions // bs]  # [B]
    offset = positions % bs  # [B]
    k_pages = k_pages.at[page, offset].set(k[:, 0])
    v_pages = v_pages.at[page, offset].set(v[:, 0])
    return k_pages, v_pages
