"""Ragged paged-attention decode over a block-paged KV cache.

The serving engine (``serve/``) keeps K/V in fixed-size **pages** drawn
from one static pool (``[num_blocks, Hkv, block_size, Dh]`` per layer —
head-major, so one (page, head) tile is a ``[block_size, Dh]`` plane
whose trailing dims are exactly what Mosaic's (8, 128) tiling wants)
instead of one contiguous ``[B, max_len, ...]`` strip per sequence. A
per-sequence **block table** maps logical block ``j`` (tokens
``j*block_size .. (j+1)*block_size-1``) to a physical page, so sequences
of wildly different lengths share the pool with zero reallocation and the
decode program never retraces as the batch churns — the shape of every
operand is fixed by ``(max_batch, blocks_per_seq, block_size)``, not by
the text.

This module is the op layer of that design, kept at the same altitude as
``ops/attention.py``:

* :func:`gather_pages` — K or V for a batch of sequences, gathered
  through their block tables into logical-token order (dequantizing when
  the pool is int8);
* :func:`ragged_paged_attention` — one decode step of attention for a
  batch at **heterogeneous** positions (each query at its own
  ``length-1``). The **dense impl is the reference**: it reuses
  :func:`~.attention.causal_attention`'s explicit position masking so
  logical slots past a sequence's length — including whole table entries
  that still point at the shared trash page — contribute *exactly zero*
  (``exp(NEG_INF - m)`` underflows to 0.0), not approximately zero.
* the **fused Pallas kernel** (``impl="pallas"``) — the "Ragged Paged
  Attention" TPU shape (PAPERS.md): the block table rides as a
  scalar-prefetch operand, so each grid step's BlockSpec index map reads
  ``table[b, t]`` and Mosaic DMAs exactly that physical page HBM->VMEM —
  gather and flash-style online-softmax attention in ONE kernel, no
  ``[B, T*bs, ...]`` gathered intermediate in HBM. Blocks past a
  sequence's length are predicated out with ``pl.when`` (their FLOPs
  never issue — which is also what makes trash-page garbage *exactly*
  zero probability, matching the dense reference), and their index maps
  all resolve to the trash page, so the block-fetch pipeline sees the
  same index on every skipped step and elides the refetch — a short
  sequence in a wide table pays one trash-page fetch, not T. Int8 pools dequantize
  inside the kernel: the per-page-per-head scale is constant across a
  page, so it fuses into the logits/output as one scalar multiply per
  (page, head) — the full-precision pool never materializes anywhere.
  ``impl="pallas-interpret"`` runs the same kernel in the Pallas
  interpreter, which is how the CPU parity suite pins it against the
  dense reference (the flash-attention playbook).

Pool-sharing convention (pinned in tests/test_paged_attention.py):
**page 0 is the trash page**. Allocators never hand it out; unused block-
table entries point at it; batched scatters of inactive batch slots land
in it. Correctness never depends on its contents.

Quantized pools (``--kv-dtype int8``) carry a per-page-per-head f32
scale tensor next to the int8 pages. Scales are **anchored**: a page's
scale derives from its slot-0 token only (``ops/quantization.py``), so
for the same token values, prefill's whole-page scatter and decode's
token-at-a-time writes produce bitwise-identical pages — the quantizer
adds no write-order dependence on top of the forward-path numerics the
engine's preemption (recompute-on-readmit) contract already manages.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.jaxcompat import pallas_tpu
# NEG_INF is shared with the dense reference on purpose: the exact-zero
# masking contract (`exp(NEG_INF - m)` underflows to 0.0) must mean the
# same thing in both impls, or dense/pallas parity silently weakens.
from .attention import NEG_INF, causal_attention
from .quantization import (
    quantize_kv_pages,
    quantize_with_scale,
    token_kv_scale,
)

# Physical page every allocator must reserve: the scatter/gather sink for
# padded block-table entries and inactive batch slots.
TRASH_PAGE = 0

PAGED_IMPLS = ("dense", "pallas", "pallas-interpret")


def blocks_for(length: int, block_size: int) -> int:
    """Pages needed to hold ``length`` tokens (host-side helper)."""
    if length <= 0:
        return 0
    return -(-length // block_size)


def resolve_paged_impl(mode: str, platform: Optional[str] = None) -> str:
    """``ModelConfig.attention`` -> paged-decode impl name.

    The paged twin of ``models.llama.resolve_attention``: "dense" forces
    the reference einsum; "flash" forces the fused kernel (interpret
    mode off-TPU, so the SAME code path is CPU-testable);
    "flash-interpret" interprets everywhere (tests); "auto" picks the
    kernel on TPU and the dense reference elsewhere.
    """
    if mode == "dense":
        return "dense"
    if mode == "flash-interpret":
        return "pallas-interpret"
    platform = platform or jax.default_backend()
    if mode == "flash":
        return "pallas" if platform == "tpu" else "pallas-interpret"
    return "pallas" if platform == "tpu" else "dense"


def gather_pages(
    pages: jnp.ndarray,  # [N, Hkv, bs, D] — the physical pool
    block_tables: jnp.ndarray,  # [B, T] int32 physical page ids
    scale: Optional[jnp.ndarray] = None,  # [N, Hkv] f32 (int8 pools)
    dtype: Optional[jnp.dtype] = None,
) -> jnp.ndarray:
    """K or V in logical token order: [B, T*bs, Hkv, D].

    Row ``b``, token ``t`` is ``pages[block_tables[b, t // bs], :,
    t % bs]``. Entries past a sequence's written length (trash-page refs
    included) gather garbage by design — the caller masks by position.
    Int8 pools pass their ``scale`` and dequantize after the gather
    (only the gathered rows, never the whole pool).
    """
    n, hkv, bs, d = pages.shape
    b, t = block_tables.shape
    out = pages[block_tables]  # [B, T, Hkv, bs, D]
    if scale is not None:
        s = scale[block_tables]  # [B, T, Hkv]
        out = out.astype(jnp.float32) * s[:, :, :, None, None]
        out = out.astype(dtype or jnp.float32)
    return jnp.transpose(out, (0, 1, 3, 2, 4)).reshape(b, t * bs, hkv, d)


def ragged_paged_attention(
    q: jnp.ndarray,  # [B, 1, Hq, D] — this step's query per sequence
    k_pages: jnp.ndarray,  # [N, Hkv, bs, D] (activation dtype or int8)
    v_pages: jnp.ndarray,  # [N, Hkv, bs, D]
    block_tables: jnp.ndarray,  # [B, T] int32
    lengths: jnp.ndarray,  # [B] int32 — tokens written, incl. this one
    k_scale: Optional[jnp.ndarray] = None,  # [N, Hkv] f32 (int8 pools)
    v_scale: Optional[jnp.ndarray] = None,
    impl: str = "dense",
) -> jnp.ndarray:
    """One decode step of attention for a ragged batch: [B, 1, Hq, D].

    Sequence ``b``'s query sits at position ``lengths[b] - 1`` and attends
    to every written slot of its own pages (the current token's K/V must
    already be scattered in — same contract as ``generate.decode_step``,
    which writes the cache before attending). GQA comes along for free
    from ``causal_attention``. ``impl`` picks the dense reference or the
    fused Pallas kernel (see :func:`resolve_paged_impl`).
    """
    if impl not in PAGED_IMPLS:
        raise ValueError(
            f"impl must be one of {PAGED_IMPLS}, got {impl!r}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    if impl != "dense":
        return _ragged_paged_attention_pallas(
            q, k_pages, v_pages, block_tables, lengths, k_scale, v_scale,
            interpret=(impl == "pallas-interpret"))
    b, t = block_tables.shape
    bs = k_pages.shape[2]
    k = gather_pages(k_pages, block_tables, k_scale, q.dtype)
    v = gather_pages(v_pages, block_tables, v_scale, q.dtype)
    # Logical key positions 0..T*bs-1; the causal test q_pos >= k_pos
    # excludes both future slots and everything past length-1 — garbage
    # in padded/trash pages never reaches the softmax support.
    q_positions = (lengths[:, None] - 1).astype(jnp.int32)  # [B, 1]
    k_positions = jnp.broadcast_to(
        jnp.arange(t * bs, dtype=jnp.int32), (b, t * bs))
    return causal_attention(q, k, v, q_positions, k_positions)


def ragged_verify_attention(
    q: jnp.ndarray,  # [B, S, Hq, D] — S consecutive queries per sequence
    k_pages: jnp.ndarray,  # [N, Hkv, bs, D]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, T] int32
    lengths: jnp.ndarray,  # [B] int32 — tokens written incl. row 0's
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    impl: str = "dense",
) -> jnp.ndarray:
    """One verify step of attention for a ragged batch at ``S``
    positions per sequence: [B, S, Hq, D] — the multi-query widening of
    :func:`ragged_paged_attention` that speculative decoding scores its
    ``spec_k + 1`` proposed positions with, in ONE call.

    Row ``j`` of sequence ``b`` sits at position ``lengths[b] - 1 + j``
    and attends every written slot up to and including its own — so row
    ``j`` sees the draft tokens of rows ``< j`` (their K/V must already
    be scattered in, the :func:`scatter_span` contract) and is blind to
    rows ``> j``: exactly the causal context plain decode would have
    given it, which is why an accepted row's logits reproduce the
    non-speculative step bitwise.

    Implementation: the dense reference flattens the S queries into S
    independent batch rows sharing the sequence's block table at
    staggered lengths and runs the UNCHANGED single-query path. The
    Pallas impls run the fused verify kernel instead: ONE grid pass per
    (sequence, KV head) scores all S staggered rows against the paged
    pool — the pages are fetched once per block, not S times. Parity is
    BITWISE, not approximate: each row's online-softmax updates are the
    exact f32 op sequence the single-query decode kernel runs for that
    row (rows of a dot_general are independent reductions, and a block
    fully masked for a shorter row is an exact no-op — ``p = exp(NEG_INF
    - m)`` underflows to 0.0, ``corr = exp(0) = 1.0``), which is what
    keeps spec ON==OFF and ``paged_rewind``'s byte-exact guarantees
    intact on the fused path.
    """
    if impl not in PAGED_IMPLS:
        raise ValueError(
            f"impl must be one of {PAGED_IMPLS}, got {impl!r}")
    if impl != "dense":
        return _ragged_verify_attention_pallas(
            q, k_pages, v_pages, block_tables, lengths, k_scale, v_scale,
            interpret=(impl == "pallas-interpret"))
    b, s, hq, d = q.shape
    t = block_tables.shape[1]
    qf = q.reshape(b * s, 1, hq, d)
    tables_f = jnp.repeat(block_tables, s, axis=0)  # [B*S, T]
    lens_f = (lengths[:, None]
              + jnp.arange(s, dtype=jnp.int32)[None, :]).reshape(-1)
    out = ragged_paged_attention(qf, k_pages, v_pages, tables_f, lens_f,
                                 k_scale, v_scale, impl=impl)
    return out.reshape(b, s, hq, d)


def paged_prefill_attention(
    q: jnp.ndarray,  # [1, C, Hq, D] — one chunk's rotary-applied queries
    k_pages: jnp.ndarray,  # [N, Hkv, bs, D] (activation dtype or quantized)
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,  # [T] int32 — the sequence's full table
    offset: jnp.ndarray,  # scalar int32 — absolute position of q's row 0
    k_scale: Optional[jnp.ndarray] = None,  # [N, Hkv] f32
    v_scale: Optional[jnp.ndarray] = None,
    impl: str = "dense",
) -> jnp.ndarray:
    """Chunked-prefill attention straight out of the paged pool:
    [1, C, Hq, D] for C queries at absolute positions ``offset ..
    offset + C - 1``, attending every written slot of the sequence's
    pages (this chunk's K/V included — the ``scatter_chunk``-first
    contract of ``models.paged.paged_prefill_chunk``).

    The dense impl is the reference and is exactly the historical
    chain: full-width :func:`gather_pages` + explicit-position
    ``causal_attention``. The Pallas impls fuse that gather and the
    attention into one grid — the block table steers each (KV head,
    block) step's page DMA, blocks past the chunk's last written token
    are predicated out and steered to the trash page, and quantized
    pools dequantize per (page, head) inside the kernel — so the
    ``[1, T*bs, Hkv, D]`` gathered intermediate never exists in HBM.
    The per-window *scatter* stays a separate XLA op by design: it
    writes O(C) tokens while the gather reads O(T*bs), and fusing it
    would turn the kernel's read-only page pipeline into a
    read-modify-write over the whole pool.
    """
    if impl not in PAGED_IMPLS:
        raise ValueError(
            f"impl must be one of {PAGED_IMPLS}, got {impl!r}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    if impl != "dense":
        return _paged_prefill_attention_pallas(
            q, k_pages, v_pages, block_table, offset, k_scale, v_scale,
            interpret=(impl == "pallas-interpret"))
    t = block_table.shape[0]
    bs = k_pages.shape[2]
    c = q.shape[1]
    kk = gather_pages(k_pages, block_table[None], k_scale, q.dtype)
    vv = gather_pages(v_pages, block_table[None], v_scale, q.dtype)
    positions = (offset + jnp.arange(c, dtype=jnp.int32))[None]  # [1, C]
    k_positions = jnp.arange(t * bs, dtype=jnp.int32)[None]  # [1, T*bs]
    return causal_attention(q, kk, vv, positions, k_positions)


def table_slots(
    block_tables: jnp.ndarray,  # [B, T] int32
    positions: jnp.ndarray,  # [B] or [B, S] int32
    block_size: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(physical page, slot offset) for per-sequence token positions —
    THE logical-position-to-pool-slot mapping, shared by every write
    path and by the verify step's undo capture/rewind (which must
    target exactly the slots the writes hit — two copies of this rule
    would be a silent-corruption hazard).

    Positions past the table's coverage resolve to the trash page:
    XLA's gather would otherwise CLAMP the logical block to the
    table's last entry, a real page (speculative pad tokens near the
    model-length window end are the case that hits this).
    """
    b, t = block_tables.shape
    blk = positions // block_size
    idx = jnp.arange(b, dtype=jnp.int32).reshape(
        (b,) + (1,) * (positions.ndim - 1))
    page = jnp.where(
        blk < t,
        block_tables[idx, jnp.minimum(blk, t - 1)],
        TRASH_PAGE)
    return page, positions % block_size


def scatter_token(
    k_pages: jnp.ndarray,  # [N, Hkv, bs, D]
    v_pages: jnp.ndarray,
    k: jnp.ndarray,  # [B, 1, Hkv, D] — this step's K per sequence
    v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, T] int32
    positions: jnp.ndarray,  # [B] int32 — slot each token lands in
    k_scale: Optional[jnp.ndarray] = None,  # [N, Hkv] f32 (int8 pools)
    v_scale: Optional[jnp.ndarray] = None,
):
    """Write one token's K/V per sequence into its page.

    Returns ``(k_pages, v_pages)`` — or ``(k_pages, v_pages, k_scale,
    v_scale)`` when the pool is quantized. Quantized writes follow the
    anchored-scale rule: a token landing in a page's slot 0 *sets* the
    page's scale from its own amplitude; any other slot quantizes
    against the stored scale (clamped) — so, for the same token values,
    incremental decode writes reproduce exactly what a whole-page
    prefill re-quantization produces (``ops/quantization.py``).

    Inactive batch slots must carry an all-trash block table (and any
    position): their writes land in the trash page, colliding only with
    each other, never with an allocated page. Positions past the
    table's coverage scatter to the trash page too (the
    :func:`table_slots` rule).
    """
    bs = k_pages.shape[2]
    page, offset = table_slots(block_tables, positions, bs)  # [B], [B]
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    if k_scale is None:
        k_pages = k_pages.at[page, :, offset].set(
            k[:, 0].astype(k_pages.dtype))
        v_pages = v_pages.at[page, :, offset].set(
            v[:, 0].astype(v_pages.dtype))
        return k_pages, v_pages
    first = (offset == 0)[:, None]  # [B, 1] — this token anchors its page
    qd = k_pages.dtype  # int8 or fp8: the anchored-scale rule is shared
    new_ks = jnp.where(first, token_kv_scale(k[:, 0], qd), k_scale[page])
    new_vs = jnp.where(first, token_kv_scale(v[:, 0], qd), v_scale[page])
    k_pages = k_pages.at[page, :, offset].set(
        quantize_with_scale(k[:, 0], new_ks[:, :, None], qd))
    v_pages = v_pages.at[page, :, offset].set(
        quantize_with_scale(v[:, 0], new_vs[:, :, None], qd))
    return (k_pages, v_pages,
            k_scale.at[page].set(new_ks), v_scale.at[page].set(new_vs))


def scatter_span(
    k_pages: jnp.ndarray,  # [N, Hkv, bs, D]
    v_pages: jnp.ndarray,
    k: jnp.ndarray,  # [B, S, Hkv, D] — S consecutive tokens per sequence
    v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, T] int32
    start: jnp.ndarray,  # [B] int32 — slot of each sequence's token 0
    k_scale: Optional[jnp.ndarray] = None,  # [N, Hkv] f32 (quantized)
    v_scale: Optional[jnp.ndarray] = None,
):
    """Write ``S`` consecutive tokens per sequence: token ``j`` lands at
    position ``start[b] + j`` — the multi-token write of the speculative
    verify step.

    Deliberately implemented as ``S`` :func:`scatter_token` calls in
    position order (``S`` is static and small — ``spec_k + 1``), NOT as
    one batched scatter: token-at-a-time writes are exactly what
    non-speculative decode issues, so the quantized pool's anchored
    scales — where a token landing in slot 0 *sets* its page's scale
    and later slots quantize against it — come out bitwise identical to
    the plain-decode byte stream. That identity is what the engine's
    exact-output parity contract stands on (docs/guide/serving.md
    §Speculative decoding).
    """
    s = k.shape[1]
    out = (k_pages, v_pages) if k_scale is None \
        else (k_pages, v_pages, k_scale, v_scale)
    for j in range(s):
        if len(out) == 2:
            kp, vp = out
            ks = vs = None
        else:
            kp, vp, ks, vs = out
        out = scatter_token(kp, vp, k[:, j:j + 1], v[:, j:j + 1],
                            block_tables, start + j, ks, vs)
    return out


def scatter_chunk(
    k_pages: jnp.ndarray,  # [N, Hkv, bs, D]
    v_pages: jnp.ndarray,
    k: jnp.ndarray,  # [1, C, Hkv, D] — a page-aligned chunk's K
    v: jnp.ndarray,
    window_table: jnp.ndarray,  # [C // bs] int32 physical pages
    k_scale: Optional[jnp.ndarray] = None,  # [N, Hkv] f32 (int8 pools)
    v_scale: Optional[jnp.ndarray] = None,
):
    """Write one page-aligned chunk's K/V into its ``C // bs`` pages.

    The chunked-prefill sibling of :func:`scatter_token`: a whole
    window of ``C`` tokens (``C`` a multiple of the block size) lands
    page-plane-transposed in the pages ``window_table`` names. Returns
    ``(k_pages, v_pages)`` — or ``(k_pages, v_pages, k_scale, v_scale)``
    when the pool is quantized, where every written page's scale is
    re-anchored from its own slot-0 token (``quantize_kv_pages``), the
    exact rule decode's incremental writes follow, so chunked and
    whole-prompt prefill produce bitwise-identical quantized pages for
    the same token values.

    Chunk tokens past the real length (a right-padded final window)
    scatter pad garbage exactly as whole-prompt prefill does: masked out
    of every later attention's support, then overwritten slot by slot by
    decode.
    """
    n, hkv, bs, d = k_pages.shape
    w = window_table.shape[0]
    _, c, _, _ = k.shape
    if c != w * bs:
        raise ValueError(
            f"chunk of {c} tokens does not cover window_table's "
            f"{w} pages of {bs} slots")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    # [1, C, Hkv, D] -> [w, Hkv, bs, D]: split tokens into pages, then
    # swap heads ahead of slots (the head-major page plane).
    kw = jnp.transpose(k[0].reshape(w, bs, hkv, d), (0, 2, 1, 3))
    vw = jnp.transpose(v[0].reshape(w, bs, hkv, d), (0, 2, 1, 3))
    if k_scale is None:
        return (k_pages.at[window_table].set(kw.astype(k_pages.dtype)),
                v_pages.at[window_table].set(vw.astype(v_pages.dtype)))
    qk, sk = quantize_kv_pages(kw, k_pages.dtype)
    qv, sv = quantize_kv_pages(vw, v_pages.dtype)
    return (k_pages.at[window_table].set(qk),
            v_pages.at[window_table].set(qv),
            k_scale.at[window_table].set(sk),
            v_scale.at[window_table].set(sv))


# ---------------------------------------------------------------------------
# Fused Pallas kernel
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _pallas_ns():
    """(pl, pltpu, CompilerParams) — resolved lazily so importing the
    dense path (every model import) never touches jax.experimental."""
    return pallas_tpu()


def _round_up(x: int, m: int) -> int:
    # Local copy (not flash_attention's): importing that module here
    # would eagerly load jax.experimental.pallas on every model import.
    return ((x + m - 1) // m) * m


def _ragged_decode_kernel(bt_ref, len_ref, *rest,
                          bs: int, num_blocks: int, sm_scale: float,
                          quantized: bool):
    """Grid (B, Hkv, T), T innermost/arbitrary: online-softmax over the
    logical blocks of one sequence for one KV head's query group.

    ``bt_ref``/``len_ref`` are the scalar-prefetch operands,
    SMEM-resident — the block table already steered this step's
    ``k_ref``/``v_ref`` BlockSpecs at the physical page, so the kernel
    body only ever sees [bs, D] tiles of its own sequence. The int8
    pool's per-(page, head) scales arrive as (1, 1, 1, 1) blocks steered
    by the SAME index map — a 4-byte fetch per grid step, never the
    whole [num_blocks, Hkv] tensor in SMEM (which would scale with pool
    size, not batch size).
    """
    pl, _, _ = _pallas_ns()
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, \
            acc_ref = rest
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[b]

    # Blocks at or past the sequence's length hold pad/trash garbage and
    # their COMPUTE is skipped outright — contribution exactly zero, the
    # same contract the dense reference meets via NEG_INF masking. (The
    # pipeline's block fetch is steered to the trash page by the index
    # map instead, where consecutive same-index steps elide the DMA —
    # pl.when predicates the kernel body, never the fetch.)
    @pl.when(t * bs < length)
    def _compute():
        q = q_ref[0, 0]  # [G8, D]
        k = k_ref[0, 0]  # [bs, D] (int8 when quantized)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k.astype(q.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [G8, bs]
        if quantized:
            # Per-page-per-head scale is constant over the tile: the
            # dequant collapses to one scalar on the logits, steered
            # here by the same block-table index map as the page DMA.
            s = s * ks_ref[0, 0, 0, 0]
        k_pos = t * bs + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(k_pos < length, s, NEG_INF)

        m_prev = m_ref[:]                           # [G8, 128]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [G8, 1]
        m_new = jnp.maximum(m_prev, m_cur)          # [G8, 128]
        p = jnp.exp(s - m_new[:, :1])               # [G8, bs] f32
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        vf = v.astype(jnp.float32 if quantized else q.dtype)
        pv = jax.lax.dot_general(
            p.astype(vf.dtype), vf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [G8, D]
        if quantized:
            pv = pv * vs_ref[0, 0, 0, 0]
        acc_ref[:] = acc_ref[:] * corr[:, :1] + pv
        m_ref[:] = m_new

    @pl.when(t == num_blocks - 1)
    def _finish():
        # l == 0 only for an inactive slot (length 0, every block
        # skipped): its output is defined-zero garbage the scheduler
        # discards; the guard keeps it NaN-free.
        l = l_ref[:, :1]
        o_ref[0, 0] = jnp.where(
            l > 0, acc_ref[:] / l, 0.0).astype(o_ref.dtype)


def _ragged_paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                   lengths, k_scale, v_scale,
                                   interpret: bool) -> jnp.ndarray:
    pl, pltpu, CompilerParams = _pallas_ns()
    b, _, hq, d = q.shape
    n, hkv, bs, _ = k_pages.shape
    t = block_tables.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    # Head h = kv_head * group + g (the causal_attention grouping): fold
    # the group onto the sublane axis, padded to the f32 tile height so
    # Mosaic gets a legal [G8, D] row block. Padded rows are zero
    # queries — finite softmax, garbage output, sliced off below.
    g8 = _round_up(group, 8)
    q4 = q[:, 0].reshape(b, hkv, group, d)
    if g8 != group:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, g8 - group), (0, 0)))

    quantized = k_scale is not None
    kernel = functools.partial(
        _ragged_decode_kernel, bs=bs, num_blocks=t,
        sm_scale=d ** -0.5, quantized=quantized)

    # Index maps receive (grid..., *scalar_prefetch_refs); the page
    # lookup bt[b, t] is THE fused gather — Mosaic's pipeline DMAs that
    # page (and only that page) into VMEM for grid step (b, h, t).
    # Blocks past the sequence's length (whose compute the kernel
    # predicates out) are steered to the trash page so every skipped
    # step presents the SAME block index and the pipeline elides the
    # refetch. The head-major pool layout makes each (page, head) block
    # a clean [bs, D] trailing plane (the Mosaic tiling constraint).
    # Int8 scales ride as (1, 1, 1, 1) blocks through the same index
    # map: the per-step fetch is one f32, and the footprint never
    # scales with num_blocks (a scalar-prefetched [N, Hkv] tensor
    # would — SMEM is KBs, production pools are millions of pages).
    def kv_index(b, h, t, *refs):
        live = t * bs < refs[1][b]
        return (jnp.where(live, refs[0][b, t], TRASH_PAGE), h, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, g8, d), lambda b, h, t, *refs: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, bs, d), kv_index),
        pl.BlockSpec((1, 1, bs, d), kv_index),
    ]
    operands = [q4, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, 1, 1), kv_index),
            pl.BlockSpec((1, 1, 1, 1), kv_index),
        ]
        operands += [k_scale[:, :, None, None], v_scale[:, :, None, None]]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, t),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, g8, d), lambda b, h, t, *refs: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g8, 128), jnp.float32),  # m, lane-replicated
            pltpu.VMEM((g8, 128), jnp.float32),  # l
            pltpu.VMEM((g8, d), jnp.float32),    # acc
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g8, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)
    return out[:, :, :group, :].reshape(b, hq, d)[:, None]


def _fold_heads(q: jnp.ndarray, hkv: int, group: int, rows8: int
                ) -> jnp.ndarray:
    """[B, S, Hq, D] -> [B, Hkv, S*group (padded to rows8), D]: head
    ``h = kv_head * group + g`` lands at row ``s * group + g`` of its KV
    head's plane — the multi-query generalization of the decode kernel's
    sublane fold. Padded rows are zero queries: finite softmax, garbage
    output, sliced off by the caller."""
    b, s, hq, d = q.shape
    qf = q.reshape(b, s, hkv, group, d)
    qf = jnp.transpose(qf, (0, 2, 1, 3, 4)).reshape(b, hkv, s * group, d)
    if rows8 != s * group:
        qf = jnp.pad(qf, ((0, 0), (0, 0), (0, rows8 - s * group), (0, 0)))
    return qf


def _prefill_chunk_kernel(bt_ref, off_ref, *rest,
                          bs: int, num_blocks: int, chunk: int,
                          group: int, sm_scale: float, quantized: bool):
    """Grid (Hkv, T), T innermost/arbitrary: fused gather + causal
    attention for one prefill chunk's C queries against the sequence's
    whole paged prefix. Query row ``r`` is (token ``r // group``, group
    member ``r % group``) at absolute position ``offset + r // group``;
    blocks past the chunk's last written token (``offset + C``) are
    predicated out and their fetches steered to the trash page."""
    pl, _, _ = _pallas_ns()
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, \
            acc_ref = rest
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    t = pl.program_id(1)
    offset = off_ref[0]

    @pl.when(t == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(t * bs < offset + chunk)
    def _compute():
        q = q_ref[0]          # [CG8, D]
        k = k_ref[0, 0]       # [bs, D]
        s = jax.lax.dot_general(
            q, k.astype(q.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if quantized:
            s = s * ks_ref[0, 0, 0, 0]
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        q_pos = offset + row // group  # padded rows: past-the-end, sliced
        k_pos = t * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, :1])
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        vf = v_ref[0, 0].astype(jnp.float32 if quantized else q.dtype)
        pv = jax.lax.dot_general(
            p.astype(vf.dtype), vf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if quantized:
            pv = pv * vs_ref[0, 0, 0, 0]
        acc_ref[:] = acc_ref[:] * corr[:, :1] + pv
        m_ref[:] = m_new

    @pl.when(t == num_blocks - 1)
    def _finish():
        l = l_ref[:, :1]
        o_ref[0] = jnp.where(
            l > 0, acc_ref[:] / l, 0.0).astype(o_ref.dtype)


def _paged_prefill_attention_pallas(q, k_pages, v_pages, block_table,
                                    offset, k_scale, v_scale,
                                    interpret: bool) -> jnp.ndarray:
    pl, pltpu, CompilerParams = _pallas_ns()
    _, c, hq, d = q.shape
    n, hkv, bs, _ = k_pages.shape
    t = block_table.shape[0]
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    cg8 = _round_up(c * group, 8)
    qf = _fold_heads(q, hkv, group, cg8)[0]  # [Hkv, CG8, D]

    quantized = k_scale is not None
    kernel = functools.partial(
        _prefill_chunk_kernel, bs=bs, num_blocks=t, chunk=c, group=group,
        sm_scale=d ** -0.5, quantized=quantized)

    # The chunk attends nothing past its own last written token
    # (offset + C - 1): later table entries are future/unwritten pages,
    # steered to the trash page and predicated out — same trick, chunk
    # edition, of the decode kernel's past-length elision.
    def kv_index(h, t, *refs):
        live = t * bs < refs[1][0] + c
        return (jnp.where(live, refs[0][t], TRASH_PAGE), h, 0, 0)

    in_specs = [
        pl.BlockSpec((1, cg8, d), lambda h, t, *refs: (h, 0, 0)),
        pl.BlockSpec((1, 1, bs, d), kv_index),
        pl.BlockSpec((1, 1, bs, d), kv_index),
    ]
    operands = [qf, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, 1, 1), kv_index),
            pl.BlockSpec((1, 1, 1, 1), kv_index),
        ]
        operands += [k_scale[:, :, None, None], v_scale[:, :, None, None]]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(hkv, t),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, cg8, d), lambda h, t, *refs: (h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((cg8, 128), jnp.float32),  # m, lane-replicated
            pltpu.VMEM((cg8, 128), jnp.float32),  # l
            pltpu.VMEM((cg8, d), jnp.float32),    # acc
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((hkv, cg8, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(block_table.astype(jnp.int32),
      jnp.asarray(offset, jnp.int32).reshape(1), *operands)
    out = out[:, :c * group].reshape(hkv, c, group, d)
    return jnp.transpose(out, (1, 0, 2, 3)).reshape(1, c, hq, d)


def _verify_kernel(bt_ref, len_ref, *rest,
                   bs: int, num_blocks: int, spec_rows: int, group: int,
                   sm_scale: float, quantized: bool):
    """Grid (B, Hkv, T), T innermost/arbitrary: ALL ``spec_rows``
    staggered verify queries of one sequence's KV head group in one
    pass. Query row ``r`` is (stagger ``r // group``, group member
    ``r % group``) at position ``lengths[b] - 1 + r // group``; a block
    is computed if ANY row attends it (``t*bs < lengths[b] +
    spec_rows - 1``), and rows it is fully masked for see an exact
    online-softmax no-op — which is what makes each row bitwise equal to
    the single-query decode kernel at that row's length (the rewind
    contract)."""
    pl, _, _ = _pallas_ns()
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, \
            acc_ref = rest
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[b]

    @pl.when(t * bs < length + spec_rows - 1)
    def _compute():
        q = q_ref[0, 0]       # [SG8, D]
        k = k_ref[0, 0]       # [bs, D]
        s = jax.lax.dot_general(
            q, k.astype(q.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if quantized:
            s = s * ks_ref[0, 0, 0, 0]
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        q_pos = length - 1 + row // group
        k_pos = t * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, :1])
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        vf = v_ref[0, 0].astype(jnp.float32 if quantized else q.dtype)
        pv = jax.lax.dot_general(
            p.astype(vf.dtype), vf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if quantized:
            pv = pv * vs_ref[0, 0, 0, 0]
        acc_ref[:] = acc_ref[:] * corr[:, :1] + pv
        m_ref[:] = m_new

    @pl.when(t == num_blocks - 1)
    def _finish():
        l = l_ref[:, :1]
        o_ref[0, 0] = jnp.where(
            l > 0, acc_ref[:] / l, 0.0).astype(o_ref.dtype)


def _ragged_verify_attention_pallas(q, k_pages, v_pages, block_tables,
                                    lengths, k_scale, v_scale,
                                    interpret: bool) -> jnp.ndarray:
    pl, pltpu, CompilerParams = _pallas_ns()
    b, s, hq, d = q.shape
    n, hkv, bs, _ = k_pages.shape
    t = block_tables.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    sg8 = _round_up(s * group, 8)
    qf = _fold_heads(q, hkv, group, sg8)  # [B, Hkv, SG8, D]

    quantized = k_scale is not None
    kernel = functools.partial(
        _verify_kernel, bs=bs, num_blocks=t, spec_rows=s, group=group,
        sm_scale=d ** -0.5, quantized=quantized)

    # A block is fetched if the LONGEST row (stagger S-1, at length
    # lengths[b] + S - 1 keys) attends it; shorter rows experience an
    # exact no-op for the trailing blocks. Everything past that steers
    # to the trash page, decode-kernel style.
    def kv_index(b, h, t, *refs):
        live = t * bs < refs[1][b] + (s - 1)
        return (jnp.where(live, refs[0][b, t], TRASH_PAGE), h, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, sg8, d), lambda b, h, t, *refs: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, bs, d), kv_index),
        pl.BlockSpec((1, 1, bs, d), kv_index),
    ]
    operands = [qf, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, 1, 1), kv_index),
            pl.BlockSpec((1, 1, 1, 1), kv_index),
        ]
        operands += [k_scale[:, :, None, None], v_scale[:, :, None, None]]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, t),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, sg8, d), lambda b, h, t, *refs: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((sg8, 128), jnp.float32),  # m, lane-replicated
            pltpu.VMEM((sg8, 128), jnp.float32),  # l
            pltpu.VMEM((sg8, d), jnp.float32),    # acc
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, sg8, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)
    out = out[:, :, :s * group].reshape(b, hkv, s, group, d)
    return jnp.transpose(out, (0, 2, 1, 3, 4)).reshape(b, s, hq, d)
