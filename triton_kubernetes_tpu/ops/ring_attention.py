"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context path (BASELINE stretch workloads; no reference analog —
SURVEY.md §5 records the reference has no sequence scaling at all). Each
device on the ``seq`` mesh axis holds a contiguous sequence shard of Q, K, V.
K/V blocks rotate around the ring via ``lax.ppermute`` (neighbor exchange on
the ICI torus — the cheapest collective TPUs have) while every device
accumulates its queries' attention over each visiting block with the online
(flash) softmax merge, in f32. After ``n_shards`` steps every Q block has
seen every KV block exactly once: the result is bitwise-equivalent math to
dense causal attention, with per-device memory O(S/n) instead of O(S).

Communication-compute overlap note: the ppermute is issued as part of the
scan body, so XLA's latency-hiding scheduler can overlap the next block's
transfer with the current block's matmuls.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .attention import NEG_INF


def _block_flash(
    q: jnp.ndarray,  # [B, Sq, Hq, D]
    k: jnp.ndarray,  # [B, Sk, Hkv, D]
    v: jnp.ndarray,
    q_pos: jnp.ndarray,  # [Sq]
    k_pos: jnp.ndarray,  # [Sk]
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One KV block's contribution: (block_max, block_sumexp, block_out).

    block_max/sumexp: [B, Hkv, G, Sq] f32; block_out: [B, Sq, Hkv, G, D] f32
    (unnormalized, scaled by exp(logits - block_max))."""
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    qg = q.reshape(b, sq, hkv, g, d)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32)
    logits = logits * (d ** -0.5)
    mask = q_pos[None, None, None, :, None] >= k_pos[None, None, None, None, :]
    logits = jnp.where(mask, logits, NEG_INF)
    m = logits.max(axis=-1)  # [B, Hkv, G, Sq]
    p = jnp.exp(logits - m[..., None])
    # Zero fully-masked rows (m == NEG_INF would give exp(0)=1 per entry).
    p = jnp.where(mask, p, 0.0)
    s = p.sum(axis=-1)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", p, v.astype(jnp.float32))
    return m, s, o


def ring_attention_inner(
    q: jnp.ndarray,  # [B, S_loc, Hq, D] — local shard
    k: jnp.ndarray,  # [B, S_loc, Hkv, D]
    v: jnp.ndarray,
    axis_name: str,
    positions: Optional[jnp.ndarray] = None,  # [S_loc] local token positions
) -> jnp.ndarray:
    """Body to run inside shard_map; ``axis_name`` is the sequence axis.

    When ``positions`` is given, each shard's q/k positions come from it and
    the k positions *rotate with the KV blocks* — no ``lax.axis_index``
    anywhere, which is what lets this nest inside the pipeline's
    partial-manual stage map (axis-index lowering inside a nested manual
    computation trips the sdy verifier under grad). Without ``positions``
    the classic derivation from the axis index is used (top-level callers).
    """
    from ..utils.jaxcompat import axis_size

    n = axis_size(axis_name)
    b, s_loc, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    if positions is None:
        idx = lax.axis_index(axis_name)
        q_pos = idx * s_loc + jnp.arange(s_loc, dtype=jnp.int32)
    else:
        q_pos = positions.astype(jnp.int32)

    m0 = jnp.full((b, hkv, g, s_loc), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, hkv, g, s_loc), dtype=jnp.float32)
    o0 = jnp.zeros((b, s_loc, hkv, g, d), dtype=jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, _):
        k_blk, v_blk, k_pos, m, l, o = carry
        bm, bs, bo = _block_flash(q, k_blk, v_blk, q_pos, k_pos)
        new_m = jnp.maximum(m, bm)
        alpha = jnp.exp(m - new_m)
        beta = jnp.exp(bm - new_m)
        l = l * alpha + bs * beta
        # [B, Sq, Hkv, G, 1] scaling of the f32 accumulator
        o = o * jnp.moveaxis(alpha, 3, 1)[..., None] \
            + bo * jnp.moveaxis(beta, 3, 1)[..., None]
        # Positions ride the ring with their blocks, so no device ever
        # needs to know which block it holds.
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        k_pos = lax.ppermute(k_pos, axis_name, perm)
        return (k_blk, v_blk, k_pos, new_m, l, o), None

    (k_f, v_f, p_f, m, l, o), _ = lax.scan(
        step, (k, v, q_pos, m0, l0, o0), None, length=n)
    del k_f, v_f, p_f
    out = o / jnp.moveaxis(l, 3, 1)[..., None]
    return out.reshape(b, s_loc, hq, d).astype(q.dtype)


def make_ring_attention(
    mesh: Optional[Mesh],
    seq_axis: str = "seq",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    nested: bool = False,
):
    """Returns attention(q, k, v) -> out, shard_mapped over the mesh.

    q/k/v layout: [batch over ``batch_axes``, seq over ``seq_axis``, heads
    over ``head_axis``, head_dim replicated]. Everything except the ring
    exchange is embarrassingly parallel across the other axes.

    ``nested=True`` builds the shard_map against the ambient mesh with only
    these axes manual, so it can nest inside an outer partial-manual
    shard_map (the pipeline's stage map) — an explicit mesh would conflict
    with the outer context's Manual stage axis.
    """
    batch_part = tuple(batch_axes) or None  # () -> replicated batch
    spec = P(batch_part, seq_axis, head_axis, None)
    pos_spec = P(batch_part, seq_axis)
    kwargs = dict(check_vma=False)
    if nested:
        kwargs["axis_names"] = set(batch_axes) | {seq_axis} | (
            {head_axis} if head_axis else set())
    else:
        kwargs["mesh"] = mesh

    from ..utils.jaxcompat import shard_map as _shard_map

    sm_nopos = _shard_map(
        lambda q, k, v: ring_attention_inner(q, k, v, seq_axis),
        in_specs=(spec, spec, spec), out_specs=spec, **kwargs)
    # Positions-operand variant: positions are [B, S] standard ranges; the
    # local [B_loc, S_loc] shard's first row is every row's positions. Used
    # under the pipeline, where axis-index-free bodies are required.
    sm_pos = _shard_map(
        lambda q, k, v, p: ring_attention_inner(
            q, k, v, seq_axis, positions=p[0]),
        in_specs=(spec, spec, spec, pos_spec), out_specs=spec, **kwargs)

    def attn(q, k, v, positions=None):
        if positions is None:
            return sm_nopos(q, k, v)
        return sm_pos(q, k, v, positions)

    return attn
