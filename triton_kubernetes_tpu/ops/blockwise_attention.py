"""Causal flash attention in pure XLA: the pallas kernel's memory-faithful
twin for non-TPU backends and AOT memory contracts.

The dense einsum path materializes [Sq, Sk] f32 logits (plus their
cotangent in backward) — O(S²) HBM, exactly what ops/flash_attention.py
exists to avoid on TPU. This op implements the same algorithm (online-
softmax forward, recompute-from-logsumexp backward) with ``lax.scan``
over KV blocks instead of a Mosaic grid, entirely in XLA HLO:

* forward: scan over [block_k]-sized KV blocks carrying the running
  (max, sumexp, unnormalized out) — peak temp O(Sq · block_k);
* ``jax.custom_vjp`` saves only (q, k, v, out, lse) — WITHOUT it, scan AD
  would stash every block's probabilities and re-create the O(S²) buffer
  it is meant to avoid;
* backward: one scan recomputing each block's p = exp(logits − lse),
  accumulating dq in the carry and emitting per-block dk/dv.

Uses: the CPU lowering for AOT memory contracts (tests/test_flagship_aot.py
compiles the training step with this attention so ``memory_analysis``
reflects the TPU flash program's streaming profile, not an interpret-mode
artifact that inflates temps to full-score scale), and a long-context-safe
fallback wherever the pallas kernel is unavailable. Exactness is pinned
against the dense path in tests/test_ops.py (forward and grads, GQA
included).

Reference analog: the reference's CUDA flash/memory-efficient attention
fallbacks; here the algorithm is expressed once in XLA and once in pallas
(ops/flash_attention.py) with the pallas docstring's same two-pass
backward. Positions follow the model contract ([B, S] int32 global,
models/llama.py AttentionFn); like the auto ring path this assumes
broadcast positions (identical across batch rows) — packed-sequence
callers need the dense path or their own kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .attention import NEG_INF

DEFAULT_BLOCK_K = 1024


def _norm_positions(positions, s: int) -> jnp.ndarray:
    if positions is None:
        return jnp.arange(s, dtype=jnp.int32)
    pos = jnp.asarray(positions)
    if pos.ndim == 2:  # [B, S] broadcast contract — every row identical
        pos = pos[0]
    return pos.astype(jnp.int32)


def _kv_blocks(k, v, k_pos, block_k: int):
    """Pad Sk to a block multiple and reshape to leading-block stacks.
    Padded keys get position INT32_MAX so the causal mask (q >= k) always
    excludes them."""
    b, sk, hkv, d = k.shape
    pad = (-sk) % block_k
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        k_pos = jnp.concatenate(
            [k_pos, jnp.full((pad,), jnp.iinfo(jnp.int32).max, jnp.int32)])
    nb = (sk + pad) // block_k
    kb = k.reshape(b, nb, block_k, hkv, d).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, nb, block_k, hkv, d).transpose(1, 0, 2, 3, 4)
    return kb, vb, k_pos.reshape(nb, block_k)


def _block_logits(q5, k_blk, q_pos, k_pos, scale):
    """Masked f32 logits [B, Hkv, G, Sq, bk] for one KV block (the shared
    forward/backward recompute step — flash's defining trade).

    The causal mask is an ADDITIVE 2D [Sq, bk] term, not a broadcast
    boolean: XLA (CPU especially) hoists loop-invariant per-block masks
    out of the scan into a stacked buffer, and a pred broadcast over the
    head dims stacks at [nb, B, H, G, Sq, bk] — 64 GiB at Mixtral shapes.
    The 2D f32 adder stacks 16x smaller and fuses into the logits add.
    NEG_INF is finite (-1e30), so downstream exp() of masked entries is
    exactly 0.0 without a second mask application."""
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k_blk
                        ).astype(jnp.float32) * scale
    adder = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, NEG_INF)
    return logits + adder[None, None, None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def blockwise_attention(q, k, v, positions=None,
                        block_k: int = DEFAULT_BLOCK_K):
    """[B, S, Hq, D] causal attention, GQA via Hq % Hkv == 0."""
    out, _ = _forward(q, k, v, positions, block_k)
    return out


def _forward(q, k, v, positions, block_k):
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    scale = d ** -0.5
    q_pos = _norm_positions(positions, sq)
    k_pos = _norm_positions(positions, sk) if positions is not None \
        else jnp.arange(sk, dtype=jnp.int32)
    bk = min(block_k, sk)
    q5 = q.reshape(b, sq, hkv, g, d)
    kb, vb, kpb = _kv_blocks(k, v, k_pos, bk)

    def step(carry, xs):
        m, l, o = carry
        k_blk, v_blk, kp = xs
        logits = _block_logits(q5, k_blk, q_pos, kp, scale)
        bm = logits.max(axis=-1)  # [B, Hkv, G, Sq]
        # Masked entries: exp(NEG_INF - bm) == 0 for any finite bm. A row
        # fully masked in THIS block gives bm = NEG_INF and p = 1s, but
        # its beta = exp(NEG_INF - new_m) zeroes the contribution (block
        # 0 always holds the self-key, so new_m is finite from step 0).
        p = jnp.exp(logits - bm[..., None])
        bs = p.sum(axis=-1)
        bo = jnp.einsum("bhgqk,bkhd->bqhgd", p, v_blk.astype(jnp.float32))
        new_m = jnp.maximum(m, bm)
        alpha = jnp.exp(m - new_m)
        beta = jnp.exp(bm - new_m)
        l = l * alpha + bs * beta
        o = o * jnp.moveaxis(alpha, 3, 1)[..., None] \
            + bo * jnp.moveaxis(beta, 3, 1)[..., None]
        return (new_m, l, o), None

    m0 = jnp.full((b, hkv, g, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, g, sq), jnp.float32)
    o0 = jnp.zeros((b, sq, hkv, g, d), jnp.float32)
    (m, l, o), _ = lax.scan(step, (m0, l0, o0), (kb, vb, kpb))
    out = (o / jnp.moveaxis(l, 3, 1)[..., None]).reshape(
        b, sq, hq, d).astype(q.dtype)
    lse = m + jnp.log(l)  # [B, Hkv, G, Sq]
    return out, lse


def _fwd(q, k, v, positions, block_k):
    out, lse = _forward(q, k, v, positions, block_k)
    return out, (q, k, v, positions, out, lse)


def _bwd(block_k, res, dout):
    q, k, v, positions, out, lse = res
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    scale = d ** -0.5
    q_pos = _norm_positions(positions, sq)
    k_pos = _norm_positions(positions, sk) if positions is not None \
        else jnp.arange(sk, dtype=jnp.int32)
    bk = min(block_k, sk)
    q5 = q.reshape(b, sq, hkv, g, d)
    do5 = dout.astype(jnp.float32).reshape(b, sq, hkv, g, d)
    # D_i = <dout_i, out_i> — the softmax-jacobian diagonal term, computed
    # once (flash2 backward preprocessing).
    dsum = jnp.einsum("bqhgd,bqhgd->bhgq",
                      do5, out.astype(jnp.float32).reshape(
                          b, sq, hkv, g, d))
    kb, vb, kpb = _kv_blocks(k, v, k_pos, bk)

    def step(dq_acc, xs):
        k_blk, v_blk, kp = xs
        logits = _block_logits(q5, k_blk, q_pos, kp, scale)
        # exp(NEG_INF - lse) == 0: masked and padded entries drop out.
        p = jnp.exp(logits - lse[..., None])
        dv_blk = jnp.einsum("bhgqk,bqhgd->bkhd", p, do5)
        dp = jnp.einsum("bqhgd,bkhd->bhgqk", do5,
                        v_blk.astype(jnp.float32))
        ds = p * (dp - dsum[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhgqk,bkhd->bqhgd", ds,
                                     k_blk.astype(jnp.float32))
        dk_blk = jnp.einsum("bhgqk,bqhgd->bkhd", ds, q5.astype(jnp.float32))
        return dq_acc, (dk_blk, dv_blk)

    dq0 = jnp.zeros((b, sq, hkv, g, d), jnp.float32)
    dq, (dkb, dvb) = lax.scan(step, dq0, (kb, vb, kpb))
    dk = dkb.transpose(1, 0, 2, 3, 4).reshape(b, -1, hkv, d)[:, :sk]
    dv = dvb.transpose(1, 0, 2, 3, 4).reshape(b, -1, hkv, d)[:, :sk]
    return (dq.reshape(b, sq, hq, d).astype(q.dtype),
            dk.astype(k.dtype), dv.astype(v.dtype), None)


blockwise_attention.defvjp(_fwd, _bwd)
