"""Int8 quantization primitives shared by the weight and KV-cache paths.

Two consumers, one math module (the ops-layer altitude of
``ops/attention.py``):

* **Weights** (``models.llama.quantize_weights``): per-channel symmetric
  int8 over the contraction axis of each big matmul — the scale is exact
  (computed from the full weight once, at quantize time), so
  dequantization error is pure rounding, bounded by ``scale/2`` per
  element.
* **KV pages** (``ops.paged_attention`` / ``models.paged``): per-page-
  per-head scales with the **anchored-scale** rule: a page's scale is a
  function of the page's FIRST token slot only. That makes the
  quantizer *write-order invariant* — given the same token K/V values,
  a page filled by one whole-page prefill scatter and the same page
  filled token-by-token by decode steps hold bitwise-identical int8
  values, because every token is quantized independently against the
  same anchor scale. A max-over-written-slots scale would NOT have this
  property (growing the scale re-rounds already-written slots through
  their dequantized values, making the result depend on arrival order).
  This is what the serving engine's preemption contract leans on: a
  preempted sequence is re-prefilled from prompt + tokens-so-far, and
  anchoring removes the quantizer itself as a divergence source — the
  only residual difference is the one the *unquantized* engine already
  carries (re-prefill's dense forward vs decode's ragged forward differ
  in f32 reduction order), which the pinned churn tests bound at
  argmax level (tests/test_serve.py). The cost is clamp risk when a
  later token's amplitude exceeds the anchor's; :data:`KV_SCALE_HEADROOM`
  trades one bit of precision for headroom against it.

Symmetric quantization throughout (no zero point): K/V and weight
distributions are near-zero-mean, and symmetric int8 keeps
dequantization a single multiply — fusable into the attention logits as
one scalar per (page, head) because the scale is constant across the
page (``(q . k_int8) * scale == q . (k_int8 * scale)``).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp

# Symmetric int8 range: +-127, never -128 (keeps abs() exact and the
# scale math symmetric).
INT8_MAX = 127.0
# Anchored KV scales quantize later tokens against the first token's
# amplitude; 2x headroom halves the clamp probability at the cost of
# one effective bit (|q| <= 63 for the anchor token itself).
KV_SCALE_HEADROOM = 2.0
# Floor on every scale: an all-zero anchor must not produce a 0 scale
# (division blows up); with the floor, later tokens simply saturate —
# deterministic on both the prefill and decode write paths.
MIN_SCALE = 1e-8


def quantize_with_scale(x: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Symmetric int8 with a caller-supplied (broadcastable) scale:
    ``clip(round(x / scale), -127, 127)``. The one quantizer every
    write path shares — bitwise agreement between prefill and decode
    writes reduces to agreeing on ``scale``."""
    q = jnp.round(x.astype(jnp.float32) / scale)
    return jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)


def quantize_int8(x: jnp.ndarray,
                  axis: Union[int, Tuple[int, ...]],
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-channel symmetric int8: scale = amax over ``axis`` / 127.

    Returns (int8 values, f32 scale with ``axis`` kept as size-1 dims —
    broadcastable straight back onto the values). The weight-quant
    primitive: exact amax, no headroom.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / INT8_MAX, MIN_SCALE)
    return quantize_with_scale(x, scale), scale


def dequantize_int8(q: jnp.ndarray, scale: jnp.ndarray,
                    dtype: jnp.dtype) -> jnp.ndarray:
    """``q * scale`` in f32, cast to ``dtype`` (int8 -> f32 is exact;
    the cast to bf16 matches the precision of the unquantized
    weight-at-use cast)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def token_kv_scale(kv: jnp.ndarray) -> jnp.ndarray:
    """Anchor scale of one token's K or V: [..., Hkv, D] -> f32 [..., Hkv].

    ``amax over D * HEADROOM / 127``, floored — the scale a page adopts
    when this token lands in its slot 0, and the same formula
    :func:`quantize_kv_pages` applies to slot 0 of every page, so both
    write paths derive identical scales from identical token values.
    """
    amax = jnp.max(jnp.abs(kv.astype(jnp.float32)), axis=-1)
    return jnp.maximum(amax * KV_SCALE_HEADROOM / INT8_MAX, MIN_SCALE)


def quantize_kv_pages(pages: jnp.ndarray,
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Whole-page anchored quantization: [..., Hkv, bs, D] exact K or V
    (the head-major page layout of ``ops.paged_attention``) ->
    (int8 pages, f32 scales [..., Hkv]).

    The scale comes from slot 0 only (every *allocated* page's slot 0
    holds a real token — allocators hand out ``ceil(length/bs)`` pages,
    so a page with no real slot-0 token is never allocated); slots past
    the written length quantize pad garbage with the same scale, exactly
    as decode will overwrite them later.
    """
    scale = token_kv_scale(pages[..., :, 0, :])  # [..., Hkv]
    q = quantize_with_scale(pages, scale[..., :, None, None])
    return q, scale


def kv_quant_error(q: jnp.ndarray, scale: jnp.ndarray,
                   exact: jnp.ndarray,
                   mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Scalar mean relative dequantization error (device scalar, jit-
    safe): mean |dequant - exact| / mean |exact|, over the elements
    ``mask`` selects (all, when None). What the
    ``tk8s_serve_quant_error`` gauge reports per quantized prefill —
    callers mask to the *real* token slots, or pad garbage and
    trash-page writes (quantized against garbage anchors) would dominate
    the number an operator reads for quantization health."""
    exact = exact.astype(jnp.float32)
    dq = q.astype(jnp.float32) * scale
    if mask is None:
        return (jnp.mean(jnp.abs(dq - exact))
                / (jnp.mean(jnp.abs(exact)) + 1e-12))
    mask = mask.astype(jnp.float32)
    return (jnp.sum(jnp.abs(dq - exact) * mask)
            / (jnp.sum(jnp.abs(exact) * mask) + 1e-12))
