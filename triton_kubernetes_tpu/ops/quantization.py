"""Int8 quantization primitives shared by the weight and KV-cache paths.

Two consumers, one math module (the ops-layer altitude of
``ops/attention.py``):

* **Weights** (``models.llama.quantize_weights``): per-channel symmetric
  int8 over the contraction axis of each big matmul — the scale is exact
  (computed from the full weight once, at quantize time), so
  dequantization error is pure rounding, bounded by ``scale/2`` per
  element.
* **KV pages** (``ops.paged_attention`` / ``models.paged``): per-page-
  per-head scales with the **anchored-scale** rule: a page's scale is a
  function of the page's FIRST token slot only. That makes the
  quantizer *write-order invariant* — given the same token K/V values,
  a page filled by one whole-page prefill scatter and the same page
  filled token-by-token by decode steps hold bitwise-identical int8
  values, because every token is quantized independently against the
  same anchor scale. A max-over-written-slots scale would NOT have this
  property (growing the scale re-rounds already-written slots through
  their dequantized values, making the result depend on arrival order).
  This is what the serving engine's preemption contract leans on: a
  preempted sequence is re-prefilled from prompt + tokens-so-far, and
  anchoring removes the quantizer itself as a divergence source — the
  only residual difference is the one the *unquantized* engine already
  carries (re-prefill's dense forward vs decode's ragged forward differ
  in f32 reduction order), which the pinned churn tests bound at
  argmax level (tests/test_serve.py). The cost is clamp risk when a
  later token's amplitude exceeds the anchor's; :data:`KV_SCALE_HEADROOM`
  trades one bit of precision for headroom against it.

Symmetric quantization throughout (no zero point): K/V and weight
distributions are near-zero-mean, and symmetric int8 keeps
dequantization a single multiply — fusable into the attention logits as
one scalar per (page, head) because the scale is constant across the
page (``(q . k_int8) * scale == q . (k_int8 * scale)``).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp

# Symmetric int8 range: +-127, never -128 (keeps abs() exact and the
# scale math symmetric).
INT8_MAX = 127.0
# float8_e4m3fn's largest finite value. fp8 OVERFLOWS TO NAN on cast
# (it has no inf), so the quantizer clips to this BEFORE the cast —
# the fp8 twin of int8's clip-before-round.
FP8_MAX = 448.0
# Anchored KV scales quantize later tokens against the first token's
# amplitude; 2x headroom halves the clamp probability at the cost of
# one effective bit (|q| <= 63 for the anchor token itself).
KV_SCALE_HEADROOM = 2.0
# Floor on every scale: an all-zero anchor must not produce a 0 scale
# (division blows up); with the floor, later tokens simply saturate —
# deterministic on both the prefill and decode write paths.
MIN_SCALE = 1e-8


class Fp8UnavailableError(RuntimeError):
    """This jax build has no ``float8_e4m3fn`` — a loud typed failure
    for ``--kv-dtype/--weight-dtype fp8`` (and the tests' skip reason),
    never a silent fallback to a different dtype."""


def fp8_supported() -> bool:
    """Whether this jax exposes ``float8_e4m3fn`` (ml_dtypes-backed;
    present on jax>=0.4.x CPU builds, absent on some minimal installs)."""
    return hasattr(jnp, "float8_e4m3fn")


def fp8_dtype() -> jnp.dtype:
    """``float8_e4m3fn`` as a dtype, or :class:`Fp8UnavailableError`."""
    if not fp8_supported():
        raise Fp8UnavailableError(
            "this jax build has no float8_e4m3fn dtype; --kv-dtype/"
            "--weight-dtype fp8 need it (int8 and bf16 remain available)")
    return jnp.dtype(jnp.float8_e4m3fn)


def qmax_for(dtype: jnp.dtype) -> float:
    """Largest representable quantized magnitude for a storage dtype —
    the one number the anchored-scale formula and the clip share, so
    every write path derives identical scales per dtype."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.dtype(jnp.int8):
        return INT8_MAX
    if fp8_supported() and dtype == jnp.dtype(jnp.float8_e4m3fn):
        return FP8_MAX
    raise ValueError(f"no quantized range for dtype {dtype}")


def quantize_with_scale(x: jnp.ndarray, scale: jnp.ndarray,
                        dtype: jnp.dtype = jnp.int8) -> jnp.ndarray:
    """Symmetric quantization with a caller-supplied (broadcastable)
    scale. int8: ``clip(round(x / scale), -127, 127)``; fp8: ``clip(x /
    scale, -448, 448)`` cast (the cast itself rounds to the nearest
    representable — fp8's mantissa plays the role int8's round() does).
    The one quantizer every write path shares — bitwise agreement
    between prefill and decode writes reduces to agreeing on ``scale``.
    """
    dtype = jnp.dtype(dtype)
    qmax = qmax_for(dtype)
    q = x.astype(jnp.float32) / scale
    if dtype == jnp.dtype(jnp.int8):
        q = jnp.round(q)
    return jnp.clip(q, -qmax, qmax).astype(dtype)


def quantize_channelwise(x: jnp.ndarray,
                         axis: Union[int, Tuple[int, ...]],
                         dtype: jnp.dtype = jnp.int8,
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-channel symmetric quantization: scale = amax over ``axis`` /
    qmax(dtype).

    Returns (quantized values in ``dtype``, f32 scale with ``axis`` kept
    as size-1 dims — broadcastable straight back onto the values). The
    weight-quant primitive: exact amax, no headroom — so dequantization
    error is pure rounding, bounded by ``scale/2`` per element for int8
    and by fp8's 3-bit relative mantissa step for fp8.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / qmax_for(dtype), MIN_SCALE)
    return quantize_with_scale(x, scale, dtype), scale


def quantize_int8(x: jnp.ndarray,
                  axis: Union[int, Tuple[int, ...]],
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-channel symmetric int8: :func:`quantize_channelwise` at its
    historical dtype (the PR 11 call sites)."""
    return quantize_channelwise(x, axis, jnp.int8)


def dequantize_int8(q: jnp.ndarray, scale: jnp.ndarray,
                    dtype: jnp.dtype) -> jnp.ndarray:
    """``q * scale`` in f32, cast to ``dtype`` (int8 -> f32 is exact;
    the cast to bf16 matches the precision of the unquantized
    weight-at-use cast)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def token_kv_scale(kv: jnp.ndarray,
                   dtype: jnp.dtype = jnp.int8) -> jnp.ndarray:
    """Anchor scale of one token's K or V: [..., Hkv, D] -> f32 [..., Hkv].

    ``amax over D * HEADROOM / qmax(dtype)``, floored — the scale a page
    adopts when this token lands in its slot 0, and the same formula
    :func:`quantize_kv_pages` applies to slot 0 of every page, so both
    write paths derive identical scales from identical token values.
    """
    amax = jnp.max(jnp.abs(kv.astype(jnp.float32)), axis=-1)
    return jnp.maximum(amax * KV_SCALE_HEADROOM / qmax_for(dtype),
                       MIN_SCALE)


def quantize_kv_pages(pages: jnp.ndarray, dtype: jnp.dtype = jnp.int8,
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Whole-page anchored quantization: [..., Hkv, bs, D] exact K or V
    (the head-major page layout of ``ops.paged_attention``) ->
    (quantized pages in ``dtype`` — int8 or fp8 — and f32 scales
    [..., Hkv]).

    The scale comes from slot 0 only (every *allocated* page's slot 0
    holds a real token — allocators hand out ``ceil(length/bs)`` pages,
    so a page with no real slot-0 token is never allocated); slots past
    the written length quantize pad garbage with the same scale, exactly
    as decode will overwrite them later.
    """
    scale = token_kv_scale(pages[..., :, 0, :], dtype)  # [..., Hkv]
    q = quantize_with_scale(pages, scale[..., :, None, None], dtype)
    return q, scale


def kv_quant_error(q: jnp.ndarray, scale: jnp.ndarray,
                   exact: jnp.ndarray,
                   mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Scalar mean relative dequantization error (device scalar, jit-
    safe): mean |dequant - exact| / mean |exact|, over the elements
    ``mask`` selects (all, when None). What the
    ``tk8s_serve_quant_error`` gauge reports per quantized prefill —
    callers mask to the *real* token slots, or pad garbage and
    trash-page writes (quantized against garbage anchors) would dominate
    the number an operator reads for quantization health."""
    exact = exact.astype(jnp.float32)
    dq = q.astype(jnp.float32) * scale
    if mask is None:
        return (jnp.mean(jnp.abs(dq - exact))
                / (jnp.mean(jnp.abs(exact)) + 1e-12))
    mask = mask.astype(jnp.float32)
    return (jnp.sum(jnp.abs(dq - exact) * mask)
            / (jnp.sum(jnp.abs(exact) * mask) + 1e-12))


# --------------------------------------------------------------------------
# Quantized ARITHMETIC (matmul_dtype): storage quantization above says how
# weights live; this section makes them CONTRACT in low precision. The
# int8 path is W8A8: activations are quantized per-token (dynamic amax
# over the contraction axes), the dot runs int8 x int8 with int32
# accumulation (`preferred_element_type` — the MXU-native form), and both
# scales fold into a rank-1 f32 epilogue. No dequantized full-precision
# weight operand is ever materialized — the stored int8/fp8 tensor IS the
# dot operand, which is the whole memory/bandwidth point.


def resolve_matmul_dtype(mode: str, weight_quant: str,
                         platform: Optional[str] = None) -> str:
    """Resolve a ``--matmul-dtype`` knob to a concrete arithmetic path:
    ``"f32"`` (dequantize-then-full-precision einsum — the pinned
    reference) or ``"int8"``/``"fp8"`` (quantized arithmetic).

    ``"auto"`` picks quantized arithmetic only on TPU (where the MXU has
    native low-precision throughput) AND only when the weights are
    already stored quantized — so off-TPU, ``auto`` is bitwise-identical
    to ``f32``. Explicit ``int8``/``fp8`` demand matching storage and
    raise loudly otherwise (never a silent fallback).
    """
    if platform is None:
        import jax
        platform = jax.default_backend()
    if mode == "f32":
        return "f32"
    if mode in ("int8", "fp8"):
        if weight_quant != mode:
            raise ValueError(
                f"matmul_dtype {mode!r} needs weights stored in the same "
                f"dtype (weight_quant is {weight_quant!r}); quantize the "
                f"weights first (--weight-dtype {mode})")
        if mode == "fp8":
            fp8_dtype()  # loud Fp8UnavailableError on builds without it
        return mode
    if mode == "auto":
        if platform == "tpu" and weight_quant in ("int8", "fp8"):
            return weight_quant
        return "f32"
    raise ValueError(f"unknown matmul_dtype {mode!r}; "
                     f"know ('auto', 'f32', 'int8', 'fp8')")


def _parse_weight_spec(spec: str):
    """Split a two-operand einsum spec ``"x,w->out"`` into (x letters,
    w letters, out letters, contraction letters). The quantized path
    supports exactly the weight-matmul shape: every letter unique per
    operand, contraction letters shared by x and w, and the output =
    x's batch letters (in x order) + w's output letters (in w order) —
    which is what all the model's weight einsums look like."""
    lhs, out = spec.replace(" ", "").split("->")
    x_sub, w_sub = lhs.split(",")
    contract = tuple(c for c in x_sub if c in w_sub)
    if not contract:
        raise ValueError(f"spec {spec!r} has no contraction")
    x_batch = tuple(c for c in x_sub if c not in contract)
    w_out = tuple(c for c in w_sub if c not in contract)
    if out != "".join(x_batch) + "".join(w_out):
        raise ValueError(
            f"spec {spec!r} is not a weight matmul (want out = x-batch "
            f"letters then w-output letters)")
    if len(set(x_sub)) != len(x_sub) or len(set(w_sub)) != len(w_sub):
        raise ValueError(f"spec {spec!r} repeats a letter within an operand")
    return x_sub, w_sub, contract, x_batch, w_out


def quantized_einsum(spec: str, x: jnp.ndarray, q: jnp.ndarray,
                     scale: jnp.ndarray,
                     out_dtype: Optional[jnp.dtype] = None) -> jnp.ndarray:
    """``einsum(spec, x, dequant(q, scale))`` without the dequant.

    ``q``/``scale`` are a :func:`quantize_channelwise` pair (scale keeps
    the contraction axes as size-1 dims). The activation is quantized
    per-token to ``q.dtype`` — amax over its contraction axes — then the
    dot runs in low precision (int8 x int8 -> int32 accumulate; fp8 x
    fp8 -> f32 accumulate) and the epilogue multiplies by
    ``x_scale (x) w_scale`` in f32. The scale fold is EXACT (scales are
    constant along the contraction axes by construction); the only new
    error vs the dequant reference is the activation rounding.
    """
    x_sub, w_sub, contract, x_batch, w_out = _parse_weight_spec(spec)
    dtype = jnp.dtype(q.dtype)
    x_c_axes = tuple(x_sub.index(c) for c in contract)
    w_c_axes = tuple(w_sub.index(c) for c in contract)
    for a in w_c_axes:
        if scale.shape[a] != 1:
            raise ValueError(
                f"scale shape {scale.shape} is not per-output-channel for "
                f"spec {spec!r} (contraction axis {a} must be size 1)")
    xf = x.astype(jnp.float32)
    x_amax = jnp.max(jnp.abs(xf), axis=x_c_axes, keepdims=True)
    x_scale = jnp.maximum(x_amax / qmax_for(dtype), MIN_SCALE)
    xq = quantize_with_scale(xf, x_scale, dtype)
    acc_dtype = jnp.int32 if dtype == jnp.dtype(jnp.int8) else jnp.float32
    acc = jnp.einsum(spec, xq, q, preferred_element_type=acc_dtype)
    # Epilogue: x_scale broadcast over w's output dims, w_scale over x's
    # batch dims — both rank-expanded to the out layout (x batch letters
    # then w output letters).
    x_scale_out = jnp.squeeze(x_scale, axis=x_c_axes).reshape(
        tuple(x.shape[x_sub.index(c)] for c in x_batch)
        + (1,) * len(w_out))
    w_scale_out = jnp.squeeze(
        scale.astype(jnp.float32), axis=w_c_axes).reshape(
        (1,) * len(x_batch)
        + tuple(q.shape[w_sub.index(c)] for c in w_out))
    y = acc.astype(jnp.float32) * x_scale_out * w_scale_out
    return y.astype(out_dtype if out_dtype is not None else x.dtype)
