"""Rotary position embeddings (RoPE), Llama-3 style.

Tables are precomputed outside the scanned layer stack (they are shared by
every layer) and passed in, so the per-layer trace stays small. Positions are
explicit — required for sequence parallelism, where each shard's tokens start
at a nonzero global offset.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def rotary_tables(
    head_dim: int,
    max_positions: int,
    theta: float = 500_000.0,
    dtype: jnp.dtype = jnp.float32,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(cos, sin) tables of shape [max_positions, head_dim//2]."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = jnp.arange(max_positions, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rotary(
    x: jnp.ndarray,  # [B, S, H, D]
    cos: jnp.ndarray,  # [max_pos, D//2]
    sin: jnp.ndarray,
    positions: jnp.ndarray,  # [B, S] int32 global positions
) -> jnp.ndarray:
    """Rotate pairs (x[..., :D/2], x[..., D/2:]) — the "split-half" RoPE
    convention (matches Llama reference weights after permutation)."""
    c = cos[positions][:, :, None, :]  # [B, S, 1, D//2]
    s = sin[positions][:, :, None, :]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate([xf1 * c - xf2 * s, xf2 * c + xf1 * s], axis=-1)
    return out.astype(x.dtype)
