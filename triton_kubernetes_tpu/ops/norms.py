"""RMSNorm — computed in f32 regardless of input dtype (bf16 activations
lose too much precision in the variance reduction), cast back on the way out.
XLA fuses this into neighboring ops; no custom kernel needed."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * scale.astype(jnp.float32)).astype(dtype)
