"""Mixture-of-Experts layer (Mixtral-style top-k routing, GShard dispatch).

Expert parallelism is expressed the TPU way: expert-indexed weight tensors
``[E, ...]`` sharded over the ``expert`` mesh axis, with dispatch/combine as
einsums against one-hot capacity tensors. Under ``jit`` + NamedSharding, XLA
lowers those einsums to the router all-to-all over ICI (BASELINE config 5's
Mixtral-8x7B expert-parallel gate) — no hand-written collective needed.

Capacity-based routing (tokens beyond an expert's slot budget are dropped and
pass through the residual connection) keeps every shape static for XLA, which
is the whole game on TPU: dynamic per-expert token counts would force
recompilation or host round-trips.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def top_k_router(
    x: jnp.ndarray,  # [T, D]
    router_w: jnp.ndarray,  # [D, E]
    num_selected: int,
    capacity: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (dispatch [T,E,C] bool-ish f32, combine [T,E,C] f32, aux_loss).

    Slot assignment is priority-ordered: every token's first choice is
    seated before any token's second choice, matching GShard semantics.
    """
    t, _ = x.shape
    e = router_w.shape[1]
    logits = (x.astype(jnp.float32) @ router_w.astype(jnp.float32))  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = lax.top_k(probs, num_selected)  # [T, K]
    top_p = top_p / top_p.sum(axis=-1, keepdims=True)

    # Load-balancing aux loss (Switch/Mixtral): E * <frac routed> . <mean prob>
    first_choice = jax.nn.one_hot(top_i[:, 0], e, dtype=jnp.float32)
    frac_routed = first_choice.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux_loss = e * jnp.sum(frac_routed * mean_prob)

    dispatch = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    combine = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    counts = jnp.zeros((e,), dtype=jnp.int32)
    for j in range(num_selected):
        mask_j = jax.nn.one_hot(top_i[:, j], e, dtype=jnp.int32)  # [T, E]
        pos = jnp.cumsum(mask_j, axis=0) - 1 + counts[None, :]  # slot index
        counts = counts + mask_j.sum(axis=0)
        # mask_j is exactly one-hot per token, so this picks the position at
        # the chosen expert; one_hot of an index >= capacity is the zero row,
        # which is precisely the "token dropped" semantics.
        slot_idx = (pos * mask_j).sum(axis=-1)  # [T]
        slot = jax.nn.one_hot(slot_idx, capacity, dtype=jnp.float32)  # [T, C]
        d_j = mask_j.astype(jnp.float32)[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d_j
        combine = combine + d_j * top_p[:, j][:, None, None]
    return dispatch, combine, aux_loss


def moe_layer(
    x: jnp.ndarray,  # [B, S, D]
    params: Dict[str, jnp.ndarray],  # router [D,E], w1/w3 [E,D,F], w2 [E,F,D]
    num_selected: int = 2,
    capacity_factor: float = 1.25,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """SwiGLU experts; returns (y [B,S,D], aux_loss scalar)."""
    b, s, d = x.shape
    e = params["router"].shape[1]
    t = b * s
    capacity = max(1, int(capacity_factor * num_selected * t / e))
    x2 = x.reshape(t, d)
    dispatch, combine, aux = top_k_router(
        x2, params["router"], num_selected, capacity)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, x2.astype(jnp.float32))
    expert_in = expert_in.astype(x.dtype)
    h = jnp.einsum("ecd,edf->ecf", expert_in, params["w1"])
    g = jnp.einsum("ecd,edf->ecf", expert_in, params["w3"])
    h = jax.nn.silu(g.astype(jnp.float32)).astype(x.dtype) * h
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w2"])
    y2 = jnp.einsum("tec,ecd->td", combine, expert_out.astype(jnp.float32))
    return y2.reshape(b, s, d).astype(x.dtype), aux
