"""Mixture-of-Experts layer (Mixtral-style top-k routing, GShard dispatch).

Expert parallelism is expressed the TPU way: expert-indexed weight tensors
``[E, ...]`` sharded over the ``expert`` mesh axis, with dispatch/combine as
einsums against one-hot capacity tensors. Under ``jit`` + NamedSharding, XLA
lowers those einsums to the router all-to-all over ICI (BASELINE config 5's
Mixtral-8x7B expert-parallel gate) — no hand-written collective needed.

Capacity-based routing (tokens beyond an expert's slot budget are dropped and
pass through the residual connection) keeps every shape static for XLA, which
is the whole game on TPU: dynamic per-expert token counts would force
recompilation or host round-trips.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _route(
    x: jnp.ndarray,  # [T, D]
    router_w: jnp.ndarray,  # [D, E]
    num_selected: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Shared routing head for both dispatch paths: returns (top_p [T,K]
    renormalized gates, top_i [T,K] expert ids, aux_loss). One
    implementation so dense and sort dispatch can never diverge in routing
    decisions or the load-balancing loss."""
    e = router_w.shape[1]
    logits = (x.astype(jnp.float32) @ router_w.astype(jnp.float32))  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = lax.top_k(probs, num_selected)  # [T, K]
    top_p = top_p / top_p.sum(axis=-1, keepdims=True)

    # Load-balancing aux loss (Switch/Mixtral): E * <frac routed> . <mean prob>
    first_choice = jax.nn.one_hot(top_i[:, 0], e, dtype=jnp.float32)
    frac_routed = first_choice.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux_loss = e * jnp.sum(frac_routed * mean_prob)
    return top_p, top_i, aux_loss


def top_k_router(
    x: jnp.ndarray,  # [T, D]
    router_w: jnp.ndarray,  # [D, E]
    num_selected: int,
    capacity: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (dispatch [T,E,C] bool-ish f32, combine [T,E,C] f32, aux_loss).

    Slot assignment is priority-ordered: every token's first choice is
    seated before any token's second choice, matching GShard semantics.
    """
    t, _ = x.shape
    e = router_w.shape[1]
    top_p, top_i, aux_loss = _route(x, router_w, num_selected)

    dispatch = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    combine = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    counts = jnp.zeros((e,), dtype=jnp.int32)
    for j in range(num_selected):
        mask_j = jax.nn.one_hot(top_i[:, j], e, dtype=jnp.int32)  # [T, E]
        pos = jnp.cumsum(mask_j, axis=0) - 1 + counts[None, :]  # slot index
        counts = counts + mask_j.sum(axis=0)
        # mask_j is exactly one-hot per token, so this picks the position at
        # the chosen expert; one_hot of an index >= capacity is the zero row,
        # which is precisely the "token dropped" semantics.
        slot_idx = (pos * mask_j).sum(axis=-1)  # [T]
        slot = jax.nn.one_hot(slot_idx, capacity, dtype=jnp.float32)  # [T, C]
        d_j = mask_j.astype(jnp.float32)[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d_j
        combine = combine + d_j * top_p[:, j][:, None, None]
    return dispatch, combine, aux_loss


def sort_router(
    x: jnp.ndarray,  # [T, D]
    router_w: jnp.ndarray,  # [D, E]
    num_selected: int,
    capacity: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sort-based slot assignment: identical semantics to ``top_k_router``
    (priority-ordered GShard seating, same drops) without ever building the
    [T, E, C] one-hot tensors — those are O(T²) at fixed capacity factor
    and dominate HBM at Mixtral scale.

    Returns (token_idx [T*K], slot [T*K], gate [T*K], keep [T*K], aux):
    assignment i sends token ``token_idx[i]`` to flat expert-slot
    ``slot[i]`` (expert*C + position) with combine weight ``gate[i]``;
    ``keep`` masks assignments beyond capacity (dropped tokens).
    """
    t, _ = x.shape
    top_p, top_i, aux_loss = _route(x, router_w, num_selected)

    # Choice-major flattening (index j*T + t): a stable sort by expert then
    # seats every token's first choice before any token's second choice,
    # and ties within a choice by token id — exactly top_k_router's
    # priority order.
    flat_e = top_i.T.reshape(-1)  # [K*T]
    flat_p = top_p.T.reshape(-1)
    order = jnp.argsort(flat_e, stable=True)
    sorted_e = flat_e[order]
    # Position within each expert's group: index minus the group's start
    # (searchsorted on the already-sorted keys).
    idx = jnp.arange(t * num_selected, dtype=jnp.int32)
    group_start = jnp.searchsorted(sorted_e, sorted_e, side="left")
    pos = idx - group_start.astype(jnp.int32)
    keep = pos < capacity
    slot = sorted_e * capacity + jnp.minimum(pos, capacity - 1)
    token_idx = (order % t).astype(jnp.int32)
    return token_idx, slot.astype(jnp.int32), flat_p[order], keep, aux_loss


def _auto_dispatch_mode(t: int, e: int, capacity: int) -> str:
    """Two f32 [T, E, C] tensors; beyond ~64 MB the quadratic term is the
    layer's HBM high-water mark and sort dispatch wins (measured on v5e,
    scripts/tpu/bench_moe.py)."""
    return "sort" if 2 * 4 * t * e * capacity > 64 * 2**20 else "dense"


def _expert_mlp(expert_in, params, out_dtype):
    """[E, C, D] -> [E, C, D] SwiGLU per expert."""
    h = jnp.einsum("ecd,edf->ecf", expert_in, params["w1"])
    g = jnp.einsum("ecd,edf->ecf", expert_in, params["w3"])
    h = jax.nn.silu(g.astype(jnp.float32)).astype(out_dtype) * h
    return jnp.einsum("ecf,efd->ecd", h, params["w2"])


def moe_layer(
    x: jnp.ndarray,  # [B, S, D]
    params: Dict[str, jnp.ndarray],  # router [D,E], w1/w3 [E,D,F], w2 [E,F,D]
    num_selected: int = 2,
    capacity_factor: float = 1.25,
    dispatch_mode: str = "auto",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """SwiGLU experts; returns (y [B,S,D], aux_loss scalar).

    ``dispatch_mode``: ``"dense"`` = one-hot [T,E,C] einsum dispatch (lowers
    to clean all-to-alls under expert sharding; fine at small T·E·C),
    ``"sort"`` = argsort-over-expert-ids with scatter/gather (avoids the
    O(T²)-at-fixed-capacity-factor one-hots; wins at scale — see
    tests/test_ops.py equivalence and bench_moe.py), ``"auto"`` picks sort
    once the dense dispatch tensors would exceed ~64 MB.

    Network profile under expert sharding (verified on the compiled HLO,
    tests/test_parallel.py::test_moe_sort_dispatch_lowers_to_all_to_all):
    the sort path's scatter/gather lowers to the SAME all-to-all pattern as
    the dense einsums — identical collective op counts and bytes on an
    fsdp×expert mesh — so choosing sort trades no ICI bandwidth for its
    HBM win.
    """
    b, s, d = x.shape
    e = params["router"].shape[1]
    t = b * s
    capacity = max(1, int(capacity_factor * num_selected * t / e))
    x2 = x.reshape(t, d)

    if dispatch_mode == "auto":
        dispatch_mode = _auto_dispatch_mode(t, e, capacity)

    if dispatch_mode == "sort":
        token_idx, slot, gate, keep, aux = sort_router(
            x2, params["router"], num_selected, capacity)
        safe_slot = jnp.where(keep, slot, e * capacity)  # OOB -> dropped
        buf = jnp.zeros((e * capacity, d), dtype=x.dtype)
        expert_in = buf.at[safe_slot].set(
            x2[token_idx], mode="drop").reshape(e, capacity, d)
        expert_out = _expert_mlp(expert_in, params, x.dtype)
        contrib = expert_out.reshape(e * capacity, d)[slot].astype(
            jnp.float32)
        contrib = contrib * (gate * keep)[:, None]
        y2 = jnp.zeros((t, d), jnp.float32).at[token_idx].add(contrib)
        return y2.reshape(b, s, d).astype(x.dtype), aux

    if dispatch_mode != "dense":
        raise ValueError(
            f"dispatch_mode must be auto|dense|sort, got {dispatch_mode!r}")
    dispatch, combine, aux = top_k_router(
        x2, params["router"], num_selected, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x2.astype(jnp.float32))
    expert_out = _expert_mlp(expert_in.astype(x.dtype), params, x.dtype)
    y2 = jnp.einsum("tec,ecd->td", combine, expert_out.astype(jnp.float32))
    return y2.reshape(b, s, d).astype(x.dtype), aux
