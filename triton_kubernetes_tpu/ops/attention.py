"""Dense causal attention with grouped-query (GQA) support.

Pure einsum formulation: on TPU, XLA lowers the two einsums onto the MXU and
fuses the mask/softmax between them, which is already near-roofline for
moderate sequence lengths; ``ops/flash_attention.py`` provides the Pallas
blockwise kernel for long sequences. Softmax runs in f32 (bf16 logits
overflow/underflow long before that matters on the MXU inputs).

Positions are explicit so the same code serves the sequence-parallel path
(``ring_attention`` calls this per KV block with shifted key positions).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-but-finite: keeps fully-masked rows NaN-free


def auto_attention(platform: Optional[str] = None) -> Optional[Callable]:
    """Best full-sequence causal attention for the current backend.

    On TPU returns the Pallas flash kernel (ops/flash_attention.py) — the
    einsum path materializes [Sq, Sk] f32 logits in HBM, which dominates the
    step at training sequence lengths. Elsewhere returns None, i.e. the
    model's dense einsum default. Only valid for standard positions
    (0..S-1); sequence-parallel callers pass their own ring attention fn.
    """
    platform = platform or jax.default_backend()
    if platform == "tpu":
        from .flash_attention import flash_attention

        return lambda q, k, v, positions: flash_attention(q, k, v)
    return None


def causal_attention(
    q: jnp.ndarray,  # [B, Sq, Hq, D]
    k: jnp.ndarray,  # [B, Sk, Hkv, D]
    v: jnp.ndarray,  # [B, Sk, Hkv, D]
    q_positions: Optional[jnp.ndarray] = None,  # [B, Sq] int32
    k_positions: Optional[jnp.ndarray] = None,  # [B, Sk] int32
) -> jnp.ndarray:
    """Returns [B, Sq, Hq, D]. Token i attends to keys with pos <= pos_i."""
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    if q_positions is None:
        q_positions = jnp.broadcast_to(jnp.arange(sq, dtype=jnp.int32), (b, sq))
    if k_positions is None:
        k_positions = jnp.broadcast_to(jnp.arange(sk, dtype=jnp.int32), (b, sk))

    qg = q.reshape(b, sq, hkv, group, d)
    scale = d ** -0.5
    # [B, Hkv, G, Sq, Sk]
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32) * scale
    mask = (q_positions[:, None, None, :, None]
            >= k_positions[:, None, None, None, :])
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v)
    return out.reshape(b, sq, hq, d)
