"""triton_kubernetes_tpu — a TPU-native, multi-cloud Kubernetes cluster-manager framework.

A from-scratch rebuild of the capability set of ``gadkins/triton-kubernetes``
(reference: /root/reference, a ~11k-LoC Go CLI that provisions Rancher-based
Kubernetes clusters across 8 cloud providers by generating a Terraform JSON
document), re-designed TPU-first:

* the GCP provider path provisions **GKE TPU pod slices** (v5e/v5p/v6e node
  pools with ``tpu_topology`` placement) instead of CUDA GPU node pools;
* host bootstrap is a libtpu + JAX/XLA DaemonSet instead of docker/nvidia
  startup scripts;
* ICI mesh coordinates are surfaced as Kubernetes node labels so multi-host
  JAX (pjit/shard_map) jobs schedule slice-contiguously;
* a bundled MaxText-class workload stack (``models/``, ``ops/``, ``parallel/``,
  ``train/``) is the acceptance test for the provisioned infrastructure
  (BASELINE.md: Llama-3-8B >=40% MFU on a v5p-64 slice).

Layering mirrors the reference's five layers (SURVEY.md §1):

    L5  cli/        cobra/viper analog           (reference: cmd/)
    L4  workflows/  create/destroy/get flows     (reference: create/ destroy/ get/)
    L3  state/ + backends/  declarative doc      (reference: state/ backend/)
    L2  executor/   plan/apply engine            (reference: shell/)
    L1  modules/    provider resource graphs     (reference: terraform/modules/)

plus the new TPU-native layers with no reference analog:

    topology/   TPU slice topologies, ICI mesh labels, JobSet rendering
    models/ ops/ parallel/ train/   the bundled JAX workload stack
"""

__version__ = "0.1.0"
