"""``create manager`` workflow.

Reference analog: create/manager.go:29-151 — pick provider, name with
uniqueness check against backend.States(), provider config fn, confirmation,
set executor backend config, apply, persist-only-on-success.
"""

from __future__ import annotations

import re

from .common import WorkflowContext, WorkflowError
from .providers import MANAGER_PROVIDERS


def _validate_name(v) -> str | None:
    # Dashes only: '_' is the module-key delimiter (state/document.py).
    if not re.match(r"^[A-Za-z0-9][A-Za-z0-9-]*$", str(v)):
        return "name must be alphanumeric with dashes"
    return None


def new_manager(ctx: WorkflowContext) -> str:
    r = ctx.resolver
    provider = r.choose("manager_cloud_provider", "Cloud Provider",
                        [(p, p) for p in sorted(MANAGER_PROVIDERS)])
    name = r.value("name", "Cluster Manager Name", validate=_validate_name)

    if ctx.backend.exists(name):
        raise WorkflowError(
            f"A cluster manager named '{name}' already exists.")

    state = ctx.backend.state(name)
    MANAGER_PROVIDERS[provider](ctx, state, name)

    if not r.confirm("confirm", f"Proceed? This will create cluster manager '{name}'"):
        return ""

    state.set_backend_config(ctx.backend.executor_backend_config(name))
    ctx.executor.apply(state)
    # Commit-after-success: the doc is persisted only now
    # (create/manager.go:147-151).
    ctx.backend.persist(state)
    return name
