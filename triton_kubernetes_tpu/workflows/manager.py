"""``create manager`` workflow.

Reference analog: create/manager.go:29-151 — pick provider, name with
uniqueness check against backend.States(), provider config fn, confirmation,
set executor backend config, apply, persist-only-on-success.
"""

from __future__ import annotations

import re

from .common import WorkflowContext, WorkflowError
from .providers import MANAGER_PROVIDERS


def _validate_name(v) -> str | None:
    # Dashes only: '_' is the module-key delimiter (state/document.py).
    if not re.match(r"^[A-Za-z0-9][A-Za-z0-9-]*$", str(v)):
        return "name must be alphanumeric with dashes"
    return None


def new_manager(ctx: WorkflowContext) -> str:
    r = ctx.resolver
    provider = r.choose("manager_cloud_provider", "Cloud Provider",
                        [(p, p) for p in sorted(MANAGER_PROVIDERS)])
    name = r.value("name", "Cluster Manager Name", validate=_validate_name)

    if ctx.backend.exists(name):
        raise WorkflowError(
            f"A cluster manager named '{name}' already exists.")

    state = ctx.backend.state(name)
    MANAGER_PROVIDERS[provider](ctx, state, name)

    # Optional silent-config key: pick a real cloud driver instead of the
    # in-process simulator (e.g. `driver: local-k8s` stands up actual kind/
    # k3d clusters for the bare-metal provider — BASELINE config 1). Never
    # prompted: the default driver is always valid.
    if ctx.config.is_set("driver"):
        from ..executor.drivers import driver_names, normalize_driver_config

        try:
            cfg = normalize_driver_config(ctx.config.get("driver"))
        except ValueError as e:
            raise WorkflowError(str(e)) from e
        if cfg.get("name") not in driver_names():
            raise WorkflowError(
                f"unknown driver {cfg.get('name')!r} "
                f"(choices: {driver_names()})")
        state.set("driver", cfg)

    if not r.confirm("confirm", f"Proceed? This will create cluster manager '{name}'"):
        return ""

    state.set_backend_config(ctx.backend.executor_backend_config(name))
    ctx.executor.apply(state)
    # Commit-after-success: the doc is persisted only now
    # (create/manager.go:147-151).
    ctx.backend.persist(state)
    return name
