"""``repair node`` workflow — failure detection's consumer, closed loop.

The reference has no repair verb: its agents ride
``--restart=unless-stopped`` + Rancher reconciliation, and a genuinely
dead host is replaced by hand (destroy node, create node). ``get
cluster`` here already *names* that cycle for NotReady nodes
(workflows/get.py hint); this verb executes it: pick the dead node
(``--set hostname=...`` or auto-target from the same health sources the
hint reads), confirm, targeted destroy of its module, re-add the SAME
module config (same hostname, same machine shape), apply. The replacement
host runs the agent bootstrap again and re-registers with the manager,
clearing the stale-heartbeat NotReady.
"""

from __future__ import annotations

from .common import (
    WorkflowContext,
    WorkflowError,
    select_cluster,
    select_manager,
)
from .get import _node_health


def repair_node(ctx: WorkflowContext) -> str:
    r = ctx.resolver
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    _, cluster_key = select_cluster(ctx, state)
    nodes = state.nodes(cluster_key)
    if not nodes:
        raise WorkflowError("No nodes.")
    state.set_backend_config(ctx.backend.executor_backend_config(manager))

    if ctx.config.is_set("hostname"):
        hostname = ctx.config.get("hostname")
        if hostname not in nodes:
            raise WorkflowError(f"A node named '{hostname}', does not exist.")
    else:
        hostname = _pick_unhealthy(ctx, state, cluster_key, nodes)

    node_key = nodes[hostname]
    if not r.confirm("confirm",
                     f"Proceed? This will destroy and re-create node "
                     f"'{hostname}'"):
        return ""

    # Same module config back in: identical hostname, machine shape, and
    # registration wiring — a repair is a replacement, not a new node.
    node_cfg = dict(state.get(f"module.{node_key}"))
    ctx.executor.destroy(state, targets=[node_key])
    state.delete(f"module.{node_key}")
    # Persist the destroyed intermediate: if the re-create apply fails,
    # the doc must not claim a node that no longer exists.
    ctx.backend.persist(state)
    state.set(f"module.{node_key}", node_cfg)
    ctx.executor.apply(state)
    ctx.backend.persist(state)
    return node_key


def _pick_unhealthy(ctx: WorkflowContext, state, cluster_key: str,
                    nodes) -> str:
    """Auto-target: the NotReady node, from the same health sources the
    ``get cluster`` hint reads (live manager heartbeat, then driver/
    simulator view)."""
    try:
        outputs = ctx.executor.output(state, cluster_key)
    except Exception:
        outputs = {}
    health = _node_health(ctx, state, outputs.get("cluster_id"),
                          outputs.get("ca_checksum", "")) or {}
    dead = sorted(h for h, st in health.items()
                  if not st.get("ready") and h in nodes)
    if not dead:
        raise WorkflowError(
            "No unhealthy nodes detected — name the node to replace with "
            "--set hostname=<name> if you want to repair one anyway.")
    if len(dead) == 1:
        return dead[0]
    if ctx.non_interactive:
        raise WorkflowError(
            f"Multiple unhealthy nodes: {dead}. Repair one at a time with "
            "--set hostname=<name>.")
    return ctx.resolver.prompter.select(
        "Unhealthy node to repair", [(h, h) for h in dead])
