"""``repair {node,slice}`` workflows — failure detection's consumer, closed loop.

The reference has no repair verb: its agents ride
``--restart=unless-stopped`` + Rancher reconciliation, and a genuinely
dead host is replaced by hand (destroy node, create node). ``get
cluster`` here already *names* that cycle for NotReady nodes
(workflows/get.py hint); ``repair node`` executes it: pick the dead node
(``--set hostname=...`` or auto-target from the same health sources the
hint reads), confirm, targeted destroy of its module, re-add the SAME
module config (same hostname, same machine shape), apply. The replacement
host runs the agent bootstrap again and re-registers with the manager,
clearing the stale-heartbeat NotReady.

``repair slice`` is the TPU-native variant: on real v5e/v5p fleets the
dominant fault is a *preempted slice* (spot reclaim, defragmentation) —
all hosts of a pool vanish together and replacement is per-slice, not
per-host. The loop: detect preempted pools from the driver's cloud state,
cordon the surviving node objects, destroy + re-apply the pool's module
with its identical config, then verify the replacement carries the exact
ICI mesh coordinate labels (topology/labels.py) — a slice that comes back
with shuffled coordinates would break slice-contiguous scheduling
silently.
"""

from __future__ import annotations

from typing import Dict

from ..state import parse_cluster_key
from ..topology import SliceSpec, verify_slice_labels
from ..utils import metrics
from .common import (
    WorkflowContext,
    WorkflowError,
    select_cluster,
    select_manager,
)
from .get import _node_health


class HealthLookupError(WorkflowError):
    """No health source (live manager, driver view) could answer — which is
    NOT the same as "everything is healthy". Auto-targeting must refuse
    loudly rather than conclude there is nothing to repair."""


class NoUnhealthyNodesError(WorkflowError):
    """Health lookup succeeded and every node reports Ready — there is
    genuinely nothing to repair."""


class NoPreemptedSlicesError(WorkflowError):
    """The driver's cloud state records no preempted TPU slice pools."""


def _counted_repair(kind: str, fn, ctx: WorkflowContext) -> str:
    """Run a repair verb and record its outcome
    (``tk8s_repairs_total{kind,outcome}``): ``ok`` on success, ``aborted``
    when the operator declined the confirm, ``failed`` on any error —
    including the typed nothing-to-repair/blind-health cases, which an
    alerting rule watching repair failures should see."""
    try:
        result = fn(ctx)
    except BaseException:
        metrics.counter("tk8s_repairs_total").inc(kind=kind,
                                                  outcome="failed")
        raise
    metrics.counter("tk8s_repairs_total").inc(
        kind=kind, outcome="ok" if result else "aborted")
    return result


def repair_node(ctx: WorkflowContext) -> str:
    return _counted_repair("node", _repair_node, ctx)


def _repair_node(ctx: WorkflowContext) -> str:
    r = ctx.resolver
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    _, cluster_key = select_cluster(ctx, state)
    nodes = state.nodes(cluster_key)
    if not nodes:
        raise WorkflowError("No nodes.")
    state.set_backend_config(ctx.backend.executor_backend_config(manager))

    if ctx.config.is_set("hostname"):
        hostname = ctx.config.get("hostname")
        if hostname not in nodes:
            raise WorkflowError(f"A node named '{hostname}', does not exist.")
    else:
        hostname = _pick_unhealthy(ctx, state, cluster_key, nodes)

    node_key = nodes[hostname]
    if not r.confirm("confirm",
                     f"Proceed? This will destroy and re-create node "
                     f"'{hostname}'"):
        return ""

    # Same module config back in: identical hostname, machine shape, and
    # registration wiring — a repair is a replacement, not a new node.
    node_cfg = dict(state.get(f"module.{node_key}"))
    ctx.executor.destroy(state, targets=[node_key])
    state.delete(f"module.{node_key}")
    # Persist the destroyed intermediate: if the re-create apply fails,
    # the doc must not claim a node that no longer exists.
    ctx.backend.persist(state)
    state.set(f"module.{node_key}", node_cfg)
    ctx.executor.apply(state)
    ctx.backend.persist(state)
    return node_key


def _pick_unhealthy(ctx: WorkflowContext, state, cluster_key: str,
                    nodes) -> str:
    """Auto-target: the NotReady node, from the same health sources the
    ``get cluster`` hint reads (live manager heartbeat, then driver/
    simulator view). Raises :class:`HealthLookupError` when no source
    answered and :class:`NoUnhealthyNodesError` when all nodes are Ready —
    callers (and operators) must be able to tell "healthy" from "blind"."""
    try:
        outputs = ctx.executor.output(state, cluster_key)
    except Exception:
        outputs = {}
    health = _node_health(ctx, state, outputs.get("cluster_id"),
                          outputs.get("ca_checksum", ""))
    if health is None:
        raise HealthLookupError(
            "Node health could not be determined (no reachable manager or "
            "driver view) — name the node to replace with --set "
            "hostname=<name> if you know which one is dead.")
    dead = sorted(h for h, st in health.items()
                  if not st.get("ready") and h in nodes)
    if not dead:
        raise NoUnhealthyNodesError(
            "No unhealthy nodes detected — name the node to replace with "
            "--set hostname=<name> if you want to repair one anyway.")
    if len(dead) == 1:
        return dead[0]
    if ctx.non_interactive:
        raise WorkflowError(
            f"Multiple unhealthy nodes: {dead}. Repair one at a time with "
            "--set hostname=<name>.")
    return ctx.resolver.prompter.select(
        "Unhealthy node to repair", [(h, h) for h in dead])


# --------------------------------------------------------------- slice repair

def repair_slice(ctx: WorkflowContext) -> str:
    return _counted_repair("slice", _repair_slice, ctx)


def repair_slice_auto(backend, executor, manager: str, cluster: str,
                      slice_id: str = "") -> str:
    """Programmatic ``repair slice`` for automation — the chaos harness's
    apply→preempt→repair→resume loop and (eventually) a reconcile
    operator. Same detect→cordon→replace→verify path as the CLI verb,
    driven through a silent auto-confirming context; raises the same
    typed errors (:class:`NoPreemptedSlicesError` when nothing is
    preempted)."""
    from ..config import Config, InputResolver

    # Hermetic config: no env, no ~/.triton-kubernetes-tpu.yaml fallback —
    # an operator's leftover `slice_id:` default must not steer an
    # automated repair onto the wrong pool.
    cfg = Config(env={}, use_default_file=False)
    cfg.set("cluster_manager", manager)
    cfg.set("cluster_name", cluster)
    if slice_id:
        cfg.set("slice_id", slice_id)
    ctx = WorkflowContext(backend=backend, executor=executor,
                          resolver=InputResolver(cfg, None, True))
    return repair_slice(ctx)


def _repair_slice(ctx: WorkflowContext) -> str:
    """Replace a preempted TPU slice pool and restore its ICI labels.

    Detect → cordon → replace → re-label → verify, all against the
    driver's persisted cloud state. The replacement re-applies the pool
    module's IDENTICAL config, so the new pool lands with the same slice
    id, topology, and per-host coordinate labels the scheduler was
    promised (modules/gcp_tpu.py re-derives them via
    topology/labels.host_labels_for_slice).
    """
    r = ctx.resolver
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    _, cluster_key = select_cluster(ctx, state)
    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    if not hasattr(ctx.executor, "cloud_view"):
        raise WorkflowError(
            "repair slice needs the in-process executor's cloud view "
            "(executor: terraform cannot introspect pool preemption).")

    nodes = state.nodes(cluster_key)  # pool name -> module key (gcp-tpu)
    _, cluster_name = parse_cluster_key(cluster_key)
    view = ctx.executor.cloud_view(state)
    # Filter on cluster AND pool: sibling clusters reuse default pool
    # names ("pool0"), and a preemption over there must never churn this
    # cluster's healthy pool.
    preempted = {
        sid: info for sid, info in view.preempted_slices().items()
        if info["cluster"] == cluster_name and info["pool"] in nodes
    }
    slice_id = _pick_preempted(ctx, preempted)
    if slice_id in preempted:
        pool_name = preempted[slice_id]["pool"]
    else:
        # Explicit --set slice_id override for a pool the state does not
        # record as preempted (operator knows better than the record).
        pool_name = next((p for p in nodes
                          if f"{cluster_name}-{p}" == slice_id), None)
        if pool_name is None:
            raise WorkflowError(
                f"Slice '{slice_id}' does not match any pool of cluster "
                f"'{cluster_name}'.")
    pool_key = nodes[pool_name]

    if not r.confirm("confirm",
                     f"Proceed? This will cordon and replace the preempted "
                     f"slice '{slice_id}' (pool '{pool_name}')"):
        return ""

    # Cordon the stale node objects before teardown: nothing new may
    # schedule onto a half-dead slice while it is being replaced.
    from ..executor.engine import load_executor_state, save_executor_state

    est = load_executor_state(state)
    from ..executor.cloudsim import CloudSimulator

    sim = CloudSimulator(est.cloud)
    sim.cordon_slice(slice_id)
    est.cloud = sim.to_dict()
    save_executor_state(state, est)

    # Replace: same module config, so the pool comes back with the same
    # accelerator, topology, and slice id (a repair is a replacement).
    pool_cfg = dict(state.get(f"module.{pool_key}"))
    ctx.executor.destroy(state, targets=[pool_key])
    state.delete(f"module.{pool_key}")
    ctx.backend.persist(state)
    state.set(f"module.{pool_key}", pool_cfg)
    ctx.executor.apply(state)
    ctx.backend.persist(state)

    # Verify the restored ICI coordinate labels — the whole point of the
    # slice-aware path. The pool module's outputs name the cluster/pool;
    # read the replacement's per-node labels back from the cloud state.
    spec = SliceSpec.from_accelerator(
        pool_cfg["tpu_accelerator"], pool_cfg.get("tpu_topology") or None)
    view2 = ctx.executor.cloud_view(state)
    gke = view2.get_resource("gke_cluster", cluster_name)
    pool = (gke or {}).get("node_pools", {}).get(pool_name, {})
    labels = [n.get("labels", {}) for n in pool.get("nodes", [])]
    problems = verify_slice_labels(labels, spec, slice_id)
    if problems:
        raise WorkflowError(
            "slice replacement came back with wrong ICI labels: "
            + "; ".join(problems))
    return pool_key


def _pick_preempted(ctx: WorkflowContext,
                    preempted: Dict[str, Dict]) -> str:
    """Auto-target the preempted slice (or honor ``--set slice_id=...``,
    which may name a pool the state does not record as preempted)."""
    if ctx.config.is_set("slice_id"):
        return str(ctx.config.get("slice_id"))
    if not preempted:
        raise NoPreemptedSlicesError(
            "No preempted TPU slices detected — name one with --set "
            "slice_id=<cluster>-<pool> if you want to replace it anyway.")
    if len(preempted) == 1:
        return next(iter(preempted))
    if ctx.non_interactive:
        raise WorkflowError(
            f"Multiple preempted slices: {sorted(preempted)}. Repair one "
            "at a time with --set slice_id=<id>.")
    return ctx.resolver.prompter.select(
        "Preempted slice to replace", [(s, s) for s in sorted(preempted)])
