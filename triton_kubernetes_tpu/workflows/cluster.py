"""``create cluster`` workflow (+ inline node batches).

Reference analog: create/cluster.go:45-301 — pick manager, pick provider,
provider fn adds ``module.cluster_*``, then per-node-type node blocks from the
silent-YAML ``nodes:`` list or an interactive add-node loop, confirm, apply,
persist. The reference's gabs re-parse workaround (cluster.go:150-154) is
unnecessary here — fresh children are immediately visible.
"""

from __future__ import annotations

from typing import List

from .common import WorkflowContext, WorkflowError, select_manager
from .manager import _validate_name
from .node import add_nodes_for_label
from .providers import CLUSTER_PROVIDERS, HOSTED_PROVIDERS, NODE_PROVIDERS


def new_cluster(ctx: WorkflowContext) -> str:
    r = ctx.resolver
    manager = select_manager(
        ctx, "No cluster managers, please create a cluster manager "
             "before creating a kubernetes cluster.")
    state = ctx.backend.state(manager)

    provider = r.choose("cluster_cloud_provider", "Cloud Provider",
                        [(p, p) for p in sorted(CLUSTER_PROVIDERS)])
    name = r.value("name", "Cluster Name", validate=_validate_name)
    cluster_key = CLUSTER_PROVIDERS[provider](ctx, state, name)

    hostnames: List[str] = []
    if provider not in HOSTED_PROVIDERS:
        hostnames = _gather_nodes(ctx, state, provider, cluster_key)

    if not r.confirm("confirm", f"Proceed? This will create cluster '{name}'"):
        return ""

    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    ctx.executor.apply(state)
    ctx.backend.persist(state)
    if hostnames:
        print(f"{len(hostnames)} nodes added: {', '.join(hostnames)}")
    return cluster_key


def _gather_nodes(ctx: WorkflowContext, state, provider: str,
                  cluster_key: str) -> List[str]:
    """Silent mode: one batch per ``nodes:`` entry (create/cluster.go:169-229).
    Interactive: add-node loop until declined (cluster.go:231-292)."""
    r = ctx.resolver
    node_fn = NODE_PROVIDERS.get(provider)
    if node_fn is None:
        return []
    created: List[str] = []

    nodes_spec = ctx.config.get("nodes")
    if isinstance(nodes_spec, list):
        for block in nodes_spec:
            if not isinstance(block, dict):
                raise WorkflowError(f"invalid nodes entry: {block!r}")
            # Scope each block's keys as overrides for the node fn
            # (viper.Set per-node-var analog, cluster.go:174-229).
            created.extend(add_nodes_for_label(ctx, state, provider,
                                               cluster_key, overrides=block))
        return created

    if ctx.non_interactive:
        return created
    while r.prompter.confirm("Add a node to this cluster?"):
        created.extend(add_nodes_for_label(ctx, state, provider, cluster_key))
    return created
