"""``restore backup`` workflow.

No reference analog — the reference CLI creates backups but never restores
them (SURVEY.md §5: "restore is not implemented in the CLI — backup create
only"). Flow mirrors the other cluster-scoped verbs: pick manager, pick
cluster, require an existing backup, confirm, replay.
"""

from __future__ import annotations

from .common import WorkflowContext, WorkflowError, select_cluster, select_manager


def restore_backup(ctx: WorkflowContext) -> str:
    manager = select_manager(
        ctx, "No cluster managers, please create a cluster manager "
             "before restoring a backup.")
    state = ctx.backend.state(manager)
    cluster_name, cluster_key = select_cluster(ctx, state)

    backup_key = state.backup(cluster_key)
    if backup_key is None:
        raise WorkflowError(f"Cluster '{cluster_name}' has no backup.")

    if not ctx.resolver.confirm(
            "confirm", f"Proceed? This will restore '{cluster_name}' "
                       "from its backup"):
        return ""

    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    return ctx.executor.restore(state, backup_key)
