"""``create node`` workflow (scale-out path).

Reference analog: create/node.go:43-195 — pick manager, pick cluster,
dispatch by the provider parsed from the cluster key, node-count semantics
(workers free-form >=1, etcd/control 1/3/5/7), hostname-prefix collision-free
numbering, confirm, apply, persist. For ``gcp-tpu`` clusters a "node" is a
TPU slice node pool — count/labels don't apply; pool name and accelerator do.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

from ..state import StateDocument, parse_cluster_key
from .common import WorkflowContext, WorkflowError, select_cluster, select_manager
from .providers import NODE_PROVIDERS
from .providers.base import (
    HOST_LABEL_CHOICES,
    new_hostnames,
    node_count_for_label,
)


@contextlib.contextmanager
def _scoped_overrides(ctx: WorkflowContext, overrides: Optional[Dict]):
    """Temporarily layer a nodes:-block's keys over the config
    (viper.Set per-node-var analog, create/cluster.go:174-229)."""
    if not overrides:
        yield
        return
    for k, v in overrides.items():
        ctx.config.set(k, v)
    try:
        yield
    finally:
        for k in overrides:
            ctx.config.unset(k)


def add_nodes_for_label(ctx: WorkflowContext, state: StateDocument,
                        provider: str, cluster_key: str,
                        overrides: Optional[Dict] = None) -> List[str]:
    """Create one batch of same-role nodes (one ``nodes:`` block)."""
    r = ctx.resolver
    node_fn = NODE_PROVIDERS[provider]
    with _scoped_overrides(ctx, overrides):
        if provider == "gcp-tpu":
            pool_name = r.value("hostname", "TPU Pool Name", default="pool0")
            node_fn(ctx, state, cluster_key, str(pool_name), "worker")
            return [str(pool_name)]
        host_label = r.choose("rancher_host_label", "Host Role",
                              [(l, l) for l in HOST_LABEL_CHOICES],
                              default="worker")
        count = node_count_for_label(ctx, host_label)
        prefix = r.value("hostname", "Hostname prefix")
        hostnames = new_hostnames(state, cluster_key, str(prefix), count)
        for hostname in hostnames:
            node_fn(ctx, state, cluster_key, hostname, host_label)
        return hostnames


def new_node(ctx: WorkflowContext) -> List[str]:
    r = ctx.resolver
    manager = select_manager(
        ctx, "No cluster managers, please create a cluster manager "
             "before creating a kubernetes node.")
    state = ctx.backend.state(manager)
    _, cluster_key = select_cluster(ctx, state)
    provider, _ = parse_cluster_key(cluster_key)
    if provider not in NODE_PROVIDERS:
        raise WorkflowError(
            f"Could not determine cloud provider for cluster '{cluster_key}'")

    hostnames = add_nodes_for_label(ctx, state, provider, cluster_key)

    if not r.confirm("confirm",
                     f"Proceed? This will add {len(hostnames)} node(s)"):
        return []

    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    ctx.executor.apply(state)
    ctx.backend.persist(state)
    return hostnames
