"""AWS provider workflows (create/manager_aws.go:24-515,
create/cluster_aws.go:24-364, create/node_aws.go:24-364 analogs)."""

from __future__ import annotations

from ...state import StateDocument
from ..common import WorkflowContext
from .base import base_cluster_config, base_manager_config, base_node_config

REGIONS = ["us-east-1", "us-east-2", "us-west-1", "us-west-2",
           "eu-west-1", "eu-central-1", "ap-southeast-1", "ap-northeast-1"]
INSTANCE_TYPES = ["t2.medium", "t2.large", "m5.large", "m5.xlarge", "c5.xlarge"]


def _creds(ctx: WorkflowContext) -> dict:
    r = ctx.resolver
    return {
        "aws_access_key": r.value("aws_access_key", "AWS Access Key"),
        "aws_secret_key": r.value("aws_secret_key", "AWS Secret Key"),
        "aws_region": r.choose("aws_region", "AWS Region",
                               [(x, x) for x in REGIONS], default=REGIONS[0]),
    }


def manager_config(ctx: WorkflowContext, state: StateDocument, name: str) -> None:
    r = ctx.resolver
    cfg = base_manager_config(ctx, "aws-manager", name)
    cfg.update(_creds(ctx))
    cfg["aws_vpc_cidr"] = r.value("aws_vpc_cidr", "AWS VPC CIDR",
                                  default="10.0.0.0/16")
    cfg["aws_subnet_cidr"] = r.value("aws_subnet_cidr", "AWS Subnet CIDR",
                                     default="10.0.2.0/24")
    cfg["aws_instance_type"] = r.choose(
        "aws_instance_type", "AWS Instance Type",
        [(t, t) for t in INSTANCE_TYPES], default=INSTANCE_TYPES[0])
    cfg["aws_public_key_path"] = r.value(
        "aws_public_key_path", "AWS Public Key Path", default="~/.ssh/id_rsa.pub")
    cfg["aws_key_name"] = r.value("aws_key_name", "AWS Key Name", default="")
    state.set_manager(cfg)


def cluster_config(ctx: WorkflowContext, state: StateDocument, name: str) -> str:
    r = ctx.resolver
    cfg = base_cluster_config(ctx, "aws-k8s", name)
    cfg.update(_creds(ctx))
    cfg["aws_vpc_cidr"] = r.value("aws_vpc_cidr", "AWS VPC CIDR",
                                  default="10.0.0.0/16")
    cfg["aws_subnet_cidr"] = r.value("aws_subnet_cidr", "AWS Subnet CIDR",
                                     default="10.0.2.0/24")
    cfg["aws_public_key_path"] = r.value(
        "aws_public_key_path", "AWS Public Key Path", default="~/.ssh/id_rsa.pub")
    cfg["aws_key_name"] = r.value("aws_key_name", "AWS Key Name", default="")
    return state.add_cluster("aws", name, cfg)


def node_config(ctx: WorkflowContext, state: StateDocument, cluster_key: str,
                hostname: str, host_label: str) -> str:
    r = ctx.resolver
    cfg = base_node_config(ctx, "aws-k8s-host", cluster_key, hostname, host_label)
    cfg.update(_creds(ctx))
    cfg["aws_ami_id"] = r.value("aws_ami_id", "AWS AMI ID", default="ami-ubuntu-lts")
    cfg["aws_instance_type"] = r.choose(
        "aws_instance_type", "AWS Instance Type",
        [(t, t) for t in INSTANCE_TYPES], default=INSTANCE_TYPES[0])
    # Wire the cluster's network envelope + keypair via interpolation.
    cfg["aws_subnet_id"] = f"${{module.{cluster_key}.aws_subnet_id}}"
    cfg["aws_security_group_id"] = f"${{module.{cluster_key}.aws_security_group_id}}"
    cfg["aws_key_name"] = f"${{module.{cluster_key}.aws_key_name}}"
    # Optional EBS volume (aws-rancher-k8s-host/main.tf:47-62 analog).
    device = r.value("ebs_volume_device_name", "EBS Volume Device Name", default="")
    if device:
        cfg["ebs_volume_device_name"] = device
        cfg["ebs_volume_mount_path"] = r.value(
            "ebs_volume_mount_path", "EBS Volume Mount Path", default="/mnt/data")
        cfg["ebs_volume_type"] = r.choose(
            "ebs_volume_type", "EBS Volume Type",
            [("standard", "standard"), ("gp2", "gp2"), ("io1", "io1")],
            default="standard")
        cfg["ebs_volume_size"] = int(r.value("ebs_volume_size", "EBS Volume Size (GB)",
                                             default=100))
    return state.add_node(cluster_key, hostname, cfg)
