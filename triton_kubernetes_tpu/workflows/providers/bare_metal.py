"""Bare-metal provider workflows (create/manager_bare_metal.go:15-150,
create/cluster_bare_metal.go:9-36, create/node_bare_metal.go:18-198 analogs).

The node flow supports the reference's multi-host form: a ``hosts:`` list
creates one module per host in a single pass.
"""

from __future__ import annotations

from ...state import StateDocument
from ..common import WorkflowContext
from .base import base_cluster_config, base_manager_config, base_node_config


def _ssh(ctx: WorkflowContext) -> dict:
    r = ctx.resolver
    return {
        "ssh_user": r.value("ssh_user", "SSH User", default="root"),
        "key_path": r.value("key_path", "SSH Key Path", default="~/.ssh/id_rsa"),
        "bastion_host": r.value("bastion_host", "Bastion Host", default=""),
    }


def manager_config(ctx: WorkflowContext, state: StateDocument, name: str) -> None:
    r = ctx.resolver
    cfg = base_manager_config(ctx, "bare-metal-manager", name)
    cfg["host"] = r.value("host", "Host (IP or DNS name)")
    cfg.update(_ssh(ctx))
    state.set_manager(cfg)


def cluster_config(ctx: WorkflowContext, state: StateDocument, name: str) -> str:
    return state.add_cluster("bare-metal", name,
                             base_cluster_config(ctx, "bare-metal-k8s", name))


def node_config(ctx: WorkflowContext, state: StateDocument, cluster_key: str,
                hostname: str, host_label: str) -> str:
    r = ctx.resolver
    cfg = base_node_config(ctx, "bare-metal-k8s-host", cluster_key,
                           hostname, host_label)
    # In silent mode a hosts: list maps hostnames to addresses; otherwise the
    # host address is prompted per node.
    hosts = ctx.config.get("hosts")
    if isinstance(hosts, list) and hosts:
        # Positional: Nth created hostname takes the Nth host entry.
        idx = len(state.nodes(cluster_key))
        entry = hosts[min(idx, len(hosts) - 1)]
        cfg["host"] = entry.get("host") if isinstance(entry, dict) else entry
    else:
        cfg["host"] = r.value("host", f"Host address for {hostname}")
    cfg.update(_ssh(ctx))
    return state.add_node(cluster_key, hostname, cfg)
