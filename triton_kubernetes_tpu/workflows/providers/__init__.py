"""Per-provider workflow config builders.

Reference analog: create/manager_*.go, create/cluster_*.go, create/node_*.go —
the functions that collect provider-specific inputs (tri-modal) and write the
module config into the state doc. Provider names match the reference's
choices plus the TPU fork (``gcp-tpu``).
"""

from . import aws, azure, bare_metal, gcp, gcp_tpu, triton, vsphere

MANAGER_PROVIDERS = {
    "triton": triton.manager_config,
    "aws": aws.manager_config,
    "gcp": gcp.manager_config,
    "azure": azure.manager_config,
    "bare-metal": bare_metal.manager_config,
}

CLUSTER_PROVIDERS = {
    "triton": triton.cluster_config,
    "aws": aws.cluster_config,
    "gcp": gcp.cluster_config,
    "gke": gcp.gke_cluster_config,
    "gcp-tpu": gcp_tpu.cluster_config,
    "azure": azure.cluster_config,
    "aks": azure.aks_cluster_config,
    "vsphere": vsphere.cluster_config,
    "bare-metal": bare_metal.cluster_config,
}

NODE_PROVIDERS = {
    "triton": triton.node_config,
    "aws": aws.node_config,
    "gcp": gcp.node_config,
    "gcp-tpu": gcp_tpu.node_config,
    "azure": azure.node_config,
    "vsphere": vsphere.node_config,
    "bare-metal": bare_metal.node_config,
}

# Hosted-control-plane providers have no agent nodes to add
# (gke-rancher-k8s analog: nodes come from provider node pools). gcp-tpu is
# hosted too, but its "nodes" are TPU slice pools, handled by gcp_tpu.node_config.
HOSTED_PROVIDERS = {"gke", "aks"}

__all__ = [
    "CLUSTER_PROVIDERS",
    "HOSTED_PROVIDERS",
    "MANAGER_PROVIDERS",
    "NODE_PROVIDERS",
]
