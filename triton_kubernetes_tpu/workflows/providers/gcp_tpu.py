"""THE TPU FORK's workflow layer: ``create cluster --provider gcp-tpu`` and
TPU slice "nodes".

No reference analog (BASELINE.json north star: "add create/cluster_tpu.go,
create/node_tpu.go"). The node flow is re-imagined for TPUs: where VM
providers ask host-label + count, the TPU path asks **accelerator**
(``v5e-8``, ``v5p-64``...) and optional topology, and one "node" module is a
whole slice node pool (nodes-per-slice is derived, never asked).
"""

from __future__ import annotations

from ...state import StateDocument, parse_cluster_key
from ...topology import TPU_GENERATIONS, SliceSpec, default_topology, parse_accelerator
from ..common import WorkflowContext, module_source
from .gcp import REGIONS, _creds

TPU_REGIONS = ["us-east5", "us-central2", "us-south1", "europe-west4",
               "asia-northeast1"]
COMMON_ACCELERATORS = [
    "v5e-1", "v5e-4", "v5e-8", "v5e-16", "v5e-64", "v5e-256",
    "v5p-8", "v5p-64", "v5p-128", "v5p-256",
    "v6e-8", "v6e-64", "v6e-256",
    "v4-8", "v4-64",
]


def cluster_config(ctx: WorkflowContext, state: StateDocument, name: str) -> str:
    """GKE control plane destined for TPU node pools."""
    r = ctx.resolver
    creds = _creds(ctx)
    # Default must come from the same list as the options: a catalog that
    # narrows the region set would otherwise default outside it.
    regions = ctx.choices("gcp-tpu", "regions", TPU_REGIONS)
    cfg = {
        "source": module_source(ctx, "gcp-tpu-k8s"),
        "name": name,
        "manager_url": "${module.cluster-manager.manager_url}",
        "manager_access_key": "${module.cluster-manager.manager_access_key}",
        "manager_secret_key": "${module.cluster-manager.manager_secret_key}",
        **creds,
        "gcp_region": r.choose("gcp_region", "GCP Region (TPU-capable)",
                               [(x, x) for x in regions],
                               default=regions[0]),
        "k8s_version": r.value("k8s_version", "Kubernetes Version", default="1.31"),
        "system_node_count": int(r.value("system_node_count",
                                         "System Pool Node Count", default=1)),
    }
    return state.add_cluster("gcp-tpu", name, cfg)


def _validate_accelerator(v) -> str | None:
    try:
        parse_accelerator(str(v))
        return None
    except ValueError as e:
        return str(e)


def node_config(ctx: WorkflowContext, state: StateDocument, cluster_key: str,
                pool_name: str, host_label: str = "worker") -> str:
    """One TPU slice as a node pool. ``pool_name`` takes the hostname slot in
    the module key scheme (node_gcp-tpu_<cluster>_<pool>)."""
    r = ctx.resolver
    creds = _creds(ctx)
    accelerator = r.choose(
        "tpu_accelerator", "TPU Accelerator (<generation>-<chips>)",
        [(a, a) for a in COMMON_ACCELERATORS], default="v5e-8") \
        if not ctx.config.is_set("tpu_accelerator") else \
        r.value("tpu_accelerator", validate=_validate_accelerator)
    gen, chips = parse_accelerator(str(accelerator))
    topology = r.value("tpu_topology", "TPU Topology (e.g. 4x4x4)",
                       default=default_topology(gen, chips))
    # Validate the pair early — fail at prompt time, not apply time.
    SliceSpec.from_accelerator(str(accelerator), str(topology) or None)
    _, cluster_name = parse_cluster_key(cluster_key)
    cfg = {
        "source": module_source(ctx, "gcp-tpu-nodepool"),
        "pool_name": pool_name,
        "gke_cluster_name": cluster_name,
        "cluster_id": f"${{module.{cluster_key}.cluster_id}}",
        **creds,
        "tpu_accelerator": str(accelerator),
        "tpu_topology": str(topology),
        "reserved": r.flag("tpu_reserved", default=False),
        "spot": r.flag("tpu_spot", default=False),
    }
    return state.add_node(cluster_key, pool_name, cfg)


def jobset_config(ctx: WorkflowContext, state: StateDocument, cluster_key: str,
                  pool_key: str, job_name: str) -> str:
    """Attach a multi-host JAX workload to a provisioned slice."""
    r = ctx.resolver
    pool_cfg = state.get(f"module.{pool_key}") or {}
    cfg = {
        "source": module_source(ctx, "tpu-jobset"),
        "job_name": job_name,
        "cluster_id": f"${{module.{cluster_key}.cluster_id}}",
        "tpu_accelerator": pool_cfg.get("tpu_accelerator", "v5e-8"),
        "tpu_topology": pool_cfg.get("tpu_topology", ""),
        "slice_id": f"${{module.{pool_key}.slice_id}}",
        "image": r.value("job_image", "Workload Image",
                         default="tk8s/jax-tpu-runtime:0.1.0"),
        "command": r.value("job_command", "Workload Command",
                           default=["python", "-m", "triton_kubernetes_tpu.train"]),
        "env": r.value("job_env", "Workload Env", default={}),
    }
    key = f"job_{job_name}"
    state.set(f"module.{key}", cfg)
    return key
