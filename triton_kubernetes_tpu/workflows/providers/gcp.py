"""GCP provider workflows — VM path and hosted-GKE path.

Reference analogs: create/manager_gcp.go:22-422 (service-account JSON ->
project id), create/cluster_gcp.go:23-168, create/node_gcp.go:21-387,
create/cluster_gke.go:26-519 (hosted path with master password >=16 chars).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ...state import StateDocument
from ..common import WorkflowContext
from .base import base_cluster_config, base_manager_config, base_node_config
from ..common import module_source

REGIONS = ["us-central1", "us-east1", "us-east5", "us-west1",
           "europe-west1", "europe-west4", "asia-northeast1"]
MACHINE_TYPES = ["n1-standard-1", "n1-standard-2", "n1-standard-4",
                 "n2-standard-4", "n2-standard-8"]
IMAGES = ["ubuntu-os-cloud/ubuntu-2204-lts", "ubuntu-os-cloud/ubuntu-2404-lts"]


def project_id_from_credentials(path: str) -> Optional[str]:
    """Extract ``project_id`` from a service-account JSON file
    (create/manager_gcp.go's re-unmarshal trick)."""
    try:
        with open(os.path.expanduser(path)) as f:
            return json.load(f).get("project_id")
    except (OSError, ValueError):
        return None


def _creds(ctx: WorkflowContext) -> dict:
    r = ctx.resolver
    path = r.value("gcp_path_to_credentials", "Path to GCP credentials file")
    project = ctx.config.get("gcp_project_id") or project_id_from_credentials(path)
    if not project:
        project = r.value("gcp_project_id", "GCP Project ID")
    return {"gcp_path_to_credentials": path, "gcp_project_id": project}


def manager_config(ctx: WorkflowContext, state: StateDocument, name: str) -> None:
    r = ctx.resolver
    cfg = base_manager_config(ctx, "gcp-manager", name)
    cfg.update(_creds(ctx))
    # Prompt-supplied credentials reach the live catalog through context —
    # interactive sessions only have them now.
    cat_ctx = {"credentials_path": cfg["gcp_path_to_credentials"],
               "project": cfg["gcp_project_id"]}
    regions = ctx.choices("gcp", "regions", REGIONS, cat_ctx)
    cfg["gcp_compute_region"] = r.choose(
        "gcp_compute_region", "GCP Region", [(x, x) for x in regions],
        default=regions[0])
    cfg["gcp_zone"] = r.value("gcp_zone", "GCP Zone",
                              default=f"{cfg['gcp_compute_region']}-a")
    machine_types = ctx.choices("gcp", "machine_types", MACHINE_TYPES,
                                {"zone": cfg["gcp_zone"], **cat_ctx})
    cfg["gcp_machine_type"] = r.choose(
        "gcp_machine_type", "GCP Machine Type",
        [(t, t) for t in machine_types],
        default=machine_types[min(1, len(machine_types) - 1)])
    images = ctx.choices("gcp", "images", IMAGES, cat_ctx)
    cfg["gcp_image"] = r.choose("gcp_image", "GCP Image",
                                [(i, i) for i in images], default=images[0])
    state.set_manager(cfg)


def cluster_config(ctx: WorkflowContext, state: StateDocument, name: str) -> str:
    r = ctx.resolver
    cfg = base_cluster_config(ctx, "gcp-k8s", name)
    cfg.update(_creds(ctx))
    regions = ctx.choices(
        "gcp", "regions", REGIONS,
        {"credentials_path": cfg["gcp_path_to_credentials"],
         "project": cfg["gcp_project_id"]})
    cfg["gcp_compute_region"] = r.choose(
        "gcp_compute_region", "GCP Region", [(x, x) for x in regions],
        default=regions[0])
    return state.add_cluster("gcp", name, cfg)


def node_config(ctx: WorkflowContext, state: StateDocument, cluster_key: str,
                hostname: str, host_label: str) -> str:
    r = ctx.resolver
    cfg = base_node_config(ctx, "gcp-k8s-host", cluster_key, hostname, host_label)
    cfg.update(_creds(ctx))
    cfg["gcp_zone"] = r.value("gcp_instance_zone", "GCP Zone", default="us-central1-a")
    cfg["gcp_machine_type"] = r.choose(
        "gcp_machine_type", "GCP Machine Type",
        [(t, t) for t in MACHINE_TYPES], default=MACHINE_TYPES[0])
    cfg["gcp_image"] = r.value("gcp_image", "GCP Image", default=IMAGES[0])
    # Network envelope from the cluster module (create/node_gcp.go contract).
    cfg["gcp_compute_network_name"] = \
        f"${{module.{cluster_key}.gcp_compute_network_name}}"
    cfg["gcp_firewall_tag"] = f"${{module.{cluster_key}.gcp_firewall_tag}}"
    disk_type = r.value("gcp_disk_type", "GCP Disk Type", default="")
    if disk_type:
        cfg["gcp_disk_type"] = disk_type
        cfg["gcp_disk_size"] = int(r.value("gcp_disk_size", "GCP Disk Size (GB)",
                                           default=100))
        cfg["gcp_disk_mount_path"] = r.value(
            "gcp_disk_mount_path", "GCP Disk Mount Path", default="/mnt/data")
    return state.add_node(cluster_key, hostname, cfg)


def gke_cluster_config(ctx: WorkflowContext, state: StateDocument, name: str) -> str:
    """Hosted GKE path — no base cluster config (no k8s_network_provider or
    registries; create/cluster_gke.go deliberately skips them)."""
    r = ctx.resolver
    creds = _creds(ctx)

    def _pw(v) -> str | None:
        return None if len(str(v)) >= 16 else \
            "master_password must be at least 16 characters"

    cfg = {
        "source": module_source(ctx, "gke-k8s"),
        "name": name,
        "manager_url": "${module.cluster-manager.manager_url}",
        "manager_access_key": "${module.cluster-manager.manager_access_key}",
        "manager_secret_key": "${module.cluster-manager.manager_secret_key}",
        **creds,
        "gcp_zone": r.value("gcp_zone", "GCP Zone", default="us-central1-a"),
        "gcp_additional_zones": r.value("gcp_additional_zones",
                                        "GCP Additional Zones", default=[]),
    }
    cat_ctx = {"zone": cfg["gcp_zone"],
               "credentials_path": creds["gcp_path_to_credentials"],
               "project": creds["gcp_project_id"]}
    machine_types = ctx.choices("gke", "machine_types", MACHINE_TYPES,
                                cat_ctx)
    # Valid master versions from the live serverConfig when the catalog has
    # them (create/cluster_gke.go's GetServerconfig prompt); free-form with
    # a default otherwise.
    versions = ctx.choices("gke", "k8s_versions", [], cat_ctx)
    cfg.update({
        "gcp_machine_type": r.choose(
            "gcp_machine_type", "GCP Machine Type",
            [(t, t) for t in machine_types],
            default=machine_types[min(1, len(machine_types) - 1)]),
        "k8s_version": (
            r.choose("k8s_version", "Kubernetes Master Version",
                     [(v, v) for v in versions], default=versions[0])
            if versions else
            r.value("k8s_version", "Kubernetes Master Version",
                    default="1.31")),
        "node_count": int(r.value("node_count", "Node Count", default=3)),
        "master_password": r.value("master_password", "GKE Master Password",
                                   default="change-me-please-16", validate=_pw),
    })
    return state.add_cluster("gke", name, cfg)
