"""vSphere provider workflows (create/cluster_vsphere.go:13-210,
create/node_vsphere.go:17-180 analogs; no vSphere manager in the reference)."""

from __future__ import annotations

from ...state import StateDocument
from ..common import WorkflowContext
from .base import base_cluster_config, base_node_config


def _creds(ctx: WorkflowContext) -> dict:
    r = ctx.resolver
    return {
        "vsphere_user": r.value("vsphere_user", "vSphere User"),
        "vsphere_password": r.value("vsphere_password", "vSphere Password"),
        "vsphere_server": r.value("vsphere_server", "vSphere Server"),
        "vsphere_datacenter_name": r.value("vsphere_datacenter_name",
                                           "vSphere Datacenter"),
        "vsphere_datastore_name": r.value("vsphere_datastore_name",
                                          "vSphere Datastore"),
        "vsphere_resource_pool_name": r.value("vsphere_resource_pool_name",
                                              "vSphere Resource Pool"),
        "vsphere_network_name": r.value("vsphere_network_name",
                                        "vSphere Network"),
    }


def cluster_config(ctx: WorkflowContext, state: StateDocument, name: str) -> str:
    cfg = base_cluster_config(ctx, "vsphere-k8s", name)
    cfg.update(_creds(ctx))
    return state.add_cluster("vsphere", name, cfg)


def node_config(ctx: WorkflowContext, state: StateDocument, cluster_key: str,
                hostname: str, host_label: str) -> str:
    r = ctx.resolver
    cfg = base_node_config(ctx, "vsphere-k8s-host", cluster_key,
                           hostname, host_label)
    cfg.update(_creds(ctx))
    cfg["vsphere_template_name"] = r.value("vsphere_template_name",
                                           "vSphere Template VM")
    cfg["ssh_user"] = r.value("ssh_user", "SSH User", default="root")
    cfg["key_path"] = r.value("key_path", "SSH Key Path", default="~/.ssh/id_rsa")
    return state.add_node(cluster_key, hostname, cfg)
