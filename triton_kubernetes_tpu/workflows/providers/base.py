"""Shared base-config builders for the three module families.

Reference analogs: getBaseManagerTerraformConfig (create/manager.go:156-300),
getBaseClusterTerraformConfig (create/cluster.go:296-532),
getBaseNodeTerraformConfig (create/node.go:197-387). Silent-YAML key names
match the reference's schema (docs/guide/silent-install-yaml.md) exactly —
``rancher_server_image``, ``k8s_network_provider``, ``rancher_host_label``...
"""

from __future__ import annotations

from typing import Any, Dict

from ...state import StateDocument
from ..common import WorkflowContext, module_source

K8S_VERSIONS = [
    "v1.27.16", "v1.28.15", "v1.29.10", "v1.30.6", "v1.31.2", "v1.32.0",
]
NETWORK_PROVIDERS = ["calico", "flannel"]


def base_manager_config(ctx: WorkflowContext, module_name: str,
                        name: str) -> Dict[str, Any]:
    r = ctx.resolver
    cfg: Dict[str, Any] = {
        "source": module_source(ctx, module_name),
        "name": name,
    }
    registry = r.value("private_registry", "Private Registry", default="")
    if registry:
        cfg["private_registry"] = registry
        cfg["private_registry_username"] = r.value(
            "private_registry_username", "Private Registry Username")
        cfg["private_registry_password"] = r.value(
            "private_registry_password", "Private Registry Password")
    server_image = r.value("rancher_server_image", "Manager Server Image", default="")
    if server_image:
        cfg["manager_image"] = server_image
    agent_image = r.value("rancher_agent_image", "Manager Agent Image", default="")
    if agent_image:
        cfg["agent_image"] = agent_image
    cfg["admin_password"] = r.value(
        "rancher_admin_password", "Admin Password (UI)", default="")
    return cfg


def base_cluster_config(ctx: WorkflowContext, module_name: str,
                        name: str) -> Dict[str, Any]:
    """Manager credentials are *interpolations* resolved at apply time by the
    executor — never literal values (create/cluster.go:297-300 contract)."""
    r = ctx.resolver
    cfg: Dict[str, Any] = {
        "source": module_source(ctx, module_name),
        "name": name,
        "manager_url": "${module.cluster-manager.manager_url}",
        "manager_access_key": "${module.cluster-manager.manager_access_key}",
        "manager_secret_key": "${module.cluster-manager.manager_secret_key}",
        "k8s_version": r.choose(
            "k8s_version", "Kubernetes Version",
            [(v, v) for v in K8S_VERSIONS], default=K8S_VERSIONS[-1]),
        "k8s_network_provider": r.choose(
            "k8s_network_provider", "Kubernetes Network Provider",
            [(n, n) for n in NETWORK_PROVIDERS], default="calico"),
    }
    registry = r.value("private_registry", "Private Registry", default="")
    if registry:
        cfg["private_registry"] = registry
        cfg["private_registry_username"] = r.value(
            "private_registry_username", "Private Registry Username")
        cfg["private_registry_password"] = r.value(
            "private_registry_password", "Private Registry Password")
    k8s_registry = r.value("k8s_registry", "Kubernetes Registry", default="")
    if k8s_registry:
        cfg["k8s_registry"] = k8s_registry
        cfg["k8s_registry_username"] = r.value(
            "k8s_registry_username", "Kubernetes Registry Username")
        cfg["k8s_registry_password"] = r.value(
            "k8s_registry_password", "Kubernetes Registry Password")
    return cfg


HOST_LABEL_CHOICES = ["worker", "etcd", "control"]


def base_node_config(ctx: WorkflowContext, module_name: str,
                     cluster_key: str, hostname: str,
                     host_label: str) -> Dict[str, Any]:
    """Registration token + CA checksum wired as interpolations from the
    cluster module (create/node.go getBaseNodeTerraformConfig contract), plus
    the worker/etcd/control host label (rancherHostLabelsConfig)."""
    return {
        "source": module_source(ctx, module_name),
        "hostname": hostname,
        "manager_url": "${module.cluster-manager.manager_url}",
        "rancher_cluster_registration_token":
            f"${{module.{cluster_key}.registration_token}}",
        "rancher_cluster_ca_checksum":
            f"${{module.{cluster_key}.ca_checksum}}",
        "rancher_host_labels": {host_label: True},
    }


def node_count_for_label(ctx: WorkflowContext, host_label: str) -> int:
    """Workers: free-form >=1. etcd/control: 1/3/5/7 (quorum-shaped), matching
    create/node.go getNodeCount."""
    r = ctx.resolver
    if host_label == "worker":
        def _validate(v: Any) -> str | None:
            try:
                return None if int(v) >= 1 else "node_count must be >= 1"
            except (TypeError, ValueError):
                return "node_count must be an integer"
        return int(r.value("node_count", "Number of nodes", default=1,
                           validate=_validate))
    return int(r.choose("node_count", "Number of nodes",
                        [("1", 1), ("3", 3), ("5", 5), ("7", 7)], default=1))


def new_hostnames(state: StateDocument, cluster_key: str,
                  prefix: str, count: int) -> list[str]:
    """Collision-free ``prefix-N`` numbering continuing past existing nodes
    (create/node.go getNewHostnames, pinned by create/node_test.go)."""
    existing = set(state.nodes(cluster_key))
    out: list[str] = []
    n = 1
    while len(out) < count:
        candidate = f"{prefix}-{n}"
        if candidate not in existing:
            out.append(candidate)
        n += 1
    return out
