"""Azure provider workflows, including the HA branch and hosted AKS.

Reference analogs: create/manager_azure.go:23-578 (``ha: true`` switches to
the azure-rke module and demands fqdn + TLS cert/key paths — note its
cert-path-into-key-path bug at :155 is fixed here), create/cluster_azure.go,
create/cluster_aks.go:27-522, create/node_azure.go:25-325.
"""

from __future__ import annotations

from ...state import StateDocument
from ..common import WorkflowContext, module_source, preferred_default
from .base import base_cluster_config, base_manager_config, base_node_config

LOCATIONS = ["West US 2", "East US", "West Europe", "Southeast Asia"]
VM_SIZES = ["Standard_D2s_v3", "Standard_D4s_v3", "Standard_D8s_v3"]


def _creds(ctx: WorkflowContext, with_location: bool = True) -> dict:
    r = ctx.resolver
    cfg = {
        "azure_subscription_id": r.value("azure_subscription_id",
                                         "Azure Subscription ID"),
        "azure_client_id": r.value("azure_client_id", "Azure Client ID"),
        "azure_client_secret": r.value("azure_client_secret", "Azure Client Secret"),
        "azure_tenant_id": r.value("azure_tenant_id", "Azure Tenant ID"),
    }
    if with_location:
        locations = ctx.choices("azure", "locations", LOCATIONS, cfg)
        cfg["azure_location"] = r.choose(
            "azure_location", "Azure Location",
            [(x, x) for x in locations],
            default=preferred_default(locations, LOCATIONS))
    return cfg


def _vm_sizes(ctx: WorkflowContext, creds: dict) -> list:
    """Live VM sizes when `catalog: live` (create/manager_azure.go's
    validated size prompt), static fallback otherwise."""
    context = dict(creds)
    if creds.get("azure_location"):
        context["location"] = creds["azure_location"]
    return ctx.choices("azure", "vm_sizes", VM_SIZES, context)


def manager_config(ctx: WorkflowContext, state: StateDocument, name: str) -> None:
    r = ctx.resolver
    ha = r.flag("ha", default=False)
    if ha:
        cfg = base_manager_config(ctx, "azure-rke-manager", name)
        cfg.update(_creds(ctx))
        cfg["node_count"] = int(r.value("node_count", "Manager Node Count",
                                        default=3))
        cfg["fqdn"] = r.value("fqdn", "Manager FQDN")
        cfg["tls_cert_path"] = r.value("tls_cert_path", "TLS Certificate Path")
        cfg["tls_private_key_path"] = r.value("tls_private_key_path",
                                              "TLS Private Key Path")
    else:
        cfg = base_manager_config(ctx, "azure-manager", name)
        cfg.update(_creds(ctx))
    sizes = _vm_sizes(ctx, cfg)
    cfg["azure_size"] = r.choose("azure_size", "Azure VM Size",
                                 [(s, s) for s in sizes],
                                 default=preferred_default(sizes, VM_SIZES))
    cfg["azure_public_key_path"] = r.value(
        "azure_public_key_path", "Azure Public Key Path",
        default="~/.ssh/id_rsa.pub")
    state.set_manager(cfg)


def cluster_config(ctx: WorkflowContext, state: StateDocument, name: str) -> str:
    cfg = base_cluster_config(ctx, "azure-k8s", name)
    cfg.update(_creds(ctx))
    return state.add_cluster("azure", name, cfg)


def node_config(ctx: WorkflowContext, state: StateDocument, cluster_key: str,
                hostname: str, host_label: str) -> str:
    r = ctx.resolver
    cfg = base_node_config(ctx, "azure-k8s-host", cluster_key, hostname, host_label)
    # No location prompt for nodes: placement comes from the cluster module
    # (azure_location interpolation below) — prompting would discard the
    # answer.
    cfg.update(_creds(ctx, with_location=False))
    sizes = _vm_sizes(ctx, cfg)
    cfg["azure_size"] = r.choose("azure_size", "Azure VM Size",
                                 [(s, s) for s in sizes],
                                 default=preferred_default(sizes, VM_SIZES))
    cfg["azure_subnet_id"] = f"${{module.{cluster_key}.azure_subnet_id}}"
    # Real-path placement: hosts land in the cluster's resource group and
    # location (the azure-k8s HCL module exports both).
    cfg["azure_resource_group"] = \
        f"${{module.{cluster_key}.azure_resource_group}}"
    cfg["azure_location"] = f"${{module.{cluster_key}.azure_location}}"
    cfg["azure_public_key_path"] = r.value(
        "azure_public_key_path", "Azure Public Key Path",
        default="~/.ssh/id_rsa.pub")
    disk_type = r.value("managed_disk_type", "Managed Disk Type", default="")
    if disk_type:
        cfg["managed_disk_type"] = disk_type
        cfg["managed_disk_size"] = int(r.value("managed_disk_size",
                                               "Managed Disk Size (GB)",
                                               default=100))
        cfg["managed_disk_mount_path"] = r.value(
            "managed_disk_mount_path", "Managed Disk Mount Path",
            default="/mnt/data")
    return state.add_node(cluster_key, hostname, cfg)


def aks_cluster_config(ctx: WorkflowContext, state: StateDocument, name: str) -> str:
    """Hosted AKS path (create/cluster_aks.go analog)."""
    r = ctx.resolver
    cfg = {
        "source": module_source(ctx, "aks-k8s"),
        "name": name,
        "manager_url": "${module.cluster-manager.manager_url}",
        "manager_access_key": "${module.cluster-manager.manager_access_key}",
        "manager_secret_key": "${module.cluster-manager.manager_secret_key}",
        **_creds(ctx),
    }
    sizes = _vm_sizes(ctx, cfg)
    versions = ctx.choices(
        "aks", "k8s_versions", [],
        {**cfg, "location": cfg.get("azure_location", "")})
    cfg.update({
        "azure_size": r.choose("azure_size", "Azure VM Size",
                               [(s, s) for s in sizes],
                               default=preferred_default(sizes, VM_SIZES)),
        "azure_ssh_user": r.value("azure_ssh_user", "Azure SSH User",
                                  default="azureuser"),
        "azure_public_key_path": r.value("azure_public_key_path",
                                         "Azure Public Key Path",
                                         default="~/.ssh/id_rsa.pub"),
        # Validated against live AKS orchestrator versions when the
        # catalog has them (cluster_aks.go analog), free-form otherwise.
        "k8s_version": (r.choose("k8s_version", "Kubernetes Version",
                                 [(v, v) for v in versions],
                                 default=versions[0]) if versions
                        else r.value("k8s_version", "Kubernetes Version",
                                     default="1.31")),
        "node_count": int(r.value("node_count", "Node Count", default=3)),
    })
    return state.add_cluster("aks", name, cfg)
