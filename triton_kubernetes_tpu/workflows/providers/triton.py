"""Triton provider workflows (create/manager_triton.go:25-399,
create/cluster_triton.go:16-140, create/node_triton.go:23-328 analogs)."""

from __future__ import annotations

from ...state import StateDocument
from ..common import WorkflowContext, WorkflowError, preferred_default
from .base import base_cluster_config, base_manager_config, base_node_config

TRITON_URLS = [
    "https://us-east-1.api.joyent.com",
    "https://us-west-1.api.joyent.com",
    "https://eu-ams-1.api.joyentcloud.com",
]
IMAGES = ["ubuntu-certified-16.04", "ubuntu-certified-18.04"]
PACKAGES = ["k4-highcpu-kvm-1.75G", "k4-highcpu-kvm-3.75G", "k4-general-kvm-7.75G"]
NETWORKS = ["Joyent-SDC-Public", "Joyent-SDC-Private"]


def _creds(ctx: WorkflowContext) -> dict:
    r = ctx.resolver
    key_path = r.value("triton_key_path", "Triton Key Path",
                       default="~/.ssh/id_rsa")
    key_id = r.value("triton_key_id", "Triton Key ID", default="")
    if not key_id:
        # Derive the md5 fingerprint from the private key, the reference's
        # fallback (util/ssh_utils.go:13-42 via create/manager_triton.go).
        from ...utils.ssh import SSHKeyError, public_key_fingerprint_from_private_key

        try:
            key_id = public_key_fingerprint_from_private_key(str(key_path))
        except SSHKeyError as e:
            # Encrypted key: the reference prompts for the passphrase and
            # retries (util/ssh_utils.go:22-28); interactive sessions get
            # the same masked prompt here, non-interactive keeps the clean
            # error (a silent install cannot answer).
            if "passphrase" not in str(e) or r.non_interactive:
                raise WorkflowError(
                    f"triton_key_id not set and it could not be derived: {e}")
            passphrase = r.secret("triton_key_passphrase",
                                  "SSH Key Passphrase")
            try:
                key_id = public_key_fingerprint_from_private_key(
                    str(key_path), str(passphrase).encode())
            except SSHKeyError as e2:
                raise WorkflowError(
                    f"triton_key_id not set and it could not be derived: "
                    f"{e2}")
    return {
        "triton_account": r.value("triton_account", "Triton Account Name"),
        "triton_key_path": key_path,
        "triton_key_id": key_id,
        # Free-form (the reference offered a menu of Joyent public-cloud
        # regions; those are gone — private installations are the norm, so
        # any CloudAPI endpoint must be accepted).
        "triton_url": r.value("triton_url", "Triton URL",
                              default=TRITON_URLS[0]),
    }


def _cat(ctx: WorkflowContext, kind: str, fallback: list,
         creds: dict) -> list:
    """Live CloudAPI choices when `catalog: live` (the reference's
    validated prompts, create/manager_triton.go:352-396), static
    fallback otherwise."""
    return ctx.choices("triton", kind, fallback, creds)


def manager_config(ctx: WorkflowContext, state: StateDocument, name: str) -> None:
    r = ctx.resolver
    cfg = base_manager_config(ctx, "triton-manager", name)
    cfg.update(_creds(ctx))
    images = _cat(ctx, "images", IMAGES, cfg)
    packages = _cat(ctx, "packages", PACKAGES, cfg)
    cfg["triton_image_name"] = r.choose(
        "triton_image_name", "Triton Image", [(i, i) for i in images],
        default=preferred_default(images, IMAGES))
    cfg["triton_machine_package"] = r.choose(
        "master_triton_machine_package", "Triton Machine Package",
        [(p, p) for p in packages],
        default=preferred_default(packages, PACKAGES))
    networks = _cat(ctx, "networks", NETWORKS, cfg)
    cfg["triton_network_names"] = r.value(
        "triton_network_names", "Triton Networks",
        default=[preferred_default(networks, NETWORKS)])
    state.set_manager(cfg)


def cluster_config(ctx: WorkflowContext, state: StateDocument, name: str) -> str:
    cfg = base_cluster_config(ctx, "triton-k8s", name)
    cfg.update(_creds(ctx))
    return state.add_cluster("triton", name, cfg)


def node_config(ctx: WorkflowContext, state: StateDocument, cluster_key: str,
                hostname: str, host_label: str) -> str:
    r = ctx.resolver
    cfg = base_node_config(ctx, "triton-k8s-host", cluster_key, hostname, host_label)
    cfg.update(_creds(ctx))
    images = _cat(ctx, "images", IMAGES, cfg)
    packages = _cat(ctx, "packages", PACKAGES, cfg)
    networks = _cat(ctx, "networks", NETWORKS, cfg)
    cfg["triton_image_name"] = r.choose(
        "triton_image_name", "Triton Image", [(i, i) for i in images],
        default=preferred_default(images, IMAGES))
    cfg["triton_ssh_user"] = r.value("triton_ssh_user", "Triton SSH User",
                                     default="ubuntu")
    cfg["triton_machine_package"] = r.choose(
        "triton_machine_package", "Triton Machine Package",
        [(p, p) for p in packages],
        default=preferred_default(packages, PACKAGES))
    cfg["triton_network_names"] = r.value(
        "triton_network_names", "Triton Networks",
        default=[preferred_default(networks, NETWORKS)])
    return state.add_node(cluster_key, hostname, cfg)
