"""Shared workflow plumbing: context object, selection helpers, error contracts.

The selection helpers reproduce the reference's exact non-interactive error
strings (get/cluster.go:23-82, destroy/node.go:24-126, create/node.go:51-112)
so silent-mode behavior is pin-compatible.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..backends import Backend
from ..catalogs import Catalog
from ..config import Config, InputResolver, MissingInputError
from ..state import StateDocument


class WorkflowError(RuntimeError):
    pass


@dataclass
class WorkflowContext:
    backend: Backend
    executor: object  # LocalExecutor or TerraformExecutor
    resolver: InputResolver
    # Provider choice catalog (live cloud APIs when `catalog: live`);
    # the default has no opinions, so static lists rule.
    catalog: Catalog = field(default_factory=Catalog)

    @property
    def config(self) -> Config:
        return self.resolver.config

    @property
    def non_interactive(self) -> bool:
        return self.resolver.non_interactive

    def choices(self, provider: str, kind: str, fallback: List[str],
                context: Optional[Dict[str, Any]] = None) -> List[str]:
        """Catalog-backed prompt options with a static fallback — the
        reference's live-API validated prompts (create/manager_gcp.go
        :22-422), behind one seam."""
        live = self.catalog.choices(provider, kind, context)
        return list(live) if live else fallback


def preferred_default(options: List[str], curated: List[str]) -> str:
    """Non-interactive default for a live-catalog choice: the first
    curated (static-list) entry the live options actually offer, else the
    first option. A silent install must not land on whatever cloud object
    happens to sort first."""
    for c in curated:
        if c in options:
            return c
    return options[0]


def module_source(ctx: WorkflowContext, name: str) -> str:
    """Module source string, honoring the local-dev redirect keys
    (``source_url``/``source_ref``; reference create/cluster.go:20-22,305-312)."""
    base = ctx.config.get("source_url")
    if base:
        ref = ctx.config.get("source_ref", "master")
        return f"{base}//modules/{name}?ref={ref}"
    return f"modules/{name}"


def select_manager(ctx: WorkflowContext,
                   none_message: str = "No cluster managers.") -> str:
    """Pick a cluster manager from the backend's persisted states."""
    states = ctx.backend.states()
    if not states:
        raise WorkflowError(none_message)
    if ctx.config.is_set("cluster_manager"):
        name = ctx.config.get("cluster_manager")
        if name not in states:
            raise WorkflowError(
                f"Selected cluster manager '{name}' does not exist.")
        return name
    if ctx.non_interactive:
        raise MissingInputError("cluster_manager must be specified")
    return ctx.resolver.prompter.select(
        "Cluster Manager", [(s, s) for s in states])


def select_cluster(ctx: WorkflowContext, state: StateDocument) -> Tuple[str, str]:
    """Pick a cluster from the state doc; returns (name, module_key)."""
    clusters = state.clusters()
    if not clusters:
        raise WorkflowError("No clusters.")
    if ctx.config.is_set("cluster_name"):
        name = ctx.config.get("cluster_name")
        if name not in clusters:
            raise WorkflowError(f"A cluster named '{name}', does not exist.")
        return name, clusters[name]
    if ctx.non_interactive:
        raise MissingInputError("cluster_name must be specified")
    name = ctx.resolver.prompter.select(
        "Cluster", [(n, n) for n in sorted(clusters)])
    return name, clusters[name]


def select_node(ctx: WorkflowContext, state: StateDocument,
                cluster_key: str) -> Tuple[str, str]:
    """Pick a node of a cluster; returns (hostname, module_key)."""
    nodes = state.nodes(cluster_key)
    if not nodes:
        raise WorkflowError("No nodes.")
    if ctx.config.is_set("hostname"):
        hostname = ctx.config.get("hostname")
        if hostname not in nodes:
            raise WorkflowError(f"A node named '{hostname}', does not exist.")
        return hostname, nodes[hostname]
    if ctx.non_interactive:
        raise MissingInputError("hostname must be specified")
    hostname = ctx.resolver.prompter.select(
        "Node", [(n, n) for n in sorted(nodes)])
    return hostname, nodes[hostname]
