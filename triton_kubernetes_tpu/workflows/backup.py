"""``create backup`` workflow.

Reference analog: create/backup.go:17-215 — pick manager, pick cluster,
reject if a backup already exists (one per cluster, :119-123), choose the
storage kind, apply, persist. Kinds: gcs (new, TPU-era checkpoints), s3,
manta (parity).
"""

from __future__ import annotations

from .common import WorkflowContext, WorkflowError, module_source, select_cluster, select_manager

BACKUP_KINDS = ["gcs", "s3", "manta"]


def new_backup(ctx: WorkflowContext) -> str:
    r = ctx.resolver
    manager = select_manager(
        ctx, "No cluster managers, please create a cluster manager "
             "before creating a backup.")
    state = ctx.backend.state(manager)
    cluster_name, cluster_key = select_cluster(ctx, state)

    if state.backup(cluster_key) is not None:
        raise WorkflowError(
            f"A backup for cluster '{cluster_name}' already exists.")

    kind = r.choose("backup_cloud_provider", "Backup Storage",
                    [(k, k) for k in BACKUP_KINDS])
    cfg = {
        "source": module_source(ctx, f"k8s-backup-{kind}"),
        "cluster_name": cluster_name,
        "cluster_id": f"${{module.{cluster_key}.cluster_id}}",
        # Manager credentials for the kubeconfig mint on the real path
        # (files/setup_backup.sh); reference wires the same via
        # rancher_api_url/access/secret (create/backup.go base config).
        "manager_url": "${module.cluster-manager.manager_url}",
        "manager_access_key": "${module.cluster-manager.manager_access_key}",
        "manager_secret_key": "${module.cluster-manager.manager_secret_key}",
    }
    if kind == "gcs":
        cfg["gcp_path_to_credentials"] = r.value(
            "gcp_path_to_credentials", "Path to GCP credentials file")
        cfg["gcs_bucket"] = r.value("gcs_bucket", "GCS Bucket")
    elif kind == "s3":
        cfg["aws_access_key"] = r.value("aws_access_key", "AWS Access Key")
        cfg["aws_secret_key"] = r.value("aws_secret_key", "AWS Secret Key")
        cfg["aws_region"] = r.value("aws_region", "AWS Region",
                                    default="us-east-1")
        cfg["aws_s3_bucket"] = r.value("aws_s3_bucket", "S3 Bucket")
    else:
        cfg["triton_account"] = r.value("triton_account", "Triton Account Name")
        cfg["triton_key_path"] = r.value("triton_key_path", "Triton Key Path",
                                         default="~/.ssh/id_rsa")
        cfg["triton_key_id"] = r.value("triton_key_id", "Triton Key ID",
                                       default="")
        cfg["manta_subuser"] = r.value("manta_subuser", "Manta Subuser",
                                       default="")

    backup_key = state.add_backup(cluster_key, cfg)

    if not r.confirm("confirm", f"Proceed? This will back up '{cluster_name}'"):
        state.delete(f"module.{backup_key}")
        return ""

    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    ctx.executor.apply(state)
    ctx.backend.persist(state)
    return backup_key
