"""``get {manager,cluster}`` workflows.

Reference analogs: get/manager.go:14-67, get/cluster.go:15-113 (``terraform
output -module <key>``). Reads come from cached applied state — no re-init
(fixing the reference's heavyweight read path, SURVEY.md §3.5).
"""

from __future__ import annotations

from typing import Any, Dict

from ..state import MANAGER_KEY
from .common import WorkflowContext, select_cluster, select_manager


def get_manager(ctx: WorkflowContext) -> Dict[str, Any]:
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    return ctx.executor.output(state, MANAGER_KEY)


def get_cluster(ctx: WorkflowContext) -> Dict[str, Any]:
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    _, cluster_key = select_cluster(ctx, state)
    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    return ctx.executor.output(state, cluster_key)
