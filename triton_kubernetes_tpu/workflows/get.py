"""``get {manager,cluster}`` workflows.

Reference analogs: get/manager.go:14-67, get/cluster.go:15-113 (``terraform
output -module <key>``). Reads come from cached applied state — no re-init
(fixing the reference's heavyweight read path, SURVEY.md §3.5).
"""

from __future__ import annotations

from typing import Any, Dict

from ..state import MANAGER_KEY
from .common import WorkflowContext, select_cluster, select_manager


def get_manager(ctx: WorkflowContext) -> Dict[str, Any]:
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    return ctx.executor.output(state, MANAGER_KEY)


def get_cluster(ctx: WorkflowContext) -> Dict[str, Any]:
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    _, cluster_key = select_cluster(ctx, state)
    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    outputs = ctx.executor.output(state, cluster_key)
    health = _node_health(ctx, state, outputs.get("cluster_id"),
                          outputs.get("ca_checksum", ""))
    if health is not None:
        outputs = {**outputs, "node_health": health}
        # Consume NotReady (round-3 verdict #9): dead hosts surface with a
        # concrete recovery action instead of sitting in a listing nobody
        # reads. The reference's agents ride `--restart=unless-stopped` +
        # Rancher reconciliation; a host that stays NotReady past the
        # heartbeat window needs replacement.
        dead = sorted(h for h, st in health.items() if not st.get("ready"))
        if dead:
            outputs["unhealthy_nodes"] = dead
            outputs["hint"] = (
                "node(s) not ready — replace with: destroy node "
                "(--set hostname=<name>) then create node; agent details: "
                + "; ".join(f"{h}: {health[h].get('reason') or 'NotReady'}"
                            for h in dead))
    return outputs


def _node_health(ctx: WorkflowContext, state, cluster_id,
                 ca_checksum: str = "") -> Any:
    """Best-effort node health for the `get cluster` read (SURVEY.md §5
    failure-detection obligation), in trust order: the live tk8s-manager
    nodes listing (heartbeat-driven NotReady, manager/server.py), real
    kubelet conditions when the doc's driver is real and its binaries are
    present, the simulator's recorded agent health otherwise."""
    if not cluster_id:
        return None
    live = _live_manager_health(ctx, state, cluster_id, ca_checksum)
    if live is not None:
        return live
    if not hasattr(ctx.executor, "cloud_view"):
        return None
    view = ctx.executor.cloud_view(state)
    try:
        from ..executor.drivers import make_driver

        driver = make_driver(state, view.to_dict())
        return driver.node_health(cluster_id)
    except Exception:
        try:
            return view.node_health(cluster_id)
        except Exception:
            return None


def _live_manager_health(ctx: WorkflowContext, state,
                         cluster_id, ca_checksum: str = "") -> Any:
    """GET /v3/clusters/<id>/nodes against the real control plane when the
    manager module's applied outputs carry a reachable URL + credentials;
    None (fall through) otherwise. This is the consumer of the server's
    heartbeat-staleness NotReady flip.

    The channel is pinned before credentials cross it: the cluster's
    ca_checksum (read from the same applied outputs) anchors the client's
    SSL context to the manager's served cert (manager/tls.py trust model)
    — a read-only command must not leak the admin keys to an on-path
    attacker. Timeout is short: this is a best-effort enrichment of a
    local read, and the manager being down is exactly when operators run
    `get cluster`."""
    try:
        mgr = ctx.executor.output(state, MANAGER_KEY)
    except Exception:
        return None
    url = mgr.get("manager_url", "")
    if not url.startswith(("http://", "https://")):
        return None
    from ..manager.client import CAPinMismatchError, ManagerClient

    client = ManagerClient(url, mgr.get("manager_access_key", ""),
                           mgr.get("manager_secret_key", ""),
                           retries=0, timeout=3.0)
    try:
        if url.startswith("https://"):
            # Pin before ANY authed request. With no stored checksum the
            # pin is trust-on-first-use (anchor to the served PEM): weaker
            # than a checksum, but the admin keys never ride a CERT_NONE
            # channel.
            client.pin_ca(ca_checksum)
    except CAPinMismatchError as e:
        # A possible active-MITM indicator — must not be silently
        # indistinguishable from the manager being down.
        from ..utils.logging import get_logger

        get_logger().log(
            "warn", "manager CA checksum mismatch — possible MITM or "
            "rotated cert; skipping live health", detail=str(e))
        return None
    except Exception:
        return None
    try:
        nodes = client.nodes(cluster_id)
    except Exception:
        return None
    if not nodes:
        # Hosted clusters (GKE/AKS) never run tk8s agents — an empty
        # listing is "no data", not "no nodes"; fall through to the
        # driver/kubelet view.
        return None
    return {n["hostname"]: {"ready": n.get("state") != "NotReady",
                            "reason": ("stale agent heartbeat"
                                       if n.get("state") == "NotReady"
                                       else "")}
            for n in nodes}
