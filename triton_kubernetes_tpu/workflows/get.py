"""``get {manager,cluster}`` workflows.

Reference analogs: get/manager.go:14-67, get/cluster.go:15-113 (``terraform
output -module <key>``). Reads come from cached applied state — no re-init
(fixing the reference's heavyweight read path, SURVEY.md §3.5).
"""

from __future__ import annotations

from typing import Any, Dict

from ..state import MANAGER_KEY
from .common import WorkflowContext, select_cluster, select_manager


def get_manager(ctx: WorkflowContext) -> Dict[str, Any]:
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    return ctx.executor.output(state, MANAGER_KEY)


def get_cluster(ctx: WorkflowContext) -> Dict[str, Any]:
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    _, cluster_key = select_cluster(ctx, state)
    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    outputs = ctx.executor.output(state, cluster_key)
    health = _node_health(ctx, state, outputs.get("cluster_id"))
    if health is not None:
        outputs = {**outputs, "node_health": health}
    return outputs


def _node_health(ctx: WorkflowContext, state, cluster_id) -> Any:
    """Best-effort live node health for the `get cluster` read (SURVEY.md
    §5 failure-detection obligation): real kubelet conditions when the
    doc's driver is real and its binaries are present, the recorded agent
    health otherwise, nothing if the executor has no cloud view."""
    if not cluster_id or not hasattr(ctx.executor, "cloud_view"):
        return None
    view = ctx.executor.cloud_view(state)
    try:
        from ..executor.drivers import make_driver

        driver = make_driver(state, view.to_dict())
        return driver.node_health(cluster_id)
    except Exception:
        try:
            return view.node_health(cluster_id)
        except Exception:
            return None
