"""L4 workflows: create/destroy/get for managers, clusters, nodes, backups.

Reference analog: ``create/``, ``destroy/``, ``get/`` — interactive or
silent-YAML flows that mutate the state document and run the executor, with
the commit-after-success discipline (persist only after apply succeeded,
create/manager.go:139-151). Error-string contracts for non-interactive guard
rails are preserved verbatim from the reference (SURVEY.md §4: "cheap and
clearly effective at pinning the silent-mode contract").
"""

from .common import WorkflowContext, WorkflowError
from .manager import new_manager
from .cluster import new_cluster
from .node import new_node
from .backup import new_backup
from .restore import restore_backup
from .destroy import delete_cluster, delete_manager, delete_node
from .get import get_cluster, get_manager
from .repair import (
    HealthLookupError,
    NoPreemptedSlicesError,
    NoUnhealthyNodesError,
    repair_node,
    repair_slice,
    repair_slice_auto,
)

__all__ = [
    "HealthLookupError",
    "NoPreemptedSlicesError",
    "NoUnhealthyNodesError",
    "WorkflowContext",
    "WorkflowError",
    "repair_slice",
    "repair_slice_auto",
    "delete_cluster",
    "delete_manager",
    "delete_node",
    "get_cluster",
    "get_manager",
    "new_backup",
    "repair_node",
    "restore_backup",
    "new_cluster",
    "new_manager",
    "new_node",
]
