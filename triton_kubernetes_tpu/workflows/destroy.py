"""``destroy {manager,cluster,node}`` workflows.

Reference analogs: destroy/manager.go:16-97 (full destroy then delete the
state from the backend), destroy/cluster.go:16-181 (targeted destroy fan-out:
cluster + every node + backup, then prune the doc and persist),
destroy/node.go:16-186 (single-node targeted destroy).
"""

from __future__ import annotations

from .common import (
    WorkflowContext,
    WorkflowError,
    select_cluster,
    select_manager,
    select_node,
)


def delete_manager(ctx: WorkflowContext) -> str:
    r = ctx.resolver
    manager = select_manager(
        ctx, "No cluster managers, please create a cluster manager "
             "before creating a kubernetes cluster.")
    if not r.confirm("confirm",
                     f"Proceed? This will destroy manager '{manager}' "
                     "and everything it manages"):
        return ""
    state = ctx.backend.state(manager)
    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    ctx.executor.destroy(state)  # no targets: whole graph
    ctx.backend.delete(manager)
    return manager


def delete_cluster(ctx: WorkflowContext) -> str:
    r = ctx.resolver
    manager = select_manager(ctx)
    state = ctx.backend.state(manager)
    cluster_name, cluster_key = select_cluster(ctx, state)
    if not r.confirm("confirm",
                     f"Proceed? This will destroy cluster '{cluster_name}'"):
        return ""

    # Target fan-out: the cluster module, all its nodes, and its backup
    # (destroy/cluster.go:126-143).
    targets = [cluster_key]
    targets.extend(state.nodes(cluster_key).values())
    backup_key = state.backup(cluster_key)
    if backup_key:
        targets.append(backup_key)

    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    ctx.executor.destroy(state, targets=targets)
    for key in targets:
        state.delete(f"module.{key}")
    ctx.backend.persist(state)
    return cluster_key


def delete_node(ctx: WorkflowContext) -> str:
    r = ctx.resolver
    manager = select_manager(
        ctx, "No cluster managers, please create a cluster manager "
             "before creating a kubernetes node.")
    state = ctx.backend.state(manager)
    _, cluster_key = select_cluster(ctx, state)
    hostname, node_key = select_node(ctx, state, cluster_key)
    if not r.confirm("confirm",
                     f"Proceed? This will destroy node '{hostname}'"):
        return ""
    state.set_backend_config(ctx.backend.executor_backend_config(manager))
    ctx.executor.destroy(state, targets=[node_key])
    state.delete(f"module.{node_key}")
    ctx.backend.persist(state)
    return node_key
