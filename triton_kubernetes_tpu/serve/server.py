"""The ``tk8s serve`` HTTP front end.

Same construction as the manager control plane (manager/server.py):
stdlib ``ThreadingHTTPServer``, embeddable in tests as a context
manager, Prometheus ``/metrics`` and ``/healthz`` unauthenticated. What
is new is the threading shape: :class:`ServeEngine` is single-owner, so
handler threads never touch it — they validate, enqueue a waiter into
the engine loop's inbox, and block on its event. One **engine loop**
thread drains the inbox, calls ``engine.step()`` while work exists, and
resolves waiters as requests complete. Continuous batching falls out:
requests that arrive while a step runs are admitted at the next tick
and decode in the same batch as everything already running.

Wire surface:

========  ============  =========================================
method    path          body / response
========  ============  =========================================
GET       /healthz      ``{"ok": true, "model": ...}``
GET       /metrics      Prometheus text (tk8s_serve_* et al.)
GET       /stats        engine scheduler/pool snapshot (JSON)
POST      /generate     ``{"tokens": [ids...], "max_new_tokens": N,
                        "temperature"/"top_k"/"top_p"/"eos_id"/"seed"
                        /"handoff"}``
                        → ``{"tokens": [...], "finish_reason",
                        "ttft_s", "tpot_s", "preemptions", ...}``
POST      /migrate/out  ``{"request_id", "dest", "reason"}`` — pack the
                        session, ship it to ``dest``'s /migrate/in,
                        release on confirm / resume on failure
POST      /migrate/in   raw wire unit (serve/migration.py) →
                        ``{"request_id": local id}``; 400 on torn
POST      /await        ``{"request_id"}`` → /generate-shaped body when
                        an imported session completes
POST      /resume       ``{"request_id"}`` → /generate-shaped body:
                        un-park a session and finish it HERE (the
                        failed-transfer fallback)
========  ============  =========================================

The migration endpoints keep the single-owner rule: engine calls run as
closures on the engine loop (``_op``); only the dumb byte shipping —
an outbound POST of an already-packed payload — happens on the handler
thread, and never under a lock.
"""

from __future__ import annotations

import json
import queue
import random
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from http.server import ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import urlparse

from ..utils import metrics
from ..utils.trace import TRACE_HEADER, FlightRecorder, valid_trace_id
from ._http import JSONHandler, route_label
from .engine import FinishedRequest, Request, ServeEngine
from .migration import MigrationError, TornPayloadError

# Default port for rendered manifests and the CLI (the serving analog of
# the manager's API port; /metrics rides the same listener).
# Single-sourced from constants.py; topology/serving.py renders the same
# value (lint rule TK8S104 keeps every site agreeing).
from ..constants import SERVE_PORT

@dataclass
class _Waiter:
    event: threading.Event = field(default_factory=threading.Event)
    result: Optional[FinishedRequest] = None
    error: Optional[str] = None
    fatal: bool = False  # loop death (503), not request rejection (400)


@dataclass
class _OpResult:
    """A migration control call marshaled onto the engine loop: the
    closure's return value or its exception, verbatim, so the handler
    thread can map typed MigrationErrors to status codes."""

    event: threading.Event = field(default_factory=threading.Event)
    value: Any = None
    exc: Optional[BaseException] = None


class _Handler(JSONHandler):
    server_version = "tk8s-serve"
    serve: "ServeHTTPServer"  # injected by ServeHTTPServer

    def send_response(self, code: int, message: Optional[str] = None) -> None:
        self._last_code = code
        super().send_response(code, message)

    def _counted(self, handler) -> None:
        self._last_code = 0
        try:
            handler()
        finally:
            metrics.counter("tk8s_serve_http_requests_total").inc(
                route=route_label(urlparse(self.path).path),
                method=self.command, code=str(self._last_code))

    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        self._counted(self._get)

    def do_POST(self) -> None:  # noqa: N802
        self._counted(self._post)

    def _get(self) -> None:
        parsed = urlparse(self.path)
        path = parsed.path
        if path == "/healthz":
            # Health is the ENGINE LOOP's, not this handler thread's: a
            # dead scheduler must flip the liveness probe (the rendered
            # Deployment restarts on /healthz), not serve 200 forever.
            err = self.serve.loop_error
            if err is not None:
                self._json(503, {"ok": False, "error": err,
                                 "model": self.serve.engine.config.name})
                return
            self._json(200, {"ok": True,
                             "model": self.serve.engine.config.name})
        elif path == "/metrics":
            self._metrics_response(metrics.get_registry(), parsed.query)
        elif path == "/stats":
            self._json(200, self.serve.engine.stats())
        else:
            self._json(404, {"type": "error", "message": "not found"})

    def _post(self) -> None:
        path = urlparse(self.path).path
        if path == "/migrate/out":
            self._migrate_out()
            return
        if path == "/migrate/in":
            self._migrate_in()
            return
        if path == "/await":
            self._await()
            return
        if path == "/resume":
            self._resume()
            return
        if path != "/generate":
            self._json(404, {"type": "error", "message": "not found"})
            return
        n = int(self.headers.get("Content-Length") or 0)
        try:
            d = json.loads(self.rfile.read(n) if n else b"{}")
            if not isinstance(d, dict):
                raise ValueError("body must be a JSON object")
            tokens = d.get("tokens")
            if (not isinstance(tokens, list)
                    or not all(isinstance(t, int) for t in tokens)):
                raise ValueError("'tokens' must be a list of token ids")
            eos_id = d.get("eos_id")
            sid = d.get("session_id")
            if sid is not None and not isinstance(sid, str):
                # The router's affinity key rides along to the replica;
                # a malformed one is the caller's fault, not ours to
                # coerce (the engine itself never reads it).
                raise ValueError("'session_id' must be a string")
            opts = {
                "max_new_tokens": int(d.get("max_new_tokens", 16)),
                "temperature": float(d.get("temperature", 0.0)),
                "top_k": int(d.get("top_k", 0)),
                "top_p": float(d.get("top_p", 1.0)),
                "eos_id": int(eos_id) if eos_id is not None else None,
                "seed": int(d.get("seed", 0)),
                # Disaggregation: a prefill-pool replica answers with
                # the first token and finish_reason "handoff", pages
                # parked for /migrate/out (router sets this).
                "handoff": bool(d.get("handoff", False)),
            }
        except (ValueError, TypeError) as e:
            # TypeError too: float(None)/int([]) from a malformed body is
            # the caller's fault, not a handler crash.
            self._json(400, {"type": "error", "message": str(e)})
            return
        # The trace-context header: the router (or any upstream) minted
        # the id; this replica propagates it through the engine so its
        # whole lifecycle is recorded under the fleet-wide id. Absent
        # OR invalid header (hostile/binary bytes must not ride into
        # span fields) = direct traffic; the engine falls back to the
        # local request id.
        trace_id = self.headers.get(TRACE_HEADER)
        if not valid_trace_id(trace_id):
            trace_id = None
        try:
            done = self.serve.generate(tokens, trace_id=trace_id, **opts)
        except ValueError as e:  # engine validation: caller's fault
            self._json(400, {"type": "error", "message": str(e)})
            return
        except TimeoutError as e:
            # Per-request timeout, NOT engine death: 504 so the router
            # can tell "slow" from "dead" — a 503 here would eject this
            # replica and re-run the same long generation on its peers
            # (serve/router.py's eject-storm contract).
            self._json(504, {"type": "error", "message": str(e)})
            return
        except RuntimeError as e:  # engine-loop death: liveness event
            self._json(503, {"type": "error", "message": str(e)})
            return
        self._json(200, _finished_body(done))

    # ------------------------------------------------------- migration
    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _json_body(self) -> Dict[str, Any]:
        d = json.loads(self._read_body() or b"{}")
        if not isinstance(d, dict):
            raise ValueError("body must be a JSON object")
        return d

    def _migrate_out(self) -> None:
        try:
            d = self._json_body()
            rid = str(d["request_id"])
            dest = str(d["dest"])
            reason = str(d.get("reason", "handoff"))
        except (ValueError, KeyError, TypeError) as e:
            self._json(400, {"type": "error", "message": str(e)})
            return
        try:
            body = self.serve.migrate_out(rid, dest, reason)
        except MigrationError as e:  # no such session / not exportable
            self._json(404, {"type": "error", "message": str(e)})
            return
        except (TimeoutError, RuntimeError) as e:
            self._json(503, {"type": "error", "message": str(e)})
            return
        if "error" in body:
            # Transfer failed: the session was resumed locally and the
            # source keeps serving it un-degraded. 502 tells the caller
            # the DESTINATION (not this replica) refused the bytes.
            self._json(502, body)
            return
        self._json(200, body)

    def _migrate_in(self) -> None:
        payload = self._read_body()
        reason = self.headers.get("X-TK8S-Migrate-Reason") or "handoff"
        try:
            body = self.serve.migrate_in(payload, reason)
        except TornPayloadError as e:
            self._json(400, {"type": "error", "torn": True,
                             "message": str(e)})
            return
        except MigrationError as e:  # incompatible / pool pressure
            self._json(409, {"type": "error", "torn": False,
                             "message": str(e)})
            return
        except (TimeoutError, RuntimeError) as e:
            self._json(503, {"type": "error", "message": str(e)})
            return
        self._json(200, body)

    def _await(self) -> None:
        try:
            rid = str(self._json_body()["request_id"])
        except (ValueError, KeyError, TypeError) as e:
            self._json(400, {"type": "error", "message": str(e)})
            return
        waiter = self.serve.imported_waiter(rid)
        if waiter is None:
            self._json(404, {"type": "error",
                             "message": f"no imported session {rid!r}"})
            return
        if not waiter.event.wait(self.serve.request_timeout_s):
            self._json(504, {"type": "error",
                             "message": f"{rid}: still decoding after "
                             f"{self.serve.request_timeout_s}s"})
            return
        if waiter.fatal or waiter.error is not None:
            self._json(503 if waiter.fatal else 400,
                       {"type": "error", "message": waiter.error})
            return
        assert waiter.result is not None
        self.serve.forget_imported(rid)
        self._json(200, _finished_body(waiter.result))

    def _resume(self) -> None:
        try:
            rid = str(self._json_body()["request_id"])
        except (ValueError, KeyError, TypeError) as e:
            self._json(400, {"type": "error", "message": str(e)})
            return
        try:
            done = self.serve.resume(rid)
        except MigrationError as e:
            self._json(404, {"type": "error", "message": str(e)})
            return
        except TimeoutError as e:
            self._json(504, {"type": "error", "message": str(e)})
            return
        except RuntimeError as e:
            self._json(503, {"type": "error", "message": str(e)})
            return
        self._json(200, _finished_body(done))


def _finished_body(done: FinishedRequest) -> Dict[str, Any]:
    body: Dict[str, Any] = {
        "request_id": done.request_id,
        "tokens": done.tokens,
        "prompt_len": done.prompt_len,
        "finish_reason": done.finish_reason,
        "ttft_s": done.ttft,
        "tpot_s": done.tpot,
        "preemptions": done.preemptions,
    }
    if done.migrated_to is not None:
        body["migrated_to"] = done.migrated_to
        body["dest_request_id"] = done.dest_request_id
    if done.trace_id is not None:
        # The per-phase latency attribution rides the response: the
        # phases sum to e2e_s exactly (the evidence-gate pin).
        body["trace_id"] = done.trace_id
        body["phases"] = done.phases
        body["e2e_s"] = done.finished_at - done.submitted_at
        if done.spec is not None:
            body["spec"] = done.spec
    return body


class DcnTransferModel:
    """Deterministic datacenter-network cost model for migration
    transfers — the serving analog of cloudsim's ``op_latency`` knob.

    Loopback tests and single-host A/Bs ship KV sessions over the
    kernel's loopback at effectively infinite bandwidth, so a
    disaggregated prefill→decode handoff looks free when the real
    deployment pays a cross-rack (or cross-DC) wire for every packed
    page. The model charges ``rtt_s + nbytes / bytes_per_s`` (plus an
    optional seeded uniform jitter in ``[0, jitter_s)``) per transfer,
    slept on the HANDLER thread around the ``/migrate/in`` POST — never
    on the engine loop and never under a lock, so a simulated slow wire
    stalls only that transfer, exactly like a real one.

    The sleeper is injectable (the cloudsim/executor pattern): tests
    assert latency *accounting* against a recorder instead of
    wall-clock thresholds that flake under load. The jitter RNG is
    seeded and private, so a fixed seed yields the same latency
    sequence run-to-run — chaos timelines that include migrations stay
    reproducible. ``to_dict``/``from_dict`` round-trip the model (sans
    sleeper) so a scenario spec can carry it."""

    def __init__(self, bytes_per_s: float = 0.0, rtt_s: float = 0.0,
                 jitter_s: float = 0.0, seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep):
        if bytes_per_s < 0 or rtt_s < 0 or jitter_s < 0:
            raise ValueError(
                f"DCN model parameters must be >= 0 (bytes_per_s="
                f"{bytes_per_s}, rtt_s={rtt_s}, jitter_s={jitter_s})")
        self.bytes_per_s = float(bytes_per_s)
        self.rtt_s = float(rtt_s)
        self.jitter_s = float(jitter_s)
        self.seed = int(seed)
        self._sleep = sleep
        self._rng = random.Random(self.seed)
        # Concurrent handler threads share the RNG; the lock keeps the
        # draw sequence deterministic per (seed, transfer index).
        self._rng_lock = threading.Lock()

    def transfer_s(self, nbytes: int) -> float:
        """Modeled seconds for one ``nbytes`` payload (draws jitter)."""
        s = self.rtt_s
        if self.bytes_per_s > 0:
            s += nbytes / self.bytes_per_s
        if self.jitter_s > 0:
            with self._rng_lock:
                s += self._rng.uniform(0.0, self.jitter_s)
        return s

    def apply(self, nbytes: int) -> float:
        """Charge one transfer: sleep the modeled latency (on the
        calling thread) and return it."""
        latency = self.transfer_s(int(nbytes))
        if latency > 0:
            self._sleep(latency)
        return latency

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.bytes_per_s:
            out["bytes_per_s"] = self.bytes_per_s
        if self.rtt_s:
            out["rtt_s"] = self.rtt_s
        if self.jitter_s:
            out["jitter_s"] = self.jitter_s
            out["seed"] = self.seed
        return out

    @classmethod
    def from_dict(cls, spec: Dict[str, Any],
                  sleep: Callable[[float], None] = time.sleep,
                  ) -> "DcnTransferModel":
        return cls(bytes_per_s=spec.get("bytes_per_s", 0.0),
                   rtt_s=spec.get("rtt_s", 0.0),
                   jitter_s=spec.get("jitter_s", 0.0),
                   seed=spec.get("seed", 0), sleep=sleep)


class ServeHTTPServer:
    """Embeddable serving endpoint:
    ``with ServeHTTPServer(engine) as url: ...`` in tests;
    ``serve_forever`` under ``tk8s serve``."""

    def __init__(self, engine: ServeEngine, host: str = "127.0.0.1",
                 port: int = 0, request_timeout_s: float = 120.0,
                 tracing: bool = True,
                 dcn: Optional[DcnTransferModel] = None):
        self.engine = engine
        if tracing and engine.flight is None:
            # Served engines trace by default (a bounded in-memory
            # recorder; JSONL export only when the caller attached a
            # writer): /generate then always carries the phase
            # breakdown. tracing=False is the overhead-A/B off arm.
            engine.flight = FlightRecorder()
        self.request_timeout_s = request_timeout_s
        # Optional simulated DCN cost charged per outbound migration
        # payload (handler thread, around the /migrate/in POST).
        self.dcn = dcn
        self._inbox: "queue.Queue[Tuple[Request, _Waiter]]" = queue.Queue()
        self._waiters: Dict[str, _Waiter] = {}
        # Migration control closures for the engine loop, and the
        # waiters /await blocks on for imported sessions (resolved by
        # the loop's ordinary finish resolution, like any request).
        self._ops: "queue.Queue[Tuple[Callable[[], Any], _OpResult]]" = (
            queue.Queue())
        self._imported: Dict[str, _Waiter] = {}
        self._id_lock = threading.Lock()
        self._next_id = 0
        self._stop = threading.Event()
        self._loop_error: Optional[str] = None
        handler = type("Handler", (_Handler,), {"serve": self})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self._engine_thread: Optional[threading.Thread] = None
        self._http_thread: Optional[threading.Thread] = None

    # ----------------------------------------------------- handler side
    def _mint_id(self, prefix: str = "req") -> str:
        with self._id_lock:
            rid = f"{prefix}-{self._next_id}"
            self._next_id += 1
        return rid

    def generate(self, tokens, **opts) -> FinishedRequest:
        rid = self._mint_id()
        request = Request(request_id=rid, tokens=list(tokens), **{
            "max_new_tokens": opts.get("max_new_tokens", 16),
            "temperature": opts.get("temperature", 0.0),
            "top_k": opts.get("top_k", 0),
            "top_p": opts.get("top_p", 1.0),
            "eos_id": opts.get("eos_id"),
            "seed": opts.get("seed", 0),
            "trace_id": opts.get("trace_id"),
            "handoff": opts.get("handoff", False),
        })
        # Fail fast off-loop; the loop's own submit re-validates.
        self.engine.validate_request(request)
        if self._loop_error is not None:
            raise RuntimeError(f"engine loop died: {self._loop_error}")
        waiter = _Waiter()
        self._inbox.put((request, waiter))
        if not waiter.event.wait(self.request_timeout_s):
            if self._loop_error is not None:
                raise RuntimeError(
                    f"engine loop died: {self._loop_error}")
            raise TimeoutError(
                f"{rid}: no completion within {self.request_timeout_s}s")
        if waiter.fatal:
            raise RuntimeError(waiter.error or "engine loop died")
        if waiter.error is not None:
            raise ValueError(waiter.error)
        assert waiter.result is not None
        return waiter.result

    @property
    def loop_error(self) -> Optional[str]:
        """Why the engine loop died, or None while it is healthy."""
        return self._loop_error

    # ------------------------------------------------------- migration
    def _op(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` on the engine-loop thread (the engine's single
        owner) and return its result, re-raising its exception here so
        typed MigrationErrors keep their meaning across the marshal."""
        if self._loop_error is not None:
            raise RuntimeError(f"engine loop died: {self._loop_error}")
        box = _OpResult()
        self._ops.put((fn, box))
        if not box.event.wait(self.request_timeout_s):
            if self._loop_error is not None:
                raise RuntimeError(
                    f"engine loop died: {self._loop_error}")
            raise TimeoutError(
                f"engine loop did not service the migration op within "
                f"{self.request_timeout_s}s")
        if box.exc is not None:
            raise box.exc
        return box.value

    def migrate_out(self, request_id: str, dest: str,
                    reason: str) -> Dict[str, Any]:
        """Pack → ship → release (or resume). The engine calls run on
        the loop; the outbound POST of the already-packed bytes runs on
        THIS handler thread with no lock held — a slow or dead
        destination stalls only this transfer, never the scheduler."""
        def _export() -> Tuple[bytes, Optional[str]]:
            blob = self.engine.export_session(request_id, reason)
            return blob, self.engine.parked[request_id].request.trace_id

        blob, trace_id = self._op(_export)
        headers = {"Content-Type": "application/octet-stream",
                   "X-TK8S-Migrate-Reason": reason}
        if trace_id is not None:
            headers[TRACE_HEADER] = trace_id
        req = urllib.request.Request(
            dest.rstrip("/") + "/migrate/in", data=blob,
            headers=headers, method="POST")
        dest_rid, err = None, None
        ship_started = time.monotonic()
        if self.dcn is not None:
            # The simulated wire cost of shipping len(blob) — charged
            # here on the handler thread (the same thread the real POST
            # blocks), so concurrent migrations overlap their latency
            # and the engine loop never notices.
            self.dcn.apply(len(blob))
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s) as resp:
                dest_rid = json.loads(resp.read()).get("request_id")
        except urllib.error.HTTPError as e:
            try:
                detail = e.read().decode("utf-8", "replace")[:200]
            except Exception:
                detail = ""
            err = f"destination refused import: HTTP {e.code} {detail}"
        except (urllib.error.URLError, OSError, ValueError) as e:
            err = f"transfer failed: {e}"
        if err is not None:
            metrics.counter("tk8s_serve_migrations_total").inc(
                direction="out", reason=reason, status="error",
                exemplar=trace_id)
            resumed = self._op(lambda: self._recover(request_id))
            return {"type": "error", "error": err,
                    "request_id": request_id, "resumed": resumed}
        metrics.histogram("tk8s_serve_migration_transfer_seconds").observe(
            time.monotonic() - ship_started, exemplar=trace_id)

        def _release() -> int:
            done = self.engine.release_session(request_id)
            if done is not None:
                # Drain/rebalance: the original /generate client is
                # still blocked — it gets finish_reason "migrated"
                # plus the forwarding address, so the router can
                # follow the session and return the full stream.
                done.migrated_to = dest.rstrip("/")
                done.dest_request_id = dest_rid
                waiter = self._waiters.pop(request_id, None)
                if waiter is not None:
                    waiter.result = done
                    waiter.event.set()
            return len(blob)

        self._op(_release)
        return {"request_id": request_id, "dest_request_id": dest_rid,
                "bytes": len(blob)}

    def _recover(self, request_id: str) -> bool:
        """Loop-side failure recovery: a drained session resumes at
        once (its original client is still waiting); a handed-off one
        — whose client was already answered — stays parked for an
        explicit /resume, which is where its remaining tokens land."""
        seq = self.engine.parked.get(request_id)
        if seq is None:
            return False
        if seq.handed_off:
            return False
        self.engine.resume_session(request_id)
        return True

    def migrate_in(self, payload: bytes, reason: str) -> Dict[str, Any]:
        """Install a shipped session under a locally-minted id and
        register the waiter /await blocks on."""
        rid = self._mint_id("mig")
        waiter = _Waiter()

        def _import() -> None:
            self.engine.import_session(payload, request_id=rid,
                                       reason=reason)
            self._waiters[rid] = waiter
            self._imported[rid] = waiter

        self._op(_import)
        return {"request_id": rid, "bytes": len(payload)}

    def imported_waiter(self, request_id: str) -> Optional[_Waiter]:
        return self._imported.get(request_id)

    def forget_imported(self, request_id: str) -> None:
        self._imported.pop(request_id, None)

    def resume(self, request_id: str) -> FinishedRequest:
        """Un-park a handed-off session and block until it finishes
        HERE — the failed-transfer fallback: the caller (router) gets
        the same /generate-shaped completion the destination would
        have produced."""
        waiter = _Waiter()

        def _go() -> None:
            if request_id in self._waiters:
                raise MigrationError(
                    f"session {request_id!r} has a live client and "
                    f"resumes automatically")
            self.engine.resume_session(request_id)
            self._waiters[request_id] = waiter

        self._op(_go)
        if not waiter.event.wait(self.request_timeout_s):
            raise TimeoutError(
                f"{request_id}: no completion within "
                f"{self.request_timeout_s}s of resume")
        if waiter.fatal:
            raise RuntimeError(waiter.error or "engine loop died")
        if waiter.error is not None:
            raise MigrationError(waiter.error)
        assert waiter.result is not None
        return waiter.result

    # ------------------------------------------------------- engine loop
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                # Drain the inbox; block briefly only when idle so
                # shutdown and new arrivals are both prompt.
                try:
                    item = self._inbox.get(
                        timeout=0.0 if self.engine.has_work else 0.05)
                except queue.Empty:
                    item = None
                while item is not None:
                    request, waiter = item
                    try:
                        self.engine.submit(request)
                        self._waiters[request.request_id] = waiter
                    except ValueError as e:
                        waiter.error = str(e)
                        waiter.event.set()
                    try:
                        item = self._inbox.get_nowait()
                    except queue.Empty:
                        item = None
                # Migration control ops run between steps, on the
                # engine's owning thread — export/import/release never
                # race a tick.
                while True:
                    try:
                        fn, box = self._ops.get_nowait()
                    except queue.Empty:
                        break
                    try:
                        box.value = fn()
                    except Exception as e:
                        box.exc = e
                    box.event.set()
                if self.engine.has_work:
                    for done in self.engine.step():
                        waiter = self._waiters.pop(done.request_id, None)
                        if waiter is not None:
                            waiter.result = done
                            waiter.event.set()
        except BaseException as e:  # loop death is a liveness event
            self._loop_error = f"{type(e).__name__}: {e}"
            # Recorded, not re-raised: /healthz now fails (the manifest's
            # liveness probe restarts the pod) and every blocked or
            # future client gets a 503 instead of a silent 200 zombie.
            self._fail_pending()
            # Flush the flight recorder LAST: the killed requests'
            # partial lifecycles survive as post-mortem traces (and as
            # already-flushed JSONL lines) even though their clients
            # only ever saw a 503.
            try:
                self.engine.abort_inflight(self._loop_error)
            except Exception:
                pass  # post-mortem best effort: the 503 path already ran

    def _fail_pending(self) -> None:
        """Release every blocked client as 503 instead of a 120s hang:
        in-flight waiters, then anything still queued in the inbox."""
        msg = f"engine loop died: {self._loop_error}"
        for waiter in list(self._waiters.values()):
            waiter.error, waiter.fatal = msg, True
            waiter.event.set()
        self._waiters.clear()
        while True:
            try:
                _, waiter = self._inbox.get_nowait()
            except queue.Empty:
                break
            waiter.error, waiter.fatal = msg, True
            waiter.event.set()
        while True:
            try:
                _, box = self._ops.get_nowait()
            except queue.Empty:
                break
            box.exc = RuntimeError(msg)
            box.event.set()

    # ---------------------------------------------------------- lifecycle
    @property
    def address(self) -> Tuple[str, int]:
        return self.httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "ServeHTTPServer":
        self._engine_thread = threading.Thread(target=self._loop,
                                               daemon=True)
        self._engine_thread.start()
        self._http_thread = threading.Thread(
            target=lambda: self.httpd.serve_forever(poll_interval=0.05),
            daemon=True)
        self._http_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        for t in (self._engine_thread, self._http_thread):
            if t is not None:
                t.join(timeout=5)

    def serve_forever(self) -> None:
        """Foreground mode (``tk8s serve``): engine loop on this thread's
        watch, HTTP on the caller's thread."""
        self._engine_thread = threading.Thread(target=self._loop,
                                               daemon=True)
        self._engine_thread.start()
        try:
            self.httpd.serve_forever()
        finally:
            self._stop.set()

    def __enter__(self) -> "ServeHTTPServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
