"""The ``tk8s serve`` HTTP front end.

Same construction as the manager control plane (manager/server.py):
stdlib ``ThreadingHTTPServer``, embeddable in tests as a context
manager, Prometheus ``/metrics`` and ``/healthz`` unauthenticated. What
is new is the threading shape: :class:`ServeEngine` is single-owner, so
handler threads never touch it — they validate, enqueue a waiter into
the engine loop's inbox, and block on its event. One **engine loop**
thread drains the inbox, calls ``engine.step()`` while work exists, and
resolves waiters as requests complete. Continuous batching falls out:
requests that arrive while a step runs are admitted at the next tick
and decode in the same batch as everything already running.

Wire surface:

========  ============  =========================================
method    path          body / response
========  ============  =========================================
GET       /healthz      ``{"ok": true, "model": ...}``
GET       /metrics      Prometheus text (tk8s_serve_* et al.)
GET       /stats        engine scheduler/pool snapshot (JSON)
POST      /generate     ``{"tokens": [ids...], "max_new_tokens": N,
                        "temperature"/"top_k"/"top_p"/"eos_id"/"seed"}``
                        → ``{"tokens": [...], "finish_reason",
                        "ttft_s", "tpot_s", "preemptions", ...}``
========  ============  =========================================
"""

from __future__ import annotations

import json
import queue
import threading
from dataclasses import dataclass, field
from http.server import ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import urlparse

from ..utils import metrics
from ..utils.trace import TRACE_HEADER, FlightRecorder, valid_trace_id
from ._http import JSONHandler, route_label
from .engine import FinishedRequest, Request, ServeEngine

# Default port for rendered manifests and the CLI (the serving analog of
# the manager's API port; /metrics rides the same listener).
# Single-sourced from constants.py; topology/serving.py renders the same
# value (lint rule TK8S104 keeps every site agreeing).
from ..constants import SERVE_PORT

@dataclass
class _Waiter:
    event: threading.Event = field(default_factory=threading.Event)
    result: Optional[FinishedRequest] = None
    error: Optional[str] = None
    fatal: bool = False  # loop death (503), not request rejection (400)


class _Handler(JSONHandler):
    server_version = "tk8s-serve"
    serve: "ServeHTTPServer"  # injected by ServeHTTPServer

    def send_response(self, code: int, message: Optional[str] = None) -> None:
        self._last_code = code
        super().send_response(code, message)

    def _counted(self, handler) -> None:
        self._last_code = 0
        try:
            handler()
        finally:
            metrics.counter("tk8s_serve_http_requests_total").inc(
                route=route_label(urlparse(self.path).path),
                method=self.command, code=str(self._last_code))

    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        self._counted(self._get)

    def do_POST(self) -> None:  # noqa: N802
        self._counted(self._post)

    def _get(self) -> None:
        parsed = urlparse(self.path)
        path = parsed.path
        if path == "/healthz":
            # Health is the ENGINE LOOP's, not this handler thread's: a
            # dead scheduler must flip the liveness probe (the rendered
            # Deployment restarts on /healthz), not serve 200 forever.
            err = self.serve.loop_error
            if err is not None:
                self._json(503, {"ok": False, "error": err,
                                 "model": self.serve.engine.config.name})
                return
            self._json(200, {"ok": True,
                             "model": self.serve.engine.config.name})
        elif path == "/metrics":
            self._metrics_response(metrics.get_registry(), parsed.query)
        elif path == "/stats":
            self._json(200, self.serve.engine.stats())
        else:
            self._json(404, {"type": "error", "message": "not found"})

    def _post(self) -> None:
        if urlparse(self.path).path != "/generate":
            self._json(404, {"type": "error", "message": "not found"})
            return
        n = int(self.headers.get("Content-Length") or 0)
        try:
            d = json.loads(self.rfile.read(n) if n else b"{}")
            if not isinstance(d, dict):
                raise ValueError("body must be a JSON object")
            tokens = d.get("tokens")
            if (not isinstance(tokens, list)
                    or not all(isinstance(t, int) for t in tokens)):
                raise ValueError("'tokens' must be a list of token ids")
            eos_id = d.get("eos_id")
            sid = d.get("session_id")
            if sid is not None and not isinstance(sid, str):
                # The router's affinity key rides along to the replica;
                # a malformed one is the caller's fault, not ours to
                # coerce (the engine itself never reads it).
                raise ValueError("'session_id' must be a string")
            opts = {
                "max_new_tokens": int(d.get("max_new_tokens", 16)),
                "temperature": float(d.get("temperature", 0.0)),
                "top_k": int(d.get("top_k", 0)),
                "top_p": float(d.get("top_p", 1.0)),
                "eos_id": int(eos_id) if eos_id is not None else None,
                "seed": int(d.get("seed", 0)),
            }
        except (ValueError, TypeError) as e:
            # TypeError too: float(None)/int([]) from a malformed body is
            # the caller's fault, not a handler crash.
            self._json(400, {"type": "error", "message": str(e)})
            return
        # The trace-context header: the router (or any upstream) minted
        # the id; this replica propagates it through the engine so its
        # whole lifecycle is recorded under the fleet-wide id. Absent
        # OR invalid header (hostile/binary bytes must not ride into
        # span fields) = direct traffic; the engine falls back to the
        # local request id.
        trace_id = self.headers.get(TRACE_HEADER)
        if not valid_trace_id(trace_id):
            trace_id = None
        try:
            done = self.serve.generate(tokens, trace_id=trace_id, **opts)
        except ValueError as e:  # engine validation: caller's fault
            self._json(400, {"type": "error", "message": str(e)})
            return
        except TimeoutError as e:
            # Per-request timeout, NOT engine death: 504 so the router
            # can tell "slow" from "dead" — a 503 here would eject this
            # replica and re-run the same long generation on its peers
            # (serve/router.py's eject-storm contract).
            self._json(504, {"type": "error", "message": str(e)})
            return
        except RuntimeError as e:  # engine-loop death: liveness event
            self._json(503, {"type": "error", "message": str(e)})
            return
        body: Dict[str, Any] = {
            "request_id": done.request_id,
            "tokens": done.tokens,
            "prompt_len": done.prompt_len,
            "finish_reason": done.finish_reason,
            "ttft_s": done.ttft,
            "tpot_s": done.tpot,
            "preemptions": done.preemptions,
        }
        if done.trace_id is not None:
            # The per-phase latency attribution rides the response: the
            # four phases sum to e2e_s exactly (the evidence-gate pin).
            body["trace_id"] = done.trace_id
            body["phases"] = done.phases
            body["e2e_s"] = done.finished_at - done.submitted_at
            if done.spec is not None:
                body["spec"] = done.spec
        self._json(200, body)


class ServeHTTPServer:
    """Embeddable serving endpoint:
    ``with ServeHTTPServer(engine) as url: ...`` in tests;
    ``serve_forever`` under ``tk8s serve``."""

    def __init__(self, engine: ServeEngine, host: str = "127.0.0.1",
                 port: int = 0, request_timeout_s: float = 120.0,
                 tracing: bool = True):
        self.engine = engine
        if tracing and engine.flight is None:
            # Served engines trace by default (a bounded in-memory
            # recorder; JSONL export only when the caller attached a
            # writer): /generate then always carries the phase
            # breakdown. tracing=False is the overhead-A/B off arm.
            engine.flight = FlightRecorder()
        self.request_timeout_s = request_timeout_s
        self._inbox: "queue.Queue[Tuple[Request, _Waiter]]" = queue.Queue()
        self._waiters: Dict[str, _Waiter] = {}
        self._id_lock = threading.Lock()
        self._next_id = 0
        self._stop = threading.Event()
        self._loop_error: Optional[str] = None
        handler = type("Handler", (_Handler,), {"serve": self})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self._engine_thread: Optional[threading.Thread] = None
        self._http_thread: Optional[threading.Thread] = None

    # ----------------------------------------------------- handler side
    def generate(self, tokens, **opts) -> FinishedRequest:
        with self._id_lock:
            rid = f"req-{self._next_id}"
            self._next_id += 1
        request = Request(request_id=rid, tokens=list(tokens), **{
            "max_new_tokens": opts.get("max_new_tokens", 16),
            "temperature": opts.get("temperature", 0.0),
            "top_k": opts.get("top_k", 0),
            "top_p": opts.get("top_p", 1.0),
            "eos_id": opts.get("eos_id"),
            "seed": opts.get("seed", 0),
            "trace_id": opts.get("trace_id"),
        })
        # Fail fast off-loop; the loop's own submit re-validates.
        self.engine.validate_request(request)
        if self._loop_error is not None:
            raise RuntimeError(f"engine loop died: {self._loop_error}")
        waiter = _Waiter()
        self._inbox.put((request, waiter))
        if not waiter.event.wait(self.request_timeout_s):
            if self._loop_error is not None:
                raise RuntimeError(
                    f"engine loop died: {self._loop_error}")
            raise TimeoutError(
                f"{rid}: no completion within {self.request_timeout_s}s")
        if waiter.fatal:
            raise RuntimeError(waiter.error or "engine loop died")
        if waiter.error is not None:
            raise ValueError(waiter.error)
        assert waiter.result is not None
        return waiter.result

    @property
    def loop_error(self) -> Optional[str]:
        """Why the engine loop died, or None while it is healthy."""
        return self._loop_error

    # ------------------------------------------------------- engine loop
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                # Drain the inbox; block briefly only when idle so
                # shutdown and new arrivals are both prompt.
                try:
                    item = self._inbox.get(
                        timeout=0.0 if self.engine.has_work else 0.05)
                except queue.Empty:
                    item = None
                while item is not None:
                    request, waiter = item
                    try:
                        self.engine.submit(request)
                        self._waiters[request.request_id] = waiter
                    except ValueError as e:
                        waiter.error = str(e)
                        waiter.event.set()
                    try:
                        item = self._inbox.get_nowait()
                    except queue.Empty:
                        item = None
                if self.engine.has_work:
                    for done in self.engine.step():
                        waiter = self._waiters.pop(done.request_id, None)
                        if waiter is not None:
                            waiter.result = done
                            waiter.event.set()
        except BaseException as e:  # loop death is a liveness event
            self._loop_error = f"{type(e).__name__}: {e}"
            # Recorded, not re-raised: /healthz now fails (the manifest's
            # liveness probe restarts the pod) and every blocked or
            # future client gets a 503 instead of a silent 200 zombie.
            self._fail_pending()
            # Flush the flight recorder LAST: the killed requests'
            # partial lifecycles survive as post-mortem traces (and as
            # already-flushed JSONL lines) even though their clients
            # only ever saw a 503.
            try:
                self.engine.abort_inflight(self._loop_error)
            except Exception:
                pass  # post-mortem best effort: the 503 path already ran

    def _fail_pending(self) -> None:
        """Release every blocked client as 503 instead of a 120s hang:
        in-flight waiters, then anything still queued in the inbox."""
        msg = f"engine loop died: {self._loop_error}"
        for waiter in list(self._waiters.values()):
            waiter.error, waiter.fatal = msg, True
            waiter.event.set()
        self._waiters.clear()
        while True:
            try:
                _, waiter = self._inbox.get_nowait()
            except queue.Empty:
                break
            waiter.error, waiter.fatal = msg, True
            waiter.event.set()

    # ---------------------------------------------------------- lifecycle
    @property
    def address(self) -> Tuple[str, int]:
        return self.httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "ServeHTTPServer":
        self._engine_thread = threading.Thread(target=self._loop,
                                               daemon=True)
        self._engine_thread.start()
        self._http_thread = threading.Thread(
            target=lambda: self.httpd.serve_forever(poll_interval=0.05),
            daemon=True)
        self._http_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        for t in (self._engine_thread, self._http_thread):
            if t is not None:
                t.join(timeout=5)

    def serve_forever(self) -> None:
        """Foreground mode (``tk8s serve``): engine loop on this thread's
        watch, HTTP on the caller's thread."""
        self._engine_thread = threading.Thread(target=self._loop,
                                               daemon=True)
        self._engine_thread.start()
        try:
            self.httpd.serve_forever()
        finally:
            self._stop.set()

    def __enter__(self) -> "ServeHTTPServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
