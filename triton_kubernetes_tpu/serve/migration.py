"""KV-page session migration: the serialized shipping protocol.

A live sequence's whole decoding state — its KV pages (at whatever
``--kv-dtype`` the pool runs), the anchored per-page quantization
scales, and the request/sampling state needed to keep emitting
bitwise-identical tokens — packs into ONE self-describing unit that
ships over the ordinary HTTP plane and unpacks into another replica's
pool **byte-exactly**.

Wire format (little-endian lengths, everything else raw)::

    MAGIC (8 bytes) | header_len (4 bytes, big-endian) |
    header JSON (utf-8) | page payload (raw array bytes) |
    sha256 digest (32 bytes, over everything before it)

Three properties the format is built around:

* **Byte-exact**: pages ship as ``tobytes()`` of the pool slice and
  land via ``frombuffer`` + scatter — no dequantize/requantize cycle,
  so a quantized pool migrates bitwise and *cheaper* (int8 ships ~4x,
  fp8 ~2x fewer bytes than an f32 pool would).
* **Self-describing**: the header carries dtype/shape for every
  array plus the model/pool identity, so the receiver can refuse an
  incompatible payload before touching its allocator.
* **Torn-transfer safe**: the trailing digest covers header and
  payload; a truncated body, a cut socket, or a single flipped bit
  raises :class:`TornPayloadError` and the destination pool is left
  untouched — the source still owns the session and keeps serving it.

The header also carries the session's prompt tokens, which is what
makes the destination-side *reference-count handshake* possible: pages
whose exact token content the destination's radix prefix cache already
indexes transfer by ``incref`` instead of by copy
(:meth:`~.engine.ServeEngine.import_session` decides per page).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

try:  # registers bfloat16/float8 dtype names with numpy (jax dep)
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover - jax always ships ml_dtypes
    pass

MAGIC = b"TK8SKV1\n"
VERSION = 1
DIGEST_BYTES = 32
#: Header keys every payload must carry (the compatibility gate reads
#: them before any bytes touch the destination pool).
HEADER_KEYS = ("version", "model", "kv_dtype", "block_size", "pages",
               "arrays", "request", "generated", "prefilled", "target",
               "preemptions")


class MigrationError(ValueError):
    """A payload this engine cannot import (wrong model, wrong pool
    geometry, malformed header) — typed so the HTTP plane can map it
    to a 4xx instead of a loop-killing crash."""


class TornPayloadError(MigrationError):
    """The digest rejected the payload: truncated body, cut transfer,
    or corrupted bytes. The destination pool was not touched."""


def _array_meta(arr: np.ndarray) -> Dict[str, Any]:
    return {"dtype": arr.dtype.name, "shape": list(arr.shape)}


def _digest(blob: bytes) -> bytes:
    return hashlib.sha256(blob).digest()


def pack_session(*, model: str, kv_dtype: str, block_size: int,
                 arrays: Dict[str, np.ndarray],
                 request: Dict[str, Any], generated: List[int],
                 prefilled: int, target: int, preemptions: int,
                 first_token_at: Optional[float] = None) -> bytes:
    """Serialize one session into the self-describing wire unit.

    ``arrays`` maps component name (``k``/``v`` and, for quantized
    pools, ``k_scale``/``v_scale``) to the gathered page slice —
    already host numpy, shaped ``[L, pages, ...]`` with the page axis
    in block-table order.
    """
    names = sorted(arrays)
    npages = {int(a.shape[1]) for a in arrays.values()}
    if len(npages) != 1:
        raise MigrationError(
            f"array page counts disagree: "
            f"{ {n: arrays[n].shape[1] for n in names} }")
    header = {
        "version": VERSION,
        "model": model,
        "kv_dtype": kv_dtype,
        "block_size": int(block_size),
        "pages": npages.pop(),
        "arrays": {n: _array_meta(arrays[n]) for n in names},
        "request": dict(request),
        "generated": [int(t) for t in generated],
        "prefilled": int(prefilled),
        "target": int(target),
        "preemptions": int(preemptions),
    }
    if first_token_at is not None:
        header["first_token_at"] = float(first_token_at)
    hdr = json.dumps(header, sort_keys=True,
                     separators=(",", ":")).encode("utf-8")
    payload = b"".join(np.ascontiguousarray(arrays[n]).tobytes()
                       for n in names)
    blob = MAGIC + len(hdr).to_bytes(4, "big") + hdr + payload
    return blob + _digest(blob)


class SessionPayload:
    """A verified, decoded wire unit: the header dict plus one numpy
    array per shipped component (zero-copy views over the blob)."""

    def __init__(self, header: Dict[str, Any],
                 arrays: Dict[str, np.ndarray], nbytes: int):
        self.header = header
        self.arrays = arrays
        self.nbytes = nbytes

    @property
    def request(self) -> Dict[str, Any]:
        return self.header["request"]

    @property
    def pages(self) -> int:
        return int(self.header["pages"])


def unpack_session(blob: bytes) -> SessionPayload:
    """Verify the digest and decode the unit. Any damage anywhere —
    truncation, a cut mid-payload, one flipped bit in header or pages
    — fails the sha256 check and raises :class:`TornPayloadError`
    before a single byte is interpreted."""
    if len(blob) < len(MAGIC) + 4 + DIGEST_BYTES:
        raise TornPayloadError(
            f"payload truncated: {len(blob)} bytes is shorter than the "
            f"fixed framing")
    body, digest = blob[:-DIGEST_BYTES], blob[-DIGEST_BYTES:]
    if _digest(body) != digest:
        raise TornPayloadError(
            "digest mismatch: payload was torn or corrupted in flight")
    if body[:len(MAGIC)] != MAGIC:
        raise MigrationError(
            f"bad magic {body[:len(MAGIC)]!r}: not a tk8s KV migration "
            f"payload")
    hdr_len = int.from_bytes(body[len(MAGIC):len(MAGIC) + 4], "big")
    hdr_start = len(MAGIC) + 4
    try:
        header = json.loads(body[hdr_start:hdr_start + hdr_len])
    except ValueError as e:
        raise MigrationError(f"unreadable header: {e}") from e
    missing = [k for k in HEADER_KEYS if k not in header]
    if missing:
        raise MigrationError(f"header missing keys {missing}")
    if header["version"] != VERSION:
        raise MigrationError(
            f"payload version {header['version']} != {VERSION}")
    arrays: Dict[str, np.ndarray] = {}
    offset = hdr_start + hdr_len
    for name in sorted(header["arrays"]):
        meta = header["arrays"][name]
        dtype = np.dtype(meta["dtype"])
        shape = tuple(int(d) for d in meta["shape"])
        count = int(np.prod(shape)) if shape else 1
        end = offset + count * dtype.itemsize
        if end > len(body):
            raise MigrationError(
                f"array {name!r} overruns the payload "
                f"({end} > {len(body)} bytes)")
        arrays[name] = np.frombuffer(
            body[offset:end], dtype=dtype).reshape(shape)
        offset = end
    if offset != len(body):
        raise MigrationError(
            f"{len(body) - offset} trailing bytes after the declared "
            f"arrays")
    return SessionPayload(header, arrays, len(blob))


def check_compatible(payload: SessionPayload, *, model: str,
                     kv_dtype: str, block_size: int,
                     expect_arrays: Tuple[str, ...]) -> None:
    """The import-side identity gate: pages are raw bytes, so they are
    only meaningful in a pool with the same model geometry, page size,
    and dtype. Refuse anything else before touching the allocator."""
    h = payload.header
    if h["model"] != model:
        raise MigrationError(
            f"payload is for model {h['model']!r}, this pool serves "
            f"{model!r}")
    if h["kv_dtype"] != kv_dtype:
        raise MigrationError(
            f"payload pool dtype {h['kv_dtype']!r} != local "
            f"{kv_dtype!r} — raw pages do not convert")
    if int(h["block_size"]) != block_size:
        raise MigrationError(
            f"payload block_size {h['block_size']} != local "
            f"{block_size}")
    if tuple(sorted(h["arrays"])) != tuple(sorted(expect_arrays)):
        raise MigrationError(
            f"payload components {sorted(h['arrays'])} != expected "
            f"{sorted(expect_arrays)}")


def corrupt(blob: bytes, *, mode: str, offset: int) -> bytes:
    """Damage a payload the way a torn transfer would — the chaos
    harness's fault model. ``truncate`` cuts the body at ``offset``
    (socket cut / dying source mid-stream); ``bitflip`` flips one bit
    at ``offset`` (a corrupted frame that kept its length)."""
    offset = max(0, min(offset, len(blob) - 1))
    if mode == "truncate":
        return blob[:offset]
    if mode == "bitflip":
        b = bytearray(blob)
        b[offset] ^= 0x01
        return bytes(b)
    raise ValueError(f"unknown corruption mode {mode!r}")
