"""Draft-free speculative decoding: the n-gram self-drafter and the
acceptance rule.

Decode is memory-bandwidth-bound: every step re-reads the whole KV pool
and the (possibly int8/fp8) weights to emit ONE token per sequence.
Speculation buys that bandwidth back by verifying several *proposed*
tokens per weight pass — ``models.paged.paged_verify_step`` scores
``spec_k + 1`` positions in one widened call, and the engine keeps the
longest prefix the model itself agrees with.

This module is the host-side half, kept as PURE FUNCTIONS (no engine
state, no jax) so the properties the whole scheme leans on are directly
testable (tests/test_speculation.py):

* :func:`draft_ngram` — prompt-lookup self-drafting: the proposal is
  the continuation of the most recent earlier occurrence of the
  sequence's own current suffix (its prompt + generated tokens). No
  second model, no extra weights, no new numerics — every proposed
  token is literally a token from the sequence's own history, which is
  also why a draft can never propose an out-of-vocab id. Repetitive
  text (code, templated prose, greedy decode loops) drafts at high
  accept rates; on text with no self-similarity it proposes nothing
  and the engine degrades to plain decode for that step.
* :func:`longest_agreeing_prefix` — greedy acceptance: keep draft
  tokens while the model's own (seed, position)-keyed sample at each
  position equals the draft, stop at the first disagreement. Because
  acceptance re-samples every position with the SAME keyed sampler the
  non-speculative engine uses, accepted output is *bitwise* the
  non-speculative output — for greedy AND for seeded sampling — not an
  approximation of it.

Determinism contract (the churn-test axis): both functions are pure
and depend only on their arguments, so a given request history always
drafts identically, whatever the batch around it is doing.
"""

from __future__ import annotations

from typing import List, Sequence

# Longest suffix the drafter tries to match before shorter ones. Longer
# matches are rarer but much more specific (fewer false continuations);
# 3 is the prompt-lookup literature's usual sweet spot and what the
# spec_decode_evidence A/B measured best on the repetition-heavy trace.
MAX_NGRAM = 3
# Shortest suffix worth matching: 1-token matches fire constantly on
# common tokens and mispredict, so the floor is their cutoff.
MIN_NGRAM = 1
# How far back the suffix search looks. The drafter runs on the
# scheduler thread once per decoding sequence per tick, and its WORST
# case is exactly the traffic where it finds nothing (non-self-similar
# text scans everything, every tick) — so the scan is bounded: with
# 32k-token prompts (what chunked prefill exists for) an unbounded
# match would put ~max_batch * 32k Python comparisons on every tick's
# host path while producing zero drafts. Recency also correlates with
# relevance: the continuation of a *recent* repeat predicts better
# than one 30k tokens ago.
MAX_SCAN = 2048


def draft_ngram(history: Sequence[int], k: int, *,
                max_ngram: int = MAX_NGRAM,
                min_ngram: int = MIN_NGRAM,
                max_scan: int = MAX_SCAN) -> List[int]:
    """Propose up to ``k`` next tokens by suffix match over the last
    ``max_scan`` tokens of ``history`` (the sequence's own prompt +
    generated tokens).

    Longest-match-first: for ``n`` from ``max_ngram`` down to
    ``min_ngram``, find the MOST RECENT earlier occurrence of the
    final ``n`` tokens and propose the tokens that followed it.
    Returns ``[]`` when nothing matches (or ``k <= 0``) — the engine
    then runs that step as plain decode.

    Pure and deterministic: same history, same proposal, independent
    of batch composition (the solo-run parity contract). Proposals are
    copies of history slices, so they cannot contain an id the
    validated request did not already carry.
    """
    if k <= 0:
        return []
    h = list(history)[-max_scan:]
    n_hist = len(h)
    for n in range(min(max_ngram, n_hist - 1), min_ngram - 1, -1):
        suffix = h[n_hist - n:]
        # Most recent occurrence whose continuation exists (ends
        # strictly before the history's end). Compare elementwise
        # first-token-out so the common miss costs one comparison, not
        # an n-length slice allocation per candidate position.
        first = suffix[0]
        for p in range(n_hist - n - 1, -1, -1):
            if h[p] == first and h[p:p + n] == suffix:
                return h[p + n:p + n + k]
    return []


def longest_agreeing_prefix(draft: Sequence[int],
                            sampled: Sequence[int]) -> int:
    """Number of leading draft tokens the model agreed with: the count
    of positions ``j`` (from 0) where ``sampled[j] == draft[j]`` before
    the first mismatch.

    ``sampled[j]`` is the model's own token for that position, drawn
    from the verify logits with the request's (seed, position) key —
    so "agrees" means "the non-speculative engine would have emitted
    exactly this", which is what makes acceptance exact rather than
    approximate. The engine emits the accepted prefix plus
    ``sampled[a]`` (the first disagreeing — or bonus — model token):
    every verify therefore nets at least one token, so speculation can
    slow a step down but never stall one.
    """
    a = 0
    for d, s in zip(draft, sampled):
        if d != s:
            break
        a += 1
    return a
